"""Flight recorder tests (ISSUE 5): span tracing, histograms, pulse,
gauges, the off-path fast path, and the bottleneck doctor.

Tier 1 (no devices). The recorder under test is the process-global
``telemetry.RECORDER`` wherever the wiring is exercised end-to-end
(options -> dataset -> spans), and private SpanRecorder instances where
the contract is about the data structure itself.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tpu_tfrecord import telemetry
from tpu_tfrecord.metrics import METRICS, Metrics, timed
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.telemetry import (
    Histogram,
    Pulse,
    SpanRecorder,
    boundness_verdict,
    prometheus_text,
)

SCHEMA = StructType(
    [
        StructField("id", LongType(), nullable=False),
        StructField("s", StringType()),
    ]
)


def write_dataset(path, n_shards=3, rows_per_shard=50):
    import tpu_tfrecord.io as tfio

    for s in range(n_shards):
        tfio.write(
            [[i, f"s{i}"] for i in range(s * rows_per_shard, (s + 1) * rows_per_shard)],
            SCHEMA,
            str(path),
            mode="append" if s else "overwrite",
        )
    return str(path)


@pytest.fixture(autouse=True)
def _clean_global_recorder():
    """Every test starts and ends with the global recorder off and empty —
    the recorder is process-global, so leakage between tests would make
    span assertions order-dependent."""
    telemetry.disable()
    telemetry.RECORDER.clear()
    METRICS.reset()
    yield
    telemetry.disable()
    telemetry.RECORDER.clear()
    METRICS.reset()


class TestSpanRecorder:
    def test_span_records_name_duration_tid(self):
        rec = SpanRecorder(enabled=True)
        with rec.span("outer", shard="a"):
            time.sleep(0.002)
        (span,) = rec.spans()
        name, t0, dur, tid, attrs, ph = span
        assert name == "outer"
        assert ph == "X"
        assert dur >= 2_000_000  # >= 2ms in ns
        assert tid == threading.get_ident()
        assert attrs == {"shard": "a"}

    def test_span_nesting(self):
        rec = SpanRecorder(enabled=True)
        with rec.span("outer"):
            with rec.span("inner"):
                time.sleep(0.001)
        # inner exits (and records) first; outer encloses it
        inner, outer = rec.spans()
        assert inner[0] == "inner" and outer[0] == "outer"
        assert outer[1] <= inner[1]  # outer began first
        assert outer[1] + outer[2] >= inner[1] + inner[2]  # and ended last

    def test_set_attrs_mid_span(self):
        rec = SpanRecorder(enabled=True)
        with rec.span("decode", shard="x") as sp:
            sp.set(rows=128)
        (span,) = rec.spans()
        assert span[4] == {"shard": "x", "rows": 128}

    def test_exception_marks_failed(self):
        rec = SpanRecorder(enabled=True)
        with pytest.raises(ValueError):
            with rec.span("decode"):
                raise ValueError("boom")
        (span,) = rec.spans()
        assert span[4] == {"failed": 1}

    def test_instant_event(self):
        rec = SpanRecorder(enabled=True)
        rec.instant("read.stall", path="p")
        (ev,) = rec.spans()
        assert ev[0] == "read.stall" and ev[5] == "i" and ev[2] == 0

    def test_thread_interleaving(self):
        rec = SpanRecorder(enabled=True, capacity=4096)
        n_threads, per_thread = 8, 50

        def work(k):
            for i in range(per_thread):
                with rec.span(f"t{k}", i=i):
                    pass

        threads = [
            threading.Thread(target=work, args=(k,)) for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = rec.spans()
        assert len(spans) == n_threads * per_thread
        assert rec.dropped == 0
        # every thread's spans all present, tids distinct per thread name
        by_name = {}
        for name, _t0, _dur, tid, _attrs, _ph in spans:
            by_name.setdefault(name, set()).add(tid)
        assert len(by_name) == n_threads
        assert all(len(tids) == 1 for tids in by_name.values())

    def test_ring_buffer_bounded(self):
        rec = SpanRecorder(enabled=True, capacity=64)
        for i in range(300):
            with rec.span("s", i=i):
                pass
        assert len(rec) == 64
        assert rec.dropped == 236
        spans = rec.spans()
        assert len(spans) == 64
        # the RETAINED spans are the most recent ones, oldest first
        assert [s[4]["i"] for s in spans] == list(range(236, 300))

    def test_clear(self):
        rec = SpanRecorder(enabled=True, capacity=8)
        for _ in range(20):
            rec.instant("x")
        rec.clear()
        assert len(rec) == 0 and rec.dropped == 0 and rec.spans() == []


class TestOffFastPath:
    def test_disabled_records_nothing_and_takes_no_lock(self):
        telemetry.disable()

        class TripLock:
            def __enter__(self):
                raise AssertionError("recorder lock taken on the off path")

            def __exit__(self, *exc):
                return None

        real = telemetry.RECORDER._lock
        telemetry.RECORDER._lock = TripLock()
        try:
            for i in range(100):
                with telemetry.span("decode", shard="x") as sp:
                    sp.set(rows=i)
                telemetry.instant("read.stall")
                telemetry.record_span("batch", 0, 10)
        finally:
            telemetry.RECORDER._lock = real
        assert len(telemetry.RECORDER) == 0

    def test_disabled_span_is_shared_noop(self):
        telemetry.disable()
        a = telemetry.span("x")
        b = telemetry.span("y", k=1)
        assert a is b  # no per-call allocation when off


class TestHistogram:
    def test_quantiles_vs_reference_sort(self):
        rng = np.random.default_rng(7)
        values = rng.lognormal(mean=-7.0, sigma=1.5, size=20_000)
        h = Histogram()
        for v in values:
            h.observe(float(v))
        for q in (0.5, 0.9, 0.99):
            ref = float(np.quantile(values, q))
            est = h.quantile(q)
            # log-bucket growth 2**0.25 bounds the relative error at
            # sqrt(2**0.25)-1 ~ 9.1%; allow a little slack for the
            # rank-vs-interpolation difference at the tail
            assert abs(est - ref) / ref < 0.12, (q, est, ref)

    def test_single_value_clamps_exact(self):
        h = Histogram()
        for _ in range(10):
            h.observe(0.003)
        assert h.quantile(0.5) == pytest.approx(0.003)
        assert h.quantile(0.99) == pytest.approx(0.003)

    def test_empty_and_tiny_values(self):
        h = Histogram()
        assert h.quantile(0.5) is None
        assert h.quantiles() == {}
        h.observe(0.0)  # below the floor: bucket 0, no crash
        assert h.count == 1
        assert h.quantile(0.5) == pytest.approx(0.0)  # clamped to observed max

    def test_quantiles_dict_shape(self):
        h = Histogram()
        h.observe(0.001)
        h.observe(0.002)
        q = h.quantiles()
        assert set(q) == {"p50_s", "p90_s", "p99_s", "count", "mean_s"}
        assert q["count"] == 2
        assert q["mean_s"] == pytest.approx(0.0015)


class TestMetricsIntegration:
    def test_timed_feeds_histogram(self):
        m = Metrics()
        with timed("decode", m):
            time.sleep(0.001)
        snap = m.snapshot("decode")["decode"]
        assert snap["hist_count"] == 1
        assert snap["p50_s"] >= 0.0005
        # the legacy keys are untouched
        for key in ("records_per_sec", "bytes_per_sec", "records", "bytes",
                    "batches", "seconds"):
            assert key in snap

    def test_timed_failure_records_error_counter(self):
        # the PR 5 bugfix: the old __exit__(*exc) swallowed the exception
        # info, so failed stages were indistinguishable from healthy ones
        m = Metrics()
        with pytest.raises(RuntimeError):
            with timed("decode", m):
                time.sleep(0.001)
                raise RuntimeError("boom")
        assert m.counter("decode.errors") == 1
        assert m.stage("decode").seconds >= 0.0005  # elapsed still recorded
        # a healthy block does not bump the error counter
        with timed("decode", m):
            pass
        assert m.counter("decode.errors") == 1

    def test_gauge_first_class(self):
        m = Metrics()
        m.gauge("prefetch.queue_depth", 3)
        m.gauge("prefetch.queue_depth", 1)  # last write wins
        assert m.gauge_value("prefetch.queue_depth") == 1.0
        assert m.gauge_value("missing") is None
        assert m.gauge_value("missing", 0.0) == 0.0
        # distinct snapshot shape; never rides the records field
        assert m.snapshot()["prefetch.queue_depth"] == {"gauge": 1.0}
        assert m.counter("prefetch.queue_depth") == 0

    def test_gauge_concurrency(self):
        m = Metrics()
        n_threads, per_thread = 8, 200

        def work(k):
            for i in range(per_thread):
                m.gauge("g", k * per_thread + i)
                m.count("c")

        threads = [
            threading.Thread(target=work, args=(k,)) for k in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # the counter is exact; the gauge holds SOME written value
        assert m.counter("c") == n_threads * per_thread
        assert 0 <= m.gauge_value("g") < n_threads * per_thread

    def test_reset_clears_gauges_and_hists(self):
        m = Metrics()
        m.gauge("g", 1)
        m.observe("s", 0.01)
        m.reset()
        assert m.gauges() == {} and m.quantiles() == {}

    def test_snapshot_prefix_filters_gauges_too(self):
        m = Metrics()
        m.gauge("write.occupancy", 0.5)
        m.gauge("prefetch.queue_depth", 2)
        m.add("write.io", records=1, seconds=0.1)
        snap = m.snapshot("write")
        assert set(snap) == {"write.occupancy", "write.io"}


class TestVerdict:
    def test_thresholds(self):
        assert boundness_verdict(None) == "unknown"
        assert boundness_verdict(0.9) == "consumer_bound"
        assert boundness_verdict(0.1) == "producer_bound"
        assert boundness_verdict(0.5) == "balanced"

    def test_from_metrics(self):
        m = Metrics()
        assert telemetry.verdict_from_metrics(m) == "unknown"
        m.gauge(telemetry.OCCUPANCY_GAUGE, 0.95)
        assert telemetry.verdict_from_metrics(m) == "consumer_bound"


class TestChromeTrace:
    def test_schema_validity(self, tmp_path):
        rec = SpanRecorder(enabled=True)
        with rec.span("decode", shard="part-0"):
            pass
        rec.instant("read.stall", path="part-1")
        doc = rec.to_chrome_trace()
        # round-trips through JSON (Perfetto loads a file, not a dict)
        doc = json.loads(json.dumps(doc))
        assert doc["displayTimeUnit"] == "ms"
        # metadata records lead: a process_name track label (fleet merges
        # rely on it) and a thread_name for each live recorded thread
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert meta[0]["name"] == "process_name"
        assert any(
            e["name"] == "thread_name" and e["args"]["name"] == "MainThread"
            for e in meta
        )
        events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        assert len(events) == 2
        for ev in events:
            for key in ("name", "cat", "ph", "ts", "pid", "tid"):
                assert key in ev, ev
            assert ev["ph"] in ("X", "i")
            if ev["ph"] == "X":
                assert "dur" in ev and ev["dur"] >= 0
            else:
                assert ev["s"] == "t"
        x = [e for e in events if e["ph"] == "X"][0]
        assert x["args"] == {"shard": "part-0"}
        path = tmp_path / "trace.json"
        rec.save_chrome_trace(str(path))
        assert json.load(open(path))["traceEvents"]


class TestPulse:
    def test_pulse_line_round_trip(self):
        m = Metrics()
        m.add("decode", records=100, nbytes=5000, seconds=0.5, latency=0.5)
        m.count("read.retries", 2)
        m.gauge(telemetry.OCCUPANCY_GAUGE, 0.9)
        lines = []
        clock = iter([0.0, 2.0]).__next__
        p = Pulse(1.0, metrics=m, emit=lines.append, clock=clock)
        payload = p.tick()
        assert lines == [payload]
        # the pulse line is one machine-parseable JSON object
        rt = json.loads(json.dumps(payload))
        assert rt["event"] == "pulse"
        assert rt["interval_s"] == pytest.approx(2.0)
        assert rt["stages"]["decode"]["records_per_sec"] == pytest.approx(50.0)
        assert rt["stages"]["decode"]["bytes_per_sec"] == pytest.approx(2500.0)
        assert rt["counters"]["read.retries"] == 2
        assert rt["gauges"][telemetry.OCCUPANCY_GAUGE] == pytest.approx(0.9)
        assert rt["quantiles"]["decode"]["count"] == 1
        assert rt["verdict"] == "consumer_bound"

    def test_pulse_reports_interval_deltas(self):
        m = Metrics()
        clock = iter([0.0, 1.0, 2.0]).__next__
        p = Pulse(1.0, metrics=m, emit=lambda _d: None, clock=clock)
        m.add("decode", records=100, seconds=0.1)
        first = p.tick()
        assert first["stages"]["decode"]["records_per_sec"] == pytest.approx(100.0)
        # no new work in the second interval: throughput drops to zero
        # (a stalled pipeline PULSES as stalled, instead of averaging)
        second = p.tick()
        assert second["stages"]["decode"]["records_per_sec"] == 0.0
        assert second["stages"]["decode"]["records"] == 100

    def test_pulse_thread_and_default_log_emit(self, caplog):
        import logging

        m = Metrics()
        m.add("decode", records=10, seconds=0.01)
        p = Pulse(0.02, metrics=m)
        with caplog.at_level(logging.INFO, logger="tpu_tfrecord"):
            p.start()
            time.sleep(0.08)
            p.stop()
        pulse_lines = [
            r.getMessage() for r in caplog.records if "tfrecord.pulse" in r.getMessage()
        ]
        assert pulse_lines
        payload = json.loads(pulse_lines[0].split("tfrecord.pulse ", 1)[1])
        assert payload["event"] == "pulse"

    def test_stop_idempotent(self):
        lines = []
        p = Pulse(10.0, metrics=Metrics(), emit=lines.append).start()
        p.stop()
        n = len(lines)
        p.stop()  # the GC finalizer path: no second final tick
        assert len(lines) == n


class TestOptions:
    def test_defaults(self):
        opts = TFRecordOptions.from_map({})
        assert opts.trace == "off"
        assert opts.pulse_interval_s is None
        assert opts.telemetry_port is None

    def test_parsing(self):
        opts = TFRecordOptions.from_map(
            trace="on", pulse_interval_s="2.5", telemetry_port="9095"
        )
        assert opts.trace == "on"
        assert opts.pulse_interval_s == 2.5
        assert opts.telemetry_port == 9095
        camel = TFRecordOptions.from_map(
            {"pulseIntervalS": 1, "telemetryPort": 0}
        )
        assert camel.pulse_interval_s == 1.0 and camel.telemetry_port == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="trace"):
            TFRecordOptions.from_map(trace="maybe")
        with pytest.raises(ValueError, match="pulse_interval_s"):
            TFRecordOptions.from_map(pulse_interval_s=0)
        with pytest.raises(ValueError, match="telemetry_port"):
            TFRecordOptions.from_map(telemetry_port=70000)


class TestEndToEnd:
    def test_read_with_trace_on_records_pipeline_spans(self, sandbox):
        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds")
        ds = TFRecordDataset(
            data, batch_size=16, schema=SCHEMA, drop_remainder=False, trace="on"
        )
        assert telemetry.RECORDER.enabled  # the option enabled the recorder
        with ds.batches() as it:
            rows = sum(b.num_rows for b in it)
        assert rows == 150
        spans = telemetry.RECORDER.spans()
        names = {s[0] for s in spans}
        assert {"open", "decode", "batch"} <= names
        decode_shards = {
            (s[4] or {}).get("shard") for s in spans if s[0] == "decode"
        }
        assert len(decode_shards) == 3  # every shard attributed
        # and the export is valid trace-event JSON containing decode spans
        doc = json.loads(json.dumps(telemetry.RECORDER.to_chrome_trace()))
        assert any(e["name"] == "decode" for e in doc["traceEvents"])

    def test_trace_off_records_nothing(self, sandbox):
        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds")
        ds = TFRecordDataset(
            data, batch_size=16, schema=SCHEMA, drop_remainder=False
        )
        with ds.batches() as it:
            for _ in it:
                pass
        assert len(telemetry.RECORDER) == 0
        # but gauges and histograms (always-on, batch-granularity) flowed
        assert METRICS.gauge_value("prefetch.queue_depth") is not None
        assert "decode" in METRICS.quantiles()

    def test_writer_trace_on_records_write_spans(self, sandbox):
        import tpu_tfrecord.io as tfio

        tfio.write(
            [[i, f"s{i}"] for i in range(200)],
            SCHEMA,
            str(sandbox / "out"),
            mode="overwrite",
            options=TFRecordOptions.from_map(
                trace="on", write_workers=2, num_shards=2
            ),
        )
        names = {s[0] for s in telemetry.RECORDER.spans()}
        assert {"write.encode", "write.io", "write.commit"} <= names
        assert METRICS.counter("write.commit.errors") == 0
        assert "write.commit" in METRICS.quantiles()

    def test_cold_cache_epoch_reports_no_errors(self, sandbox):
        # a routine cold miss (absent entry) is NOT an error: a healthy
        # first epoch with cache="auto" must leave every *.errors counter
        # at zero, or dashboards alerting on error rates fire on every
        # fresh cache
        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds", n_shards=2)
        for _epoch in range(2):
            ds = TFRecordDataset(
                data,
                batch_size=16,
                schema=SCHEMA,
                drop_remainder=False,
                cache="auto",
                cache_dir=str(sandbox / "cache"),
            )
            with ds.batches() as it:
                for _ in it:
                    pass
        errors = {
            name: totals[0]
            for name, totals in METRICS.raw_totals().items()
            if name.endswith(".errors") and totals[0]
        }
        assert errors == {}, errors
        assert METRICS.counter("cache.hits") > 0  # epoch 2 actually served
        assert "cache.open" in METRICS.quantiles()  # latency still recorded

    def test_pulse_option_emits_during_iteration(self, sandbox, caplog):
        import logging

        from tpu_tfrecord.io.dataset import TFRecordDataset

        data = write_dataset(sandbox / "ds")
        ds = TFRecordDataset(
            data,
            batch_size=16,
            schema=SCHEMA,
            drop_remainder=False,
            pulse_interval_s=0.02,
        )
        with caplog.at_level(logging.INFO, logger="tpu_tfrecord"):
            with ds.batches() as it:
                for _ in it:
                    time.sleep(0.01)
        pulse_lines = [
            r.getMessage() for r in caplog.records if "tfrecord.pulse" in r.getMessage()
        ]
        assert pulse_lines  # at least the final tick
        payload = json.loads(pulse_lines[-1].split("tfrecord.pulse ", 1)[1])
        assert payload["verdict"] in (
            "producer_bound", "consumer_bound", "balanced", "unknown"
        )
        assert "prefetch.queue_depth" in payload["gauges"]


class TestPrometheus:
    def test_text_format(self):
        m = Metrics()
        m.add("decode", records=10, nbytes=100, seconds=0.5, latency=0.5)
        m.gauge("prefetch.queue_depth", 2)
        text = prometheus_text(m)
        assert 'tfrecord_stage_records_total{stage="decode"} 10' in text
        assert 'tfrecord_gauge{name="prefetch.queue_depth"} 2' in text
        assert 'tfrecord_latency_seconds{stage="decode",quantile="0.99"}' in text
        assert 'tfrecord_latency_seconds_count{stage="decode"} 1' in text

    def test_families_are_contiguous_and_parse(self):
        # the exposition format requires one contiguous block per metric
        # family; interleaving per stage makes strict parsers reject the
        # page as duplicate families (pinned with the official parser)
        m = Metrics()
        m.add("decode", records=10, nbytes=100, seconds=0.5, latency=0.5)
        m.add("read.open", records=3, seconds=0.1, latency=0.1)
        m.gauge("prefetch.queue_depth", 2)
        parser = pytest.importorskip("prometheus_client.parser")
        families = list(
            parser.text_string_to_metric_families(prometheus_text(m))
        )
        names = [f.name for f in families]
        assert len(names) == len(set(names)), names  # no duplicate families
        # the parser strips the counter _total suffix into the family name
        recs = {f.name: f for f in families}["tfrecord_stage_records"]
        by_stage = {s.labels["stage"]: s.value for s in recs.samples}
        assert by_stage == {"decode": 10.0, "read.open": 3.0}
        lat = {f.name: f for f in families}["tfrecord_latency_seconds"]
        assert lat.type == "summary"
        assert any(s.name.endswith("_count") for s in lat.samples)

    def test_http_endpoint(self):
        m = Metrics()
        m.add("decode", records=7, seconds=0.1)
        server = telemetry.ensure_exporter(0, metrics=m)
        try:
            # the public way to learn the ephemeral port: keyed by the
            # REQUESTED port (0), not the one the OS picked
            host, port = telemetry.exporter_address(0)
            assert port == server.server_address[1]
            # idempotent per port key
            assert telemetry.ensure_exporter(0, metrics=m) is server
            body = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5
            ).read().decode()
            assert 'tfrecord_stage_records_total{stage="decode"} 7' in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://{host}:{port}/other", timeout=5
                )
        finally:
            telemetry.shutdown_exporter(0)
        assert telemetry.exporter_address(0) is None

    def test_taken_port_never_raises(self):
        # an observability knob must not take the pipeline down: binding a
        # port another process holds warns and returns None
        import socket

        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        sock.listen(1)
        taken = sock.getsockname()[1]
        try:
            assert telemetry.ensure_exporter(taken, metrics=Metrics()) is None
            assert telemetry.exporter_address(taken) is None
        finally:
            sock.close()


class TestDoctorReport:
    def test_report_subcommand(self, sandbox):
        data = write_dataset(sandbox / "ds")
        trace_out = str(sandbox / "trace.json")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tools",
                    "tfrecord_doctor.py",
                ),
                "report",
                data,
                "--batches", "6",
                "--batch-size", "16",
                "--trace-out", trace_out,
            ],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        stages = [l for l in lines if l["event"] == "stage"]
        shards = [l for l in lines if l["event"] == "shard"]
        (report,) = [l for l in lines if l["event"] == "report"]
        assert any(l["stage"] == "decode" and "p50_ms" in l for l in stages)
        assert shards and all("seconds" in s for s in shards)
        assert report["verdict"] in (
            "producer_bound", "consumer_bound", "balanced", "unknown"
        )
        assert report["rows"] == 96
        assert report["straggler_p99_p50"] >= 1.0
        assert report["slowest_shard"]
        doc = json.load(open(trace_out))
        assert any(e["name"] == "decode" for e in doc["traceEvents"])

    def test_report_unreadable_dataset_exits_2(self, sandbox):
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tools",
                    "tfrecord_doctor.py",
                ),
                "report",
                str(sandbox / "nope"),
            ],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 2, (proc.stdout, proc.stderr)
        lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        assert lines and lines[0]["event"] == "error"
