"""Full GSPMD mesh (PR 19): dp×fsdp×pp(+EP) weight sharding and
segment-masked bin packing. Pins: every fsdp composition reproduces the
pure-dp loss trajectory on the same params and data; per-device param
bytes shrink ~linearly in the fsdp axis; checkpoints move freely between
mesh layouts through `AsyncCheckpointer`; packed rows score exactly like
each document alone (the per-document oracle); and the TokenPacker bin
modes checkpoint/resume byte-identically."""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tools.graftlint import hlo_contracts
from tpu_tfrecord.checkpoint import AsyncCheckpointer
from tpu_tfrecord.models import lm
from tpu_tfrecord.tpu import TokenPacker, create_mesh

CFG = lm.LMConfig(vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16)
CFG4 = lm.LMConfig(
    vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16, n_micro=4
)
_PLACEMENT_AXES = ("pipe_axis", "expert_axis", "fsdp_axis")


def batch(cfg=CFG, b=8, seed=0):
    return jnp.asarray(lm.make_synthetic_tokens(cfg, b, seed=seed))


def place(params, mesh, **axes):
    return jax.device_put(params, lm.param_shardings(mesh, params, **axes))


def trajectory(cfg, mesh=None, steps=6, **axes):
    params = lm.init_params(jax.random.key(0), cfg)
    if mesh is not None:
        pl = {k: axes[k] for k in _PLACEMENT_AXES if axes.get(k)}
        params = place(params, mesh, **pl)
    tx = optax.adam(3e-3)
    opt = tx.init(params)
    step = jax.jit(
        functools.partial(lm.train_step, cfg=cfg, tx=tx, mesh=mesh, **axes)
    )
    losses = []
    for i in range(steps):
        params, opt, loss = step(params, opt, batch(cfg, b=8, seed=100 + i))
        losses.append(float(loss))
    return losses


class TestFSDPTrajectory:
    """Weight sharding must be a LAYOUT choice, not a numerics choice:
    same params + same data => the pure-dp loss trajectory."""

    def test_dp_fsdp_matches_pure_dp(self):
        ref = trajectory(CFG)
        mesh = create_mesh({"data": 2, "fsdp": 4})
        got = trajectory(CFG, mesh=mesh, data_axis="data", fsdp_axis="fsdp")
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_dp_fsdp_pp_matches_pure_dp(self):
        """The full 3-axis mesh: params at rest P(pipe, fsdp, ...), the
        pipeline's own param_spec boundary reshard does the per-step
        gather — zero pipeline.py changes, same trajectory."""
        ref = trajectory(CFG4)
        mesh = create_mesh({"pipe": 2, "data": 2, "fsdp": 2})
        got = trajectory(
            CFG4, mesh=mesh, data_axis="data", pipe_axis="pipe",
            fsdp_axis="fsdp",
        )
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_dp_fsdp_ep_matches_dp_ep(self):
        """fsdp composed against the expert axis: the moe shard_map's
        in_spec reshard gathers ONLY the fsdp dim, so adding fsdp to
        dp×ep must not move the trajectory at all. (EP itself diverges
        from pure dp by routing/capacity discreteness — pre-existing —
        so the tight pin is against dp×ep on the SAME mesh, with a
        coarse sanity bound against pure dp.)"""
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=2, max_len=16,
            moe_experts=4, moe_top_k=2, moe_capacity_factor=4.0,
        )
        mesh = create_mesh({"data": 2, "fsdp": 2, "expert": 2})
        ref_ep = trajectory(
            cfg, mesh=mesh, data_axis="data", expert_axis="expert"
        )
        got = trajectory(
            cfg, mesh=mesh, data_axis="data", expert_axis="expert",
            fsdp_axis="fsdp",
        )
        np.testing.assert_allclose(got, ref_ep, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(got, trajectory(cfg), atol=0.05)


class TestFSDPMemory:
    """The point of fsdp: per-device at-rest bytes (params + opt state,
    the compiled argument bytes) shrink ~linearly in the fsdp axis."""

    def _argument_bytes(self, mesh_axes, fsdp_axis):
        cfg = lm.LMConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=4, max_len=16
        )
        mesh = create_mesh(mesh_axes)
        params = lm.init_params(jax.random.key(0), cfg)
        params = place(params, mesh, fsdp_axis=fsdp_axis)
        tx = optax.adam(3e-3)
        opt = tx.init(params)
        toks = jax.device_put(
            batch(cfg), NamedSharding(mesh, P("data", None))
        )
        step = jax.jit(
            functools.partial(
                lm.train_step, cfg=cfg, tx=tx, mesh=mesh,
                data_axis="data", fsdp_axis=fsdp_axis,
            )
        )
        mem = step.lower(params, opt, toks).compile().memory_analysis()
        return mem.argument_size_in_bytes

    def test_param_bytes_shrink_linearly_in_fsdp(self):
        b1 = self._argument_bytes({"data": 8}, None)
        b2 = self._argument_bytes({"data": 4, "fsdp": 2}, "fsdp")
        b4 = self._argument_bytes({"data": 2, "fsdp": 4}, "fsdp")
        # ~linear: each doubling of fsdp roughly halves the at-rest
        # bytes (0.65 leaves room for the unsharded scalars/biases and
        # the replicated token batch)
        assert b2 < 0.65 * b1, (b1, b2)
        assert b4 < 0.65 * b2, (b2, b4)


class TestFSDPContracts:
    def test_dp_fsdp_hlo_contract(self):
        hlo_contracts.verify("lm_train_step_fsdp")

    def test_dp_fsdp_pp_hlo_contract(self):
        hlo_contracts.verify("lm_train_step_fsdp_pp")


class TestCheckpointInterchange:
    """A checkpoint is layout-free: save under pure dp, restore under
    dp×fsdp or dp×fsdp×pp (and back) — params byte-identical through the
    round trip, trajectories indistinguishable at test scale."""

    def _host(self, tree):
        return jax.tree.map(np.asarray, jax.device_get(tree))

    def _place_state(self, mesh, params, opt, tx, **axes):
        p_sh = place(params, mesh, **axes)
        tmpl = tx.init(p_sh)  # zeros_like: inherits the sharded layout
        repl = NamedSharding(mesh, P())

        def put(t, v):
            sh = t.sharding if isinstance(t.sharding, NamedSharding) else repl
            return jax.device_put(jnp.asarray(v), sh)

        opt_sh = jax.tree.map(put, tmpl, opt)
        return p_sh, opt_sh

    def _run(self, cfg, params, opt, tx, mesh, steps, seed0, **axes):
        step = jax.jit(
            functools.partial(lm.train_step, cfg=cfg, tx=tx, mesh=mesh, **axes)
        )
        losses = []
        for i in range(steps):
            params, opt, loss = step(
                params, opt, batch(cfg, b=8, seed=seed0 + i)
            )
            losses.append(float(loss))
        return params, opt, losses

    def test_save_dp_restore_fsdp_and_fsdp_pp(self, tmp_path):
        cfg = CFG4
        tx = optax.adam(3e-3)
        ref = trajectory(cfg)
        params = lm.init_params(jax.random.key(0), cfg)
        opt = tx.init(params)
        params, opt, head = self._run(cfg, params, opt, tx, None, 3, 100)
        np.testing.assert_allclose(head, ref[:3], rtol=1e-6)
        saved_host = self._host({"params": params, "opt": opt})
        with AsyncCheckpointer(str(tmp_path / "dp")) as ckpt:
            ckpt.save(3, {"params": params, "opt": opt})
            ckpt.wait()
            fresh = lm.init_params(jax.random.key(1), cfg)
            step_no, state, _ = ckpt.restore(
                {"params": fresh, "opt": tx.init(fresh)}
            )
        assert step_no == 3
        jax.tree.map(
            np.testing.assert_array_equal, state, saved_host
        )  # save/restore is byte-identical
        for mesh_axes, axes in (
            ({"data": 2, "fsdp": 4},
             dict(data_axis="data", fsdp_axis="fsdp")),
            ({"pipe": 2, "data": 2, "fsdp": 2},
             dict(data_axis="data", pipe_axis="pipe", fsdp_axis="fsdp")),
        ):
            mesh = create_mesh(mesh_axes)
            pl = {k: axes[k] for k in _PLACEMENT_AXES if axes.get(k)}
            p_sh, opt_sh = self._place_state(
                mesh, state["params"], state["opt"], tx, **pl
            )
            _, _, tail = self._run(
                cfg, p_sh, opt_sh, tx, mesh, 3, 103, **axes
            )
            np.testing.assert_allclose(tail, ref[3:], rtol=1e-5, atol=1e-6)

    def test_save_fsdp_restore_dp(self, tmp_path):
        cfg = CFG4
        tx = optax.adam(3e-3)
        mesh = create_mesh({"data": 2, "fsdp": 4})
        axes = dict(data_axis="data", fsdp_axis="fsdp")
        full = trajectory(cfg, mesh=mesh, **axes)
        params = place(lm.init_params(jax.random.key(0), cfg), mesh,
                       fsdp_axis="fsdp")
        opt = tx.init(params)
        params, opt, head = self._run(cfg, params, opt, tx, mesh, 3, 100,
                                      **axes)
        np.testing.assert_allclose(head, full[:3], rtol=1e-6)
        saved_host = self._host({"params": params, "opt": opt})
        with AsyncCheckpointer(str(tmp_path / "fsdp")) as ckpt:
            ckpt.save(3, {"params": params, "opt": opt})
            ckpt.wait()
            fresh = lm.init_params(jax.random.key(1), cfg)
            _, state, _ = ckpt.restore(
                {"params": fresh, "opt": tx.init(fresh)}
            )
        jax.tree.map(np.testing.assert_array_equal, state, saved_host)
        _, _, tail = self._run(
            cfg, state["params"], state["opt"], tx, None, 3, 103
        )
        np.testing.assert_allclose(tail, full[3:], rtol=1e-5, atol=1e-6)


def _pack_batch(docs, b=2, seq_len=16, packing="best_fit"):
    packer = TokenPacker(b, seq_len, packing=packing)
    packer.feed_docs(docs)
    out = packer.pop()
    assert out is not None, "corpus did not close a batch"
    return out["tokens"], out["segment_ids"]


def _oracle_docs(rng, sizes):
    return [rng.integers(1, CFG.vocab_size, size=s).astype(np.int32)
            for s in sizes]


class TestSegmentOracle:
    """Segment-masked packing vs the per-document oracle: a packed row
    must produce, at each document's positions, exactly the logits of
    that document run alone — same mask, same (per-segment) positions."""

    def _alone(self, toks, segs, r, s):
        """Extract doc (row r, segment s) into its own single-doc row."""
        pos = np.where(segs[r] == s)[0]
        at, n = int(pos[0]), int(pos.size)
        cap = toks.shape[1]
        a_toks = np.zeros((1, cap), np.int32)
        a_toks[0, :n] = toks[r, at : at + n]
        a_segs = np.zeros((1, cap), np.int32)
        a_segs[0, :n] = 1
        return a_toks, a_segs, at, n

    def test_packed_logits_match_per_document_oracle(self):
        rng = np.random.default_rng(7)
        # 9/6/12 (+eos) fill two rows of cap 17; the trailing 4-doc fits
        # no open bin and closes the batch
        toks, segs = _pack_batch(_oracle_docs(rng, [9, 6, 12, 4]))
        params = lm.init_params(jax.random.key(0), CFG)
        packed, _ = lm.forward(params, jnp.asarray(toks), CFG,
                               segments=jnp.asarray(segs))
        packed = np.asarray(packed)
        L = packed.shape[1]
        checked = 0
        for r in range(toks.shape[0]):
            for s in np.unique(segs[r][segs[r] > 0]):
                a_toks, a_segs, at, n = self._alone(toks, segs, r, s)
                alone, _ = lm.forward(
                    params, jnp.asarray(a_toks), CFG,
                    segments=jnp.asarray(a_segs),
                )
                m = min(at + n, L) - at
                np.testing.assert_allclose(
                    packed[r, at : at + m], np.asarray(alone)[0, :m],
                    rtol=1e-5, atol=1e-5,
                )
                checked += 1
        assert checked == 3

    def test_packed_masked_loss_is_per_document_mean(self):
        """The segment-masked CE is exactly the valid-position-weighted
        mean of each document's alone CE: no cross-document targets, no
        pad contribution."""
        rng = np.random.default_rng(7)
        toks, segs = _pack_batch(_oracle_docs(rng, [9, 6, 12, 4]))
        params = lm.init_params(jax.random.key(0), CFG)
        packed = float(lm.loss_fn(params, jnp.asarray(toks), CFG,
                                  segments=jnp.asarray(segs)))
        num = den = 0.0
        for r in range(toks.shape[0]):
            for s in np.unique(segs[r][segs[r] > 0]):
                a_toks, a_segs, _, n = TestSegmentOracle._alone(
                    self, toks, segs, r, s
                )
                l_d = float(lm.loss_fn(params, jnp.asarray(a_toks), CFG,
                                       segments=jnp.asarray(a_segs)))
                num += l_d * (n - 1)
                den += n - 1
        np.testing.assert_allclose(packed, num / den, rtol=1e-5)

    def test_sp_fsdp_segments_forward_matches_dense(self):
        """Tentpole composition: segment masking through the zigzag ring
        (sp) UNDER fsdp weight sharding == the dense reference."""
        rng = np.random.default_rng(11)
        docs = [rng.integers(1, CFG.vocab_size, size=int(n)).astype(np.int32)
                for n in rng.integers(3, 15, size=60)]
        toks, segs = _pack_batch(docs, b=8)
        params = lm.init_params(jax.random.key(0), CFG)
        want, _ = lm.forward(params, jnp.asarray(toks), CFG,
                             segments=jnp.asarray(segs))
        mesh = create_mesh({"data": 2, "seq": 2, "fsdp": 2})
        p_sh = place(params, mesh, fsdp_axis="fsdp")
        got, _ = jax.jit(
            functools.partial(
                lm.forward, cfg=CFG, mesh=mesh, data_axis="data",
                seq_axis="seq", fsdp_axis="fsdp",
            )
        )(p_sh, jnp.asarray(toks), segments=jnp.asarray(segs))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_train_with_segments_dp_fsdp_matches_dense(self):
        """End to end: best-fit packed batches + segment-masked loss
        train identically dense vs dp×fsdp, and the loss actually
        falls."""
        rng = np.random.default_rng(3)
        packer = TokenPacker(8, CFG.max_len, packing="best_fit")
        packer.feed_docs(
            rng.integers(1, CFG.vocab_size, size=int(n)).astype(np.int32)
            for n in rng.integers(3, 15, size=400)
        )
        batches = []
        while len(batches) < 6:
            out = packer.pop()
            assert out is not None
            batches.append(out)

        def run(mesh, **axes):
            params = lm.init_params(jax.random.key(0), CFG)
            if mesh is not None:
                params = place(params, mesh, fsdp_axis=axes["fsdp_axis"])
            tx = optax.adam(3e-3)
            opt = tx.init(params)
            step = jax.jit(functools.partial(
                lm.train_step, cfg=CFG, tx=tx, mesh=mesh, **axes
            ))
            losses = []
            for hb in batches:
                params, opt, loss = step(
                    params, opt, jnp.asarray(hb["tokens"]),
                    segments=jnp.asarray(hb["segment_ids"]),
                )
                losses.append(float(loss))
            return losses

        ref = run(None)
        mesh = create_mesh({"data": 2, "fsdp": 4})
        got = run(mesh, data_axis="data", fsdp_axis="fsdp")
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)
        assert ref[-1] < ref[0]

    def test_segments_rejected_in_pipeline(self):
        mesh = create_mesh({"pipe": 2, "data": 4})
        params = lm.init_params(jax.random.key(0), CFG4)
        toks = batch(CFG4)
        segs = jnp.ones_like(toks)
        with pytest.raises(ValueError, match="pipeline"):
            lm.forward(params, toks, CFG4, mesh, pipe_axis="pipe",
                       segments=segs)


class TestTokenPackerBins:
    """Satellite 3: best-fit bin packing — exact placement, byte-identical
    mid-carry resume, and density >= the greedy (first-fit) baseline."""

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="packing"):
            TokenPacker(2, 8, packing="nope")

    def test_best_fit_placement_and_segments(self):
        packer = TokenPacker(2, 8, packing="best_fit")  # cap 9
        d5 = np.arange(1, 6, dtype=np.int32)
        d3 = np.arange(11, 14, dtype=np.int32)
        d7 = np.arange(21, 28, dtype=np.int32)
        packer.feed_docs([d5, d3, d7])  # +eos: 6, 4, 8 — 8 fits no bin
        out = packer.pop()
        toks, segs = out["tokens"], out["segment_ids"]
        np.testing.assert_array_equal(
            toks[0], np.concatenate([d5, [0], np.zeros(3, np.int32)])
        )
        np.testing.assert_array_equal(
            segs[0], [1, 1, 1, 1, 1, 1, 0, 0, 0]
        )
        np.testing.assert_array_equal(
            toks[1], np.concatenate([d3, [0], np.zeros(5, np.int32)])
        )
        np.testing.assert_array_equal(
            segs[1], [1, 1, 1, 1, 0, 0, 0, 0, 0]
        )
        assert packer.pop() is None
        assert packer.density() == pytest.approx(10 / 18)

    def test_long_doc_splits_into_own_segments(self):
        packer = TokenPacker(2, 8, packing="first_fit")  # cap 9
        packer.feed_docs([np.arange(1, 21, dtype=np.int32)])  # +eos = 21
        # chunks 9, 9, 3: third chunk fits neither full bin -> close
        out = packer.pop()
        toks, segs = out["tokens"], out["segment_ids"]
        np.testing.assert_array_equal(segs[0], np.ones(9, np.int32))
        np.testing.assert_array_equal(segs[1], np.ones(9, np.int32))
        np.testing.assert_array_equal(toks[0], np.arange(1, 10))
        np.testing.assert_array_equal(toks[1], np.arange(10, 19))

    def test_state_resume_byte_identical_mid_carry(self):
        rng = np.random.default_rng(5)
        docs = [rng.integers(1, 64, size=int(n)).astype(np.int32)
                for n in rng.integers(2, 12, size=80)]
        a = TokenPacker(2, 8, packing="best_fit")
        a.feed_docs(docs[:40])
        drained = []
        while (got := a.pop()) is not None:
            drained.append(got)
        carry = json.loads(json.dumps(a.state()))  # the wire round trip
        b = TokenPacker(2, 8, packing="best_fit")
        b.restore(carry)
        a.feed_docs(docs[40:])
        b.feed_docs(docs[40:])
        assert a.density() == b.density()
        while True:
            ga, gb = a.pop(), b.pop()
            assert (ga is None) == (gb is None)
            if ga is None:
                break
            np.testing.assert_array_equal(ga["tokens"], gb["tokens"])
            np.testing.assert_array_equal(
                ga["segment_ids"], gb["segment_ids"]
            )

    def test_pending_batches_survive_restore(self):
        a = TokenPacker(2, 8, packing="best_fit")
        rng = np.random.default_rng(9)
        a.feed_docs(rng.integers(1, 64, size=int(n)).astype(np.int32)
                    for n in rng.integers(2, 9, size=30))
        carry = json.loads(json.dumps(a.state()))
        b = TokenPacker(2, 8, packing="best_fit")
        b.restore(carry)
        while (ga := a.pop()) is not None:
            gb = b.pop()
            np.testing.assert_array_equal(ga["tokens"], gb["tokens"])
            np.testing.assert_array_equal(
                ga["segment_ids"], gb["segment_ids"]
            )
        assert b.pop() is None

    def test_best_fit_density_beats_greedy_on_ragged_corpus(self):
        rng = np.random.default_rng(15)
        sizes = rng.choice([2, 6, 10, 15, 16, 21, 25, 31], size=300)
        docs = [np.ones(int(s), np.int32) for s in sizes]
        dens = {}
        for mode in ("first_fit", "best_fit"):
            p = TokenPacker(4, 32, packing=mode)
            p.feed_docs(docs)
            while p.pop() is not None:
                pass
            dens[mode] = p.density()
        assert dens["best_fit"] > dens["first_fit"], dens
