"""Corruption-tolerance subsystem: RetryPolicy unit behavior, writer
commit retries, orphaned-staging sweep, tolerant row-level reads, salvage
observability, and the tfrecord_doctor CLI.

The dataset-level salvage corpus (byte-flip matrix, quota escalation,
resume-under-skip determinism) lives in tests/test_fuzz.py.
"""

import importlib.util
import json
import os
import socket
import sys

import pytest

import tpu_tfrecord.io as tfio
from tpu_tfrecord import fs as tfs, wire
from tpu_tfrecord.io import writer as writer_mod
from tpu_tfrecord.io.dataset import TFRecordDataset
from tpu_tfrecord.io.writer import DatasetWriter, sweep_orphan_jobs
from tpu_tfrecord.metrics import METRICS
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.retry import NO_RETRY, RetryPolicy
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.serde import TFRecordSerializer, encode_row

SCHEMA = StructType(
    [StructField("id", LongType(), nullable=False), StructField("s", StringType())]
)
ROWS = [[i, f"val{i}"] for i in range(24)]

UID_SCHEMA = StructType([StructField("uid", LongType(), nullable=False)])


def _noop_sleep(_s):
    return


def _write_corrupt_shard(dirname, n=30, corrupt_frames=(10,)):
    """One shard of n uid records with the payload of each listed frame
    corrupted; returns (dir, shard_path)."""
    ser = TFRecordSerializer(UID_SCHEMA)
    frames = [
        wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i]))
        for i in range(n)
    ]
    offs = [0]
    for f in frames:
        offs.append(offs[-1] + len(f))
    blob = bytearray(b"".join(frames))
    for k in corrupt_frames:
        blob[offs[k] + wire.HEADER_BYTES + 1] ^= 0xFF
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, "part-0.tfrecord")
    with open(path, "wb") as fh:
        fh.write(bytes(blob))
    return dirname, path


class TestRetryPolicy:
    def test_backoff_is_capped_exponential(self):
        pol = RetryPolicy(max_retries=9, base_delay=0.1, max_delay=2.0, jitter=False)
        assert pol.backoff(1) == pytest.approx(0.1)
        assert pol.backoff(2) == pytest.approx(0.2)
        assert pol.backoff(5) == pytest.approx(1.6)
        assert pol.backoff(6) == pytest.approx(2.0)  # capped
        assert pol.backoff(20) == pytest.approx(2.0)

    def test_full_jitter_stays_within_cap(self):
        vals = iter([0.0, 0.5, 1.0])
        pol = RetryPolicy(max_retries=3, base_delay=0.1, rand=lambda: next(vals))
        assert pol.backoff(3) == pytest.approx(0.0)
        assert pol.backoff(3) == pytest.approx(0.2)
        assert pol.backoff(3) == pytest.approx(0.4)

    def test_pause_budget_and_injected_sleep(self):
        slept = []
        pol = RetryPolicy(max_retries=2, jitter=False, sleep=slept.append)
        assert pol.pause(1) and pol.pause(2)
        assert not pol.pause(3)  # budget exhausted: no sleep, caller raises
        assert slept == [pytest.approx(0.1), pytest.approx(0.2)]

    def test_deadline_with_injected_clock(self):
        now = [0.0]
        pol = RetryPolicy(
            max_retries=100, jitter=False, base_delay=1.0, max_delay=1.0,
            deadline=2.5, sleep=lambda s: now.__setitem__(0, now[0] + s),
            clock=lambda: now[0],
        )
        start = pol.clock()
        assert pol.pause(1, start) and pol.pause(2, start)
        # 2.0 elapsed + 1.0 backoff > 2.5: the backoff is CAPPED to the
        # remaining 0.5s budget (never sleeps past the deadline) and the
        # retry is still taken; the NEXT pause finds the budget exhausted
        assert pol.pause(3, start)
        assert now[0] == 2.5
        assert not pol.pause(4, start)
        assert now[0] == 2.5  # refused without sleeping

    def test_call_retries_then_returns(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        retries = []
        pol = RetryPolicy(max_retries=5, sleep=_noop_sleep)
        assert pol.call(flaky, on_retry=lambda a, e: retries.append(a)) == "ok"
        assert calls["n"] == 3 and retries == [1, 2]

    def test_call_exhausts_and_raises(self):
        pol = RetryPolicy(max_retries=2, sleep=_noop_sleep)
        with pytest.raises(OSError, match="always"):
            pol.call(lambda: (_ for _ in ()).throw(OSError("always")))

    def test_no_retry_default(self):
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise OSError("x")

        with pytest.raises(OSError):
            NO_RETRY.call(boom)
        assert calls["n"] == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_retries=-1)
        with pytest.raises(ValueError):
            RetryPolicy(base_delay=-0.5)


class TestOptionsSurface:
    def test_on_corrupt_values_validated(self):
        opts = TFRecordOptions.from_map(
            {"on_corrupt": "skip_record", "maxCorruptRecords": 7,
             "corrupt_fallback": "skip_shard", "writeRetries": 3}
        )
        assert opts.on_corrupt == "skip_record"
        assert opts.max_corrupt_records == 7
        assert opts.corrupt_fallback == "skip_shard"
        assert opts.write_retries == 3

    def test_bad_values_raise(self):
        with pytest.raises(ValueError, match="on_corrupt"):
            TFRecordOptions.from_map({"on_corrupt": "ignore"})
        with pytest.raises(ValueError, match="corrupt_fallback"):
            TFRecordOptions.from_map({"corrupt_fallback": "skip_record"})
        with pytest.raises(ValueError, match="max_corrupt_records"):
            TFRecordOptions.from_map({"max_corrupt_records": -1})
        with pytest.raises(ValueError, match="write_retries"):
            TFRecordOptions.from_map({"write_retries": -1})

    def test_defaults_are_strict(self):
        opts = TFRecordOptions()
        assert opts.on_corrupt == "raise"
        assert opts.corrupt_fallback == "raise"
        assert opts.write_retries == 0


class TestTolerantRowReads:
    """io.read / ShardReader honor on_corrupt too — the row-level analog of
    the dataset policy (the doctor CLI's online counterpart)."""

    def test_skip_record_row_path(self, sandbox):
        d, _ = _write_corrupt_shard(str(sandbox / "rows"), corrupt_frames=(7,))
        with pytest.raises(wire.TFRecordCorruptionError):
            tfio.read(d, schema=UID_SCHEMA)
        table = tfio.read(d, schema=UID_SCHEMA, on_corrupt="skip_record")
        assert table.column("uid") == [i for i in range(30) if i != 7]

    def test_skip_shard_row_path(self, sandbox):
        d, _ = _write_corrupt_shard(str(sandbox / "rows2"), corrupt_frames=(7,))
        skipped0 = METRICS.counter("read.skipped_shards")
        table = tfio.read(d, schema=UID_SCHEMA, on_corrupt="skip_shard")
        # rows validated before the corruption survive; the rest is dropped
        assert table.column("uid") == list(range(7))
        assert METRICS.counter("read.skipped_shards") == skipped0 + 1

    def test_inference_skips_corrupt_shard_under_tolerant_policy(self, sandbox):
        """Schema inference must survive a corrupt candidate shard under a
        tolerant policy: it falls back to the salvageable records."""
        d = str(sandbox / "infer")
        _write_corrupt_shard(d, corrupt_frames=(10,))  # part-0, scanned first
        ser = TFRecordSerializer(UID_SCHEMA)
        with open(os.path.join(d, "part-1.tfrecord"), "wb") as fh:
            for i in range(100, 110):
                fh.write(wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i])))
        with pytest.raises(wire.TFRecordCorruptionError):
            tfio.read(d)  # strict: inference hits the corruption and raises
        table = tfio.read(d, on_corrupt="skip_record")  # schema inferred
        assert sorted(table.column("uid")) == [
            i for i in range(30) if i != 10
        ] + list(range(100, 110))

    def test_inference_salvages_single_corrupt_shard(self, sandbox):
        """A dataset whose ONLY shard is corrupt still opens under
        skip_record: inference folds over the salvageable records."""
        d, _ = _write_corrupt_shard(str(sandbox / "infer1"), corrupt_frames=(10,))
        table = tfio.read(d, on_corrupt="skip_record")  # no schema given
        assert table.column("uid") == [i for i in range(30) if i != 10]

    def test_retry_rescan_does_not_double_count_salvage(self, sandbox, monkeypatch):
        """A transient-IO retry re-scans the same corrupt regions: the
        quota must reset, but the fleet counters and logs must not
        re-report regions already reported (deterministic scan order)."""
        d, path = _write_corrupt_shard(str(sandbox / "recount"), corrupt_frames=(5, 12))
        real_open = wire.open_compressed
        calls = {"n": 0}

        class LateFault:
            def __init__(self, fh):
                self._fh = fh
                self._reads = 0

            def read(self, n=-1):
                self._reads += 1
                # fail once mid-stream on the FIRST pass, after the scanner
                # saw both corrupt regions (file fits one read; fault the
                # EOF-confirming empty read)
                if calls["n"] == 1 and self._reads == 2:
                    raise OSError("post-scan transient blip")
                return self._fh.read(n)

            def __getattr__(self, name):
                return getattr(self._fh, name)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                self._fh.close()

        def flaky(p, mode, codec):
            calls["n"] += 1
            return LateFault(real_open(p, mode, codec))

        monkeypatch.setattr("tpu_tfrecord.wire.open_compressed", flaky)
        corrupt0 = METRICS.counter("read.corrupt_records")
        ds = TFRecordDataset(
            d, batch_size=4, schema=UID_SCHEMA, drop_remainder=False,
            on_corrupt="skip_record",
            retry_policy=RetryPolicy(max_retries=2, sleep=_noop_sleep),
        )
        got = [v for cb in ds.batches() for v in cb["uid"].values.tolist()]
        assert got == [i for i in range(30) if i not in (5, 12)]
        assert calls["n"] >= 2  # the retry actually happened
        # two regions, reported exactly once each despite the re-scan
        assert METRICS.counter("read.corrupt_records") == corrupt0 + 2

    def test_salvage_counters_and_structured_log(self, sandbox, caplog):
        d, _ = _write_corrupt_shard(str(sandbox / "rows3"), corrupt_frames=(5,))
        corrupt0 = METRICS.counter("read.corrupt_records")
        resync0 = METRICS.counter("read.resyncs")
        with caplog.at_level("WARNING", logger="tpu_tfrecord"):
            tfio.read(d, schema=UID_SCHEMA, on_corrupt="skip_record")
        assert METRICS.counter("read.corrupt_records") == corrupt0 + 1
        assert METRICS.counter("read.resyncs") == resync0 + 1
        salvage = [r for r in caplog.records if "tfrecord.salvage" in r.getMessage()]
        assert salvage, caplog.records
        payload = json.loads(salvage[0].getMessage().split(" ", 1)[1])
        assert payload["path"].endswith("part-0.tfrecord")
        assert isinstance(payload["offset"], int)
        assert payload["kind"] == "data_crc"


class TestSkipShardDataset:
    def test_epoch_continues_past_bad_shard(self, sandbox):
        d = str(sandbox / "multi")
        os.makedirs(d)
        ser = TFRecordSerializer(UID_SCHEMA)
        good = b"".join(
            wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i]))
            for i in range(100, 120)
        )
        with open(os.path.join(d, "part-b.tfrecord"), "wb") as fh:
            fh.write(good)
        _write_corrupt_shard(d, corrupt_frames=(0,))
        skipped0 = METRICS.counter("read.skipped_shards")
        ds = TFRecordDataset(
            d, batch_size=4, schema=UID_SCHEMA, drop_remainder=False,
            on_corrupt="skip_shard",
        )
        got = [v for cb in ds.batches() for v in cb["uid"].values.tolist()]
        assert got == list(range(100, 120))
        assert METRICS.counter("read.skipped_shards") == skipped0 + 1


class TestReadRetryCounter:
    def test_transient_retry_increments_counter(self, sandbox, monkeypatch):
        out = str(sandbox / "retry")
        tfio.write(ROWS[:7], SCHEMA, out, mode="overwrite")
        real_open = wire.open_compressed
        calls = {"n": 0}

        def flaky(path, mode, codec):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient blip")
            return real_open(path, mode, codec)

        monkeypatch.setattr("tpu_tfrecord.wire.open_compressed", flaky)
        retries0 = METRICS.counter("read.retries")
        ds = TFRecordDataset(
            out, batch_size=7, schema=SCHEMA, use_mmap=False,
            retry_policy=RetryPolicy(max_retries=2, sleep=_noop_sleep),
        )
        got = [v for cb in ds.batches() for v in cb["id"].values.tolist()]
        assert len(got) == 7
        assert METRICS.counter("read.retries") == retries0 + 1


class TestWriterCommitRetries:
    def test_flaky_rename_retried_and_counted(self, sandbox, monkeypatch):
        calls = {"n": 0}
        real_rename = tfs.LocalFS.rename

        def flaky_rename(self, src, dst):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient rename blip")
            return real_rename(self, src, dst)

        monkeypatch.setattr(tfs.LocalFS, "rename", flaky_rename)
        out = str(sandbox / "commit")
        retries0 = METRICS.counter("write.commit_retries")
        w = DatasetWriter(
            out, SCHEMA, mode="error",
            retry_policy=RetryPolicy(max_retries=2, sleep=_noop_sleep),
        )
        w.write_rows(ROWS)
        assert METRICS.counter("write.commit_retries") == retries0 + 1
        assert sorted(tfio.read(out, schema=SCHEMA).column("id")) == [
            r[0] for r in ROWS
        ]
        assert tfio.has_success_marker(out)

    def test_rename_that_actually_landed_not_rerun(self, sandbox, monkeypatch):
        """Remote stores can error AFTER the rename landed: the retry must
        detect the landed rename instead of failing on the missing source."""
        real_rename = tfs.LocalFS.rename
        calls = {"n": 0}

        def lying_rename(self, src, dst):
            calls["n"] += 1
            real_rename(self, src, dst)
            if calls["n"] == 1:
                raise OSError("rename landed but the store said no")

        monkeypatch.setattr(tfs.LocalFS, "rename", lying_rename)
        out = str(sandbox / "landed")
        w = DatasetWriter(
            out, SCHEMA, mode="error",
            retry_policy=RetryPolicy(max_retries=2, sleep=_noop_sleep),
        )
        paths = w.write_rows(ROWS)
        assert len(paths) == 1
        assert sorted(tfio.read(out, schema=SCHEMA).column("id")) == [
            r[0] for r in ROWS
        ]

    def test_default_policy_still_fails_fast(self, sandbox, monkeypatch):
        def always_fail(self, src, dst):
            raise OSError("permanently broken")

        monkeypatch.setattr(tfs.LocalFS, "rename", always_fail)
        out = str(sandbox / "failfast")
        with pytest.raises(OSError, match="permanently broken"):
            tfio.write(ROWS, SCHEMA, out, mode="error")


class TestOrphanSweep:
    def _make_job_dir(self, out, name, pid=None, host=None, marker=True):
        d = os.path.join(out, "_temporary", name)
        os.makedirs(d)
        with open(os.path.join(d, "part-stale.tfrecord"), "wb") as fh:
            fh.write(b"stale bytes")
        if marker:
            meta = {
                "pid": os.getpid() if pid is None else pid,
                "host": socket.gethostname() if host is None else host,
            }
            with open(os.path.join(d, writer_mod._JOB_MARKER), "w") as fh:
                fh.write(json.dumps(meta))
        return d

    def test_commit_sweeps_dead_pid_staging(self, sandbox):
        out = str(sandbox / "sweep")
        tfio.write(ROWS[:4], SCHEMA, out, mode="overwrite")
        dead = self._make_job_dir(out, "deadjob000001", pid=2**22 + 12345)
        live = self._make_job_dir(out, "livejob000001")  # our own pid
        foreign = self._make_job_dir(out, "foreignjob001", pid=1, host="elsewhere")
        unmarked = self._make_job_dir(out, "unmarkedjob01", marker=False)
        tfio.write(ROWS[:4], SCHEMA, out, mode="append")
        assert not os.path.exists(dead), "crashed-job staging must be swept"
        assert os.path.exists(live), "live concurrent job must be preserved"
        assert os.path.exists(foreign), "other hosts' jobs must be preserved"
        assert os.path.exists(unmarked), "unjudgeable dirs must be preserved"

    def test_abort_sweeps_too(self, sandbox):
        out = str(sandbox / "sweepabort")
        tfio.write(ROWS[:4], SCHEMA, out, mode="overwrite")
        dead = self._make_job_dir(out, "deadjob000002", pid=2**22 + 23456)

        class Boom(Exception):
            pass

        def exploding_rows():
            yield ROWS[0]
            raise Boom()

        with pytest.raises(Boom):
            DatasetWriter(out, SCHEMA, mode="append").write_rows(exploding_rows())
        assert not os.path.exists(dead)

    def test_sweep_never_raises(self, sandbox):
        class HostileFS:
            def isdir(self, path):
                raise OSError("listing denied")

        assert sweep_orphan_jobs(HostileFS(), str(sandbox)) == []


def _load_doctor():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tfrecord_doctor", os.path.join(root, "tools", "tfrecord_doctor.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestDoctorCLI:
    def test_scan_reports_each_corruption(self, sandbox, capsys):
        doctor = _load_doctor()
        d, path = _write_corrupt_shard(str(sandbox / "doc"), corrupt_frames=(4, 20))
        rc = doctor.main([path])
        out = capsys.readouterr().out
        lines = [json.loads(l) for l in out.splitlines()]
        assert rc == 1
        corrupt = [l for l in lines if l["event"] == "corrupt"]
        summary = [l for l in lines if l["event"] == "summary"][0]
        assert len(corrupt) == 2
        assert summary["records"] == 28
        assert summary["corrupt_events"] == 2
        assert all(c["kind"] == "data_crc" for c in corrupt)

    def test_repair_round_trips(self, sandbox, capsys):
        doctor = _load_doctor()
        d, path = _write_corrupt_shard(str(sandbox / "fix"), corrupt_frames=(9,))
        rc = doctor.main(["--repair", path])
        out = capsys.readouterr().out
        assert rc == 1
        summary = [
            json.loads(l) for l in out.splitlines()
        ][-1]
        repaired = summary["repaired_path"]
        assert os.path.exists(repaired)
        # the salvaged shard reads CLEANLY (strict framing) and keeps order
        recs = list(wire.read_records(repaired))
        assert len(recs) == 29
        ds_got = [i for i in range(30) if i != 9]
        from tpu_tfrecord.serde import TFRecordDeserializer, decode_record

        de = TFRecordDeserializer(UID_SCHEMA)
        assert [
            decode_record(de, RecordType.EXAMPLE, r)[0] for r in recs
        ] == ds_got

    def test_repaired_copy_invisible_to_discovery(self, sandbox, capsys):
        """--repair in place must not make the next read serve both the
        corrupt original and the salvaged copy (hidden-file naming), and a
        second doctor run must not re-scan repaired output."""
        doctor = _load_doctor()
        d, path = _write_corrupt_shard(str(sandbox / "inplace"), corrupt_frames=(9,))
        assert doctor.main(["--repair", path]) == 1
        out = capsys.readouterr().out
        repaired = [json.loads(l) for l in out.splitlines()][-1]["repaired_path"]
        assert os.path.basename(repaired).startswith("_")
        # tolerant dir read sees ONLY the original shard — no duplicates
        table = tfio.read(d, schema=UID_SCHEMA, on_corrupt="skip_record")
        assert table.column("uid") == [i for i in range(30) if i != 9]
        # a second doctor pass over the DIR scans one file, not two
        assert doctor.main([d]) == 1
        lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
        assert [l["path"] for l in lines if l["event"] == "summary"] == [path]

    def test_explicit_out_kept_even_when_clean(self, sandbox, capsys):
        """--out is a contract: the caller consumes that path whether or
        not the input turned out corrupt."""
        doctor = _load_doctor()
        d = str(sandbox / "cleanout")
        os.makedirs(d)
        ser = TFRecordSerializer(UID_SCHEMA)
        src = os.path.join(d, "part-0.tfrecord")
        with open(src, "wb") as fh:
            for i in range(10):
                fh.write(wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i])))
        dst = os.path.join(d, "verified.tfrecord")
        assert doctor.main(["--repair", "--out", dst, src]) == 0
        summary = [json.loads(l) for l in capsys.readouterr().out.splitlines()][-1]
        assert summary["repaired_path"] == dst
        assert len(list(wire.read_records(dst))) == 10

    def test_clean_file_exit_zero(self, sandbox, capsys):
        doctor = _load_doctor()
        d = str(sandbox / "clean")
        os.makedirs(d)
        ser = TFRecordSerializer(UID_SCHEMA)
        with open(os.path.join(d, "part-0.tfrecord"), "wb") as fh:
            for i in range(10):
                fh.write(wire.encode_record(encode_row(ser, RecordType.EXAMPLE, [i])))
        rc = doctor.main([d])  # directory input expands to shards
        out = capsys.readouterr().out
        summary = [json.loads(l) for l in out.splitlines()][-1]
        assert rc == 0
        assert summary["records"] == 10 and summary["corrupt_events"] == 0
