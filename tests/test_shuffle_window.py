"""Windowed row shuffle: determinism, coverage, resume, checkpoint format.

No reference analog (Spark shuffles via DataFrame ops, not the format
plugin; TFRecord is unsplittable so a global row permutation is impossible
without an index) — this pins the streaming-native equivalent: rows permute
deterministically across windows of ``shuffle_window`` batches, with
O(1)-state resume (IteratorState.window_emitted).
"""

import numpy as np
import pytest

from tpu_tfrecord import wire
from tpu_tfrecord.io.dataset import IteratorState, TFRecordDataset
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import LongType, StringType, StructField, StructType
from tpu_tfrecord.serde import TFRecordSerializer, encode_row

SCHEMA = StructType(
    [StructField("i", LongType(), nullable=False), StructField("s", StringType())]
)


def write_dataset(d, shards=3, rows_per_shard=40):
    ser = TFRecordSerializer(SCHEMA)
    n = 0
    for s in range(shards):
        recs = []
        for _ in range(rows_per_shard):
            recs.append(encode_row(ser, RecordType.EXAMPLE, [n, f"r{n}"]))
            n += 1
        wire.write_records(str(d / f"part-{s:05d}.tfrecord"), recs)
    return n


def make_ds(d, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("schema", SCHEMA)
    kw.setdefault("num_epochs", 1)
    kw.setdefault("drop_remainder", False)
    kw.setdefault("shuffle_window", 4)
    return TFRecordDataset(str(d), **kw)


def read_ids(it):
    out = []
    for b in it:
        out.extend(int(v) for v in b["i"].values)
    return out


class TestShuffleWindow:
    def test_coverage_and_determinism(self, sandbox):
        total = write_dataset(sandbox)
        ids1 = read_ids(make_ds(sandbox, seed=7).batches())
        ids2 = read_ids(make_ds(sandbox, seed=7).batches())
        ids3 = read_ids(make_ds(sandbox, seed=8).batches())
        assert sorted(ids1) == list(range(total))  # every row exactly once
        assert ids1 == ids2  # same seed -> identical order
        assert ids1 != ids3  # different seed -> different order
        assert ids1 != list(range(total))  # actually shuffled

    def test_rows_move_across_batches_within_window(self, sandbox):
        write_dataset(sandbox)
        ds = make_ds(sandbox, batch_size=8, shuffle_window=4, seed=1)
        batches = [list(map(int, b["i"].values)) for b in ds.batches()]
        # window 0 covers rows 0..31: its four batches together hold exactly
        # those ids, but no single batch is a contiguous run
        window0 = sorted(sum(batches[:4], []))
        assert window0 == list(range(32))
        assert any(b != sorted(b) or b != list(range(b[0], b[0] + 8)) for b in batches[:4])

    def test_string_column_rides_the_permutation(self, sandbox):
        write_dataset(sandbox)
        for b in make_ds(sandbox, seed=3).batches():
            ids = [int(v) for v in b["i"].values]
            strs = [bytes(s).decode() for s in b["s"].blobs]
            assert strs == [f"r{i}" for i in ids]  # rows stay intact

    def test_windows_span_shards_and_epochs(self, sandbox):
        total = write_dataset(sandbox, shards=2, rows_per_shard=13)  # 26 rows
        ds = make_ds(sandbox, batch_size=4, shuffle_window=3, num_epochs=2, seed=5)
        ids = read_ids(ds.batches())
        assert sorted(ids) == sorted(list(range(total)) * 2)

    def test_drop_remainder_tail(self, sandbox):
        total = write_dataset(sandbox, shards=1, rows_per_shard=21)
        ids = read_ids(make_ds(sandbox, batch_size=4, drop_remainder=True).batches())
        assert len(ids) == 20  # 21 rows -> 5 batches, tail row dropped
        ids_keep = read_ids(make_ds(sandbox, batch_size=4, drop_remainder=False).batches())
        assert sorted(ids_keep) == list(range(total))

    @pytest.mark.parametrize("kill_after", [1, 3, 4, 6, 9])
    def test_resume_mid_window_is_exact(self, sandbox, kill_after):
        write_dataset(sandbox)
        full = []
        it = make_ds(sandbox, seed=11).batches()
        for b in it:
            full.append([int(v) for v in b["i"].values])

        it = make_ds(sandbox, seed=11).batches()
        got = []
        for _ in range(kill_after):
            got.append([int(v) for v in next(it)["i"].values])
        state = it.state()
        it.close()
        # resume on a FRESH dataset object from the saved state
        it2 = make_ds(sandbox, seed=11).batches(state)
        for b in it2:
            got.append([int(v) for v in b["i"].values])
        assert got == full

    def test_state_points_at_window_start_mid_window(self, sandbox):
        write_dataset(sandbox)
        it = make_ds(sandbox, seed=2).batches()
        next(it)  # batch 0 of window 0
        st = it.state()
        assert st.window_emitted == 1
        assert (st.epoch, st.shard_cursor, st.record_offset) == (0, 0, 0)
        for _ in range(3):
            next(it)  # finish window 0 (4 batches of 8 = 32 = window)
        st2 = it.state()
        assert st2.window_emitted == 0  # clean between-window position
        it.close()

    def test_fingerprint_guards_window_config(self, sandbox):
        write_dataset(sandbox)
        it = make_ds(sandbox, shuffle_window=4).batches()
        next(it)
        state = it.state()
        it.close()
        with pytest.raises(ValueError, match="fingerprint"):
            make_ds(sandbox, shuffle_window=2).batches(state)
        with pytest.raises(ValueError, match="fingerprint"):
            make_ds(sandbox, shuffle_window=4, batch_size=16).batches(state)
        with pytest.raises(ValueError, match="fingerprint"):
            make_ds(sandbox, shuffle_window=0).batches(state)

    def test_checkpoint_format_version(self, sandbox, tmp_path):
        from tpu_tfrecord import checkpoint

        write_dataset(sandbox)
        it = make_ds(sandbox, seed=4).batches()
        next(it)
        ckdir = str(tmp_path / "ck")
        import os

        os.makedirs(ckdir, exist_ok=True)
        checkpoint.save_state(ckdir, it)
        it.close()
        import json

        payload = json.loads(
            open(checkpoint.state_path(ckdir, 0)).read()
        )
        assert payload["version"] == 2  # mid-window states are version 2
        restored = checkpoint.load_state(ckdir)
        assert restored.window_emitted == 1
        # between-window states stay version 1 (old readers keep working)
        it = make_ds(sandbox, seed=4).batches()
        for _ in range(4):
            next(it)
        checkpoint.save_state(ckdir, it)
        it.close()
        payload = json.loads(open(checkpoint.state_path(ckdir, 0)).read())
        assert payload["version"] == 1

    def test_composes_with_shard_shuffle_and_native_off(self, sandbox):
        total = write_dataset(sandbox)
        ids_native = read_ids(make_ds(sandbox, shuffle=True, seed=9).batches())
        assert sorted(ids_native) == list(range(total))
        # force the pure-Python decode path (env caching makes the
        # TPU_TFRECORD_NO_NATIVE knob process-start-only): same stream
        ds = make_ds(sandbox, shuffle=True, seed=9)
        ds._native_decoder = None
        ids_oracle = read_ids(ds.batches())
        assert ids_oracle == ids_native

    def test_rejects_negative_window(self, sandbox):
        write_dataset(sandbox)
        with pytest.raises(ValueError, match="shuffle_window"):
            make_ds(sandbox, shuffle_window=-1)
