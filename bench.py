#!/usr/bin/env python
"""End-to-end ingest benchmark: Criteo-like TFRecords -> device memory.

Measures the BASELINE.md north-star metric: tf.Example/sec/host sustained
into device HBM through the full pipeline — native frame scan + CRC, native
batch decode to columnar buffers (background prefetch thread, GIL released),
categorical hashing, global-array assembly on the device mesh, transfer
blocked to completion.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is value / 1e6 (the reference publishes no numbers —
BASELINE.md: >=1M examples/sec/host target; >1.0 beats it).

Dataset: Criteo-shaped — int64 label, 13 int64 dense features, 26
categorical byte strings — TFR_BENCH_SHARDS shards (default 4) of
RECORDS_PER_SHARD records, generated once and cached (the cache key
includes the shard count, so changing TFR_BENCH_SHARDS regenerates
instead of silently benchmarking a stale dataset).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_SHARDS = int(os.environ.get("TFR_BENCH_SHARDS", 4))
RECORDS_PER_SHARD = int(os.environ.get("TFR_BENCH_RECORDS_PER_SHARD", 32768))
BATCH_SIZE = int(os.environ.get("TFR_BENCH_BATCH", 16384))
HASH_BUCKETS = 1 << 20
CAT_BITS = 20  # hash_buckets = 2**20 -> bucket indices carry 20 bits
WARMUP_BATCHES = 4
MEASURE_SECONDS = float(os.environ.get("TFR_BENCH_SECONDS", 6.0))
SUSTAIN_SECONDS = float(os.environ.get("TFR_BENCH_SUSTAIN", 8.0))
# Transport study (PARITY.md "Device link" section): this box's TPU is
# behind a forwarded tunnel with token-bucket traffic shaping — ~1.4GB/s
# until a burst budget (~0.8-1GB after idle) drains, then ~130-250MB/s,
# recovering after ~15s of link quiet. A short rest before the device phase
# measures the pipeline rather than leftover limiter state from whatever
# ran before the bench. Real PCIe-attached TPU hosts have neither the
# shaping nor the rest.
REST_SECONDS = float(os.environ.get("TFR_BENCH_REST", 15.0))


def criteo_schema():
    """Write-side schema (inference parity: ints are LongType)."""
    from tpu_tfrecord.schema import LongType, StringType, StructField, StructType

    fields = [StructField("label", LongType(), nullable=False)]
    fields += [StructField(f"I{i}", LongType()) for i in range(1, 14)]
    fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    return StructType(fields)


def criteo_read_schema():
    """Read-side schema: IntegerType for the int features — the reference's
    IntegerType read path (Long.toInt truncation, TFRecordDeserializer
    IntegerType case) — so every device-bound column is int32 and the whole
    batch packs into ONE [B, 40] i32 matrix (one transfer dispatch)."""
    from tpu_tfrecord.schema import IntegerType, StringType, StructField, StructType

    fields = [StructField("label", IntegerType(), nullable=False)]
    fields += [StructField(f"I{i}", IntegerType()) for i in range(1, 14)]
    fields += [StructField(f"C{i}", StringType()) for i in range(1, 27)]
    return StructType(fields)


def ensure_dataset(data_dir: str) -> str:
    """Generate the benchmark dataset once; reuse across runs. The cache
    key (a subdirectory) includes the generation parameters, so changing
    TFR_BENCH_SHARDS / TFR_BENCH_RECORDS_PER_SHARD regenerates instead of
    silently measuring a stale dataset of the wrong shape."""
    from tpu_tfrecord import wire
    from tpu_tfrecord.options import RecordType
    from tpu_tfrecord.serde import TFRecordSerializer, encode_row

    data_dir = os.path.join(data_dir, f"s{N_SHARDS}r{RECORDS_PER_SHARD}")
    marker = os.path.join(data_dir, "_BENCH_READY")
    if os.path.exists(marker):
        return data_dir
    os.makedirs(data_dir, exist_ok=True)
    schema = criteo_schema()
    ser = TFRecordSerializer(schema)
    rng = np.random.default_rng(0)
    for s in range(N_SHARDS):
        ints = rng.integers(0, 1 << 31, size=(RECORDS_PER_SHARD, 13))
        labels = rng.integers(0, 2, size=RECORDS_PER_SHARD)
        cats = rng.integers(0, 16, size=(RECORDS_PER_SHARD, 26, 8), dtype=np.uint8) + 97

        def rows():
            for r in range(RECORDS_PER_SHARD):
                row = [int(labels[r])]
                row += [int(v) for v in ints[r]]
                row += [cats[r, c].tobytes().decode() for c in range(26)]
                yield encode_row(ser, RecordType.EXAMPLE, row)

        wire.write_records(
            os.path.join(data_dir, f"part-{s:05d}-bench.tfrecord"), rows()
        )
    with open(marker, "w") as fh:
        fh.write("ok")
    return data_dir


def _make_dataset(data_dir, schema, hash_buckets, pack, **kw):
    from tpu_tfrecord.io.dataset import TFRecordDataset

    return TFRecordDataset(
        data_dir,
        batch_size=BATCH_SIZE,
        schema=schema,
        prefetch=4,
        hash_buckets=hash_buckets,  # fused into native decode
        pack=pack,              # groups assembled in C++ as [B, K] matrices
        **kw,
    )


def _host_side_throughput(data_dir, schema, hash_buckets, pack, seconds=4.0, **ds_kw):
    """Device-free pipeline throughput: frame scan + CRC + decode + hash +
    pack to dense host batches, no device anywhere. Measured on EVERY run
    (before backend init) so a dead TPU tunnel still yields a comparable
    number for the round's artifact instead of only an error string.
    ``ds_kw`` forwards extra dataset options (the stall-guard overhead
    probe runs this same loop with deadlines+watchdog enabled)."""
    from tpu_tfrecord.tpu import host_batch_from_columnar

    ds = _make_dataset(data_dir, schema, hash_buckets, pack, num_epochs=None, **ds_kw)
    it = ds.batches()
    try:
        for _ in range(2):  # warm the decode threads / entry-shape caches
            host_batch_from_columnar(
                next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
            )
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < seconds:
            hb = host_batch_from_columnar(
                next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
            )
            n += hb["packed"].shape[0]
        return n / (time.perf_counter() - t0)
    finally:
        it.close()


def _drop_page_cache(data_dir) -> None:
    """Evict the shards from the page cache (POSIX_FADV_DONTNEED; works on
    ext4 for clean pages without any privileges)."""
    for name in sorted(os.listdir(data_dir)):
        if not name.startswith("part-"):
            continue
        fd = os.open(os.path.join(data_dir, name), os.O_RDONLY)
        try:
            # fsync first: DONTNEED silently skips dirty pages, so a
            # just-generated dataset would otherwise measure warm.
            os.fsync(fd)
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        finally:
            os.close(fd)


def _raw_disk_mbps(data_dir) -> float:
    """Serial cold read of the shards, 8MB blocks, no hints: the
    UNENGINEERED IO bound, disclosed next to cold_value so the pipeline
    number reads against the store's state during THIS run (the backing
    volume on this box swings 150 MB/s .. 2 GB/s between moments)."""
    _drop_page_cache(data_dir)
    buf = bytearray(8 << 20)
    t0 = time.perf_counter()
    nb = 0
    for name in sorted(os.listdir(data_dir)):
        if not name.startswith("part-"):
            continue
        with open(os.path.join(data_dir, name), "rb", buffering=0) as fh:
            while True:
                k = fh.readinto(buf)
                if not k:
                    break
                nb += k
    return nb / (time.perf_counter() - t0) / 1e6


def _cold_io_throughput(data_dir, schema, hash_buckets, pack) -> dict:
    """One full pass over the dataset right after dropping it from the page
    cache: the only number here that includes real disk IO (the main
    measurement loops over a cache-resident dataset — BASELINE.md configs[4]
    is about line-rate ingest of storage-resident data).

    Engineered (round 4): sliding posix_fadvise(WILLNEED) readahead inside
    the decode paths (io/dataset.py) keeps the kernel streaming ahead of
    the decoder, and ``num_workers`` shards decode/IO concurrently (IO
    waits release the GIL, so overlap is real even on this 1-core host).
    The raw serial disk rate is measured first and disclosed, so
    cold_value / cold_disk_bound_value tells IO-bound from decode-bound."""
    from tpu_tfrecord.tpu import host_batch_from_columnar

    disk_mbps = _raw_disk_mbps(data_dir)
    wire_bytes = sum(
        os.path.getsize(os.path.join(data_dir, n))
        for n in os.listdir(data_dir)
        if n.startswith("part-")
    )
    n_records = N_SHARDS * RECORDS_PER_SHARD
    bytes_per_example = wire_bytes / n_records
    workers = int(os.environ.get("TFR_BENCH_COLD_WORKERS", 2))
    readahead = int(os.environ.get("TFR_BENCH_COLD_READAHEAD", 64 << 20))
    _drop_page_cache(data_dir)
    ds = _make_dataset(
        data_dir, schema, hash_buckets, pack,
        num_epochs=1, num_workers=workers, readahead_bytes=readahead,
    )
    # Stage attribution (VERDICT r4 item 2): process CPU time vs wall tells
    # IO-stalled from CPU-bound; consumer-side wait/pack and the decode
    # stage's per-worker seconds (sums across threads, so it can exceed
    # wall when overlap works) localize where the wall time went; majflt ~ 0
    # proves the WILLNEED readahead turned cold reads into prefetched
    # (minor-fault) hits.
    import resource

    from tpu_tfrecord.metrics import METRICS

    d0 = METRICS.stage("decode").seconds
    r0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    n = 0
    wait_s = 0.0
    pack_s = 0.0
    with ds.batches() as it:
        while True:
            w0 = time.perf_counter()
            cb = next(it, None)
            wait_s += time.perf_counter() - w0
            if cb is None:
                break
            p0 = time.perf_counter()
            hb = host_batch_from_columnar(
                cb, ds.schema, hash_buckets=hash_buckets, pack=pack
            )
            pack_s += time.perf_counter() - p0
            n += hb["packed"].shape[0]
    wall = time.perf_counter() - t0
    r1 = resource.getrusage(resource.RUSAGE_SELF)
    decode_s = METRICS.stage("decode").seconds - d0
    cpu_s = (r1.ru_utime - r0.ru_utime) + (r1.ru_stime - r0.ru_stime)
    value = n / wall
    bound = disk_mbps * 1e6 / bytes_per_example  # ex/s if purely IO-bound
    # The raw-disk bound is unreachable when decode CPU alone exceeds the
    # disk's per-example time budget (a 1-core host decoding at ~0.8us/ex
    # cannot ingest a >2 GB/s stream at ~0.3us/ex). The corrected bound is
    # the binding constraint: min(disk rate, this run's measured CPU work
    # rate) — a multi-core host relaxes the CPU term toward the disk bound.
    cpu_bound = n / cpu_s if cpu_s > 0 else None
    eff_bound = min(bound, cpu_bound) if cpu_bound else bound
    return {
        "cold_value": round(value, 1),
        # serial no-hint read rate measured immediately before the pass
        "cold_disk_mbps": round(disk_mbps, 1),
        # that rate expressed in ex/s: the raw-disk bound cold_value reads
        # against (>1.0 cold_vs_disk_bound = the engineered path beat the
        # serial-read bound via readahead/overlap; <1.0 = decode-bound or
        # the store sped up/slowed down between the two measurements)
        "cold_disk_bound_value": round(bound, 1),
        "cold_vs_disk_bound": round(value / bound, 3) if bound else None,
        "cold_cpu_bound_value": round(cpu_bound, 1) if cpu_bound else None,
        "cold_vs_bound": round(value / eff_bound, 3) if eff_bound else None,
        "cold_stage_s": {
            "wall": round(wall, 3),
            "cpu": round(cpu_s, 3),
            "decode_workers": round(decode_s, 3),
            "consumer_wait": round(wait_s, 3),
            "consumer_pack": round(pack_s, 3),
        },
        "cold_majflt": r1.ru_majflt - r0.ru_majflt,
        "cold_wire_bytes_per_example": round(bytes_per_example, 1),
        "cold_workers": workers,
        "cold_readahead_mb": readahead >> 20,
    }


def _stall_guard_overhead(data_dir, schema, hash_buckets, pack) -> dict:
    """Bench guardrail for the stall-defense layer (ISSUE 3 acceptance:
    fault-free read throughput regresses < 2% with deadlines + watchdog
    enabled): the SAME device-free host loop measured with the guards off
    and on — generous deadlines that never fire, watchdog armed, parallel
    workers so the watchdog actually monitors something — interleaved
    A/B/A/B with best-of-each (this box's one-sided noise estimator, same
    argument as the main attempts loop). Reported as one JSON field:
    ``stall_guard_overhead_pct`` (negative = in the noise)."""
    import statistics

    seconds = float(os.environ.get("TFR_BENCH_STALL_SECONDS", 2.0))
    repeats = int(os.environ.get("TFR_BENCH_STALL_REPEATS", 3))
    guarded_kw = dict(
        read_deadline_ms=60_000.0,
        open_deadline_ms=60_000.0,
        watchdog_timeout_ms=60_000.0,
        num_workers=2,
    )
    base_kw = dict(num_workers=2)

    def run(kw):
        return _host_side_throughput(
            data_dir, schema, hash_buckets, pack, seconds=seconds, **kw
        )

    # Interleaved rounds, alternating B/G then G/B so drift in the shared
    # box's load hits both sides equally. Interference here is strictly
    # one-sided (other tenants only SLOW a run down), so the overhead
    # estimate compares the BEST of each side — the same min-of-repeats
    # argument the main attempts loop documents; the per-round paired
    # ratios are disclosed so a reader can see the noise floor (single
    # pairs swing +-5% on this box, far above the true overhead).
    base, guarded, pair_pct = [], [], []
    for r in range(repeats):
        if r % 2 == 0:
            b, g = run(base_kw), run(guarded_kw)
        else:
            g, b = run(guarded_kw), run(base_kw)
        base.append(b)
        guarded.append(g)
        pair_pct.append((1.0 - g / b) * 100.0)
    best_b, best_g = max(base), max(guarded)
    return {
        "stall_guard_baseline_eps": round(best_b, 1),
        "stall_guard_enabled_eps": round(best_g, 1),
        "stall_guard_overhead_pct": round((1.0 - best_g / best_b) * 100.0, 2),
        "stall_guard_pair_median_pct": round(statistics.median(pair_pct), 2),
        "stall_guard_pair_pcts": [round(p, 2) for p in pair_pct],
    }


def _tracing_overhead(data_dir, schema, hash_buckets, pack) -> dict:
    """Bench guardrail for the flight recorder (ISSUE 5 acceptance:
    ``trace="on"`` costs <= 2%, ``trace="off"`` is within noise of the
    pre-PR baseline): the SAME device-free host loop measured with tracing
    off and on, interleaved A/B with best-of-each (the box's one-sided
    noise estimator — same argument as the stall-guard probe). The traced
    runs also produce the ``telemetry`` block: per-stage latency quantiles
    from the always-on histograms plus the bound-ness verdict from the
    prefetch-occupancy gauge."""
    import statistics

    from tpu_tfrecord import telemetry as tm
    from tpu_tfrecord.metrics import METRICS

    seconds = float(os.environ.get("TFR_BENCH_TRACE_SECONDS", 2.0))
    repeats = int(os.environ.get("TFR_BENCH_TRACE_REPEATS", 3))
    # the earlier phases (cold pass, stall probe, warm-cache epochs) ran
    # under different configurations; their histogram observations would
    # blend into the reported quantiles, so the probe starts clean (every
    # later bench phase captures its own baselines, none reads cumulative
    # pre-probe state)
    METRICS.reset()

    def run(traced: bool):
        # the recorder is process-global: force the state per run (a
        # trace="off" dataset deliberately does not disable it)
        if traced:
            tm.RECORDER.clear()
            tm.enable()
        else:
            tm.disable()
        try:
            return _host_side_throughput(
                data_dir, schema, hash_buckets, pack, seconds=seconds,
                **({"trace": "on"} if traced else {}),
            )
        finally:
            tm.disable()

    base, traced, pair_pct = [], [], []
    for r in range(repeats):
        if r % 2 == 0:
            b, g = run(False), run(True)
        else:
            g, b = run(True), run(False)
        base.append(b)
        traced.append(g)
        pair_pct.append((1.0 - g / b) * 100.0)
    best_b, best_g = max(base), max(traced)

    # cluster-spool arm (ISSUE 7, same <=2% bar): the identical loop with
    # TRACE on AND the telemetry spool ticking into a scratch dir — the
    # full fleet-observed configuration a disaggregated worker would run
    # with. One interleaved pair against a fresh baseline (the spool is a
    # 1 Hz daemon-thread JSONL rewrite; it either costs ~nothing or the
    # number says so).
    import shutil
    import tempfile

    from tpu_tfrecord import fleet

    spool_dir = tempfile.mkdtemp(prefix="tfr_bench_spool_")
    try:

        def run_spooled():
            tm.RECORDER.clear()
            tm.enable()
            try:
                return _host_side_throughput(
                    data_dir, schema, hash_buckets, pack, seconds=seconds,
                    trace="on", telemetry_spool_dir=spool_dir,
                    telemetry_role="bench",
                )
            finally:
                tm.disable()

        # interleaved A/B, best-of-each — the same one-sided noise
        # estimator as the trace arm above
        b0, s0 = run(False), run_spooled()
        # the second spooled run's spool object rewrites the (same-pid)
        # spool file from scratch, so the aggregator only ever sees ITS
        # lines — count the writes over the same window so the two
        # corroborating fields below agree
        writes_before_s1 = METRICS.counter("fleet.spool_writes")
        s1, b1 = run_spooled(), run(False)
        spool_base, spool_on = max(b0, b1), max(s0, s1)
        fleet_snap = fleet.TelemetryAggregator(spool_dir).aggregate()
        spool_info = {
            "spool_baseline_eps": round(spool_base, 1),
            "spool_enabled_eps": round(spool_on, 1),
            "spool_overhead_pct": round(
                (1.0 - spool_on / spool_base) * 100.0, 2
            ),
            "spool_snapshots": sum(p.seq for p in fleet_snap.processes),
            "spool_writes_counted": METRICS.counter("fleet.spool_writes")
            - writes_before_s1,
        }
    finally:
        shutil.rmtree(spool_dir, ignore_errors=True)

    quantiles = tm.quantiles_ms(METRICS.quantiles())
    occ = METRICS.gauge_value(tm.OCCUPANCY_GAUGE)
    ctx = tm.current_context()
    out = {
        "tracing_baseline_eps": round(best_b, 1),
        "tracing_enabled_eps": round(best_g, 1),
        "tracing_overhead_pct": round((1.0 - best_g / best_b) * 100.0, 2),
        "tracing_pair_median_pct": round(statistics.median(pair_pct), 2),
        "tracing_pair_pcts": [round(p, 2) for p in pair_pct],
        **spool_info,
        "telemetry": {
            "quantiles": quantiles,
            "prefetch_occupancy": round(occ, 4) if occ is not None else None,
            "verdict": tm.boundness_verdict(occ),
            "spans_recorded": len(tm.RECORDER),
            "spans_dropped": tm.RECORDER.dropped,
            # identity stamp: correlates this artifact with pulse lines,
            # spool snapshots, and merged traces from the same run
            "proc": {"host": ctx.host, "pid": ctx.pid, "role": ctx.role,
                     "trace_id": ctx.trace_id},
        },
    }
    tm.RECORDER.clear()
    return out


def _warm_epoch_throughput(data_dir, schema, hash_buckets, pack) -> dict:
    """Columnar epoch cache (ISSUE 4): populate the cache with one full
    pass (decode + cache append), then measure the mmap-served warm-epoch
    rate with the SAME device-free loop host_side_value uses — so
    warm_epoch_value / host_side_value is the cache's speedup over the
    decode-bound path on this box (acceptance bar: >= 1.5x). The populate
    pass rate is disclosed too (it pays decode + cache-file writes)."""
    import shutil
    import tempfile

    from tpu_tfrecord.metrics import METRICS

    cache_dir = tempfile.mkdtemp(prefix="tfr_bench_cache_")
    kw = dict(cache="auto", cache_dir=cache_dir)
    try:
        b0 = METRICS.counter("cache.bytes_written")
        ds = _make_dataset(data_dir, schema, hash_buckets, pack, num_epochs=1, **kw)
        t0 = time.perf_counter()
        n = 0
        with ds.batches() as it:
            for cb in it:
                n += cb.num_rows
        populate_eps = n / (time.perf_counter() - t0)
        h0 = METRICS.counter("cache.hits")
        c0 = METRICS.counter("cache.corrupt_fallbacks")
        value = _host_side_throughput(
            data_dir, schema, hash_buckets, pack,
            seconds=float(os.environ.get("TFR_BENCH_WARM_SECONDS", 3.0)), **kw,
        )
        return {
            # cache-served epoch: decode replaced by mmap views + hash/pack
            "warm_epoch_value": round(value, 1),
            # the one-time population pass (decode + cache append)
            "warm_populate_value": round(populate_eps, 1),
            "warm_cache_hits": METRICS.counter("cache.hits") - h0,
            "warm_cache_corrupt_fallbacks": METRICS.counter("cache.corrupt_fallbacks") - c0,
            "warm_cache_bytes_written": METRICS.counter("cache.bytes_written") - b0,
        }
    finally:
        # unpin the probe entries' mmaps BEFORE deleting the dir, or the
        # deleted inodes' blocks stay allocated for the rest of the run
        from tpu_tfrecord.cache import release_registry

        release_registry(cache_dir)
        shutil.rmtree(cache_dir, ignore_errors=True)


# SEQ_* are env-overridable like the Criteo knobs; ensure_seq_dataset keys
# its cache directory on all four generation parameters, so changing any
# of them regenerates instead of silently benchmarking stale data
# (ADVICE: seq bench cache key).
SEQ_SHARDS = int(os.environ.get("TFR_BENCH_SEQ_SHARDS", 2))
SEQ_DOCS_PER_SHARD = int(os.environ.get("TFR_BENCH_SEQ_DOCS", 4096))
SEQ_MAX_LEN = int(os.environ.get("TFR_BENCH_SEQ_MAX_LEN", 64))
SEQ_DIM = int(os.environ.get("TFR_BENCH_SEQ_DIM", 16))
SEQ_BATCH = int(os.environ.get("TFR_BENCH_SEQ_BATCH", 1024))


def _remote_prefetch_probe() -> dict:
    """Disclosed evidence for the remote readahead path (VERDICT r4 item 3):
    stream one object through PrefetchReader over a simulated high-RTT link
    (every range request pays a fixed latency; requests on independent
    handles overlap, like real object-store GETs) vs a serial read loop
    paying one RTT per block. The pipelined rate approaching
    block_size*depth/RTT = the prefetcher saturates the link. Device-free,
    ~2s; memory-backed so no network variance. Correctness (byte equality,
    fault injection) is pinned in tests/test_fs.py — this records the
    NUMBER next to the headline."""
    try:
        import fsspec  # noqa: F401
    except ImportError:
        return {"remote_skipped": "fsspec unavailable"}
    import threading

    from tpu_tfrecord import fs as tfs

    rtt_s = float(os.environ.get("TFR_BENCH_REMOTE_RTT_S", 0.02))
    block = int(os.environ.get("TFR_BENCH_REMOTE_BLOCK", 2 << 20))
    depth = int(os.environ.get("TFR_BENCH_REMOTE_DEPTH", 4))
    nbytes = 32 << 20
    path = "memory://tfr-bench/remote.bin"
    fsys = tfs.filesystem_for(path)
    payload = np.random.default_rng(3).integers(0, 256, nbytes, np.uint8)
    with fsys.open(path, "wb") as fh:
        fh.write(payload.tobytes())

    io_lock = threading.Lock()

    class _LinkFile:
        def __init__(self, inner):
            self._inner = inner
            self._pos = 0

        def seek(self, pos, whence=0):
            self._pos = pos

        def read(self, size=-1):
            time.sleep(rtt_s)  # per-request RTT, outside the lock
            with io_lock:  # memory:// shares one cursor across handles
                self._inner.seek(self._pos)
                data = self._inner.read(size)
            self._pos += len(data)
            return data

        def close(self):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            pass

    class _LinkFS:
        protocol = "simlink"  # independent handles: no serialization needed

        def __init__(self, fs):
            self._fs = fs

        def open(self, p, mode):
            # under io_lock: memory://'s _open seeks the SHARED file object
            # to 0, which must not interleave with another handle's
            # locked seek+read
            with io_lock:
                return _LinkFile(self._fs.open(p, mode))

        def __getattr__(self, name):
            return getattr(self._fs, name)

    link = _LinkFS(fsys)

    def drain_serial() -> float:
        # loop the KNOWN block count: a read-until-empty loop would pay one
        # extra RTT for the EOF probe that the pipelined path never issues,
        # biasing the speedup upward (~1/nblocks)
        t0 = time.perf_counter()
        with link.open(path, "rb") as fh:
            for _ in range((nbytes + block - 1) // block):
                fh.read(block)
        return nbytes / (time.perf_counter() - t0) / 1e6

    def drain_pipelined() -> float:
        t0 = time.perf_counter()
        with tfs.PrefetchReader(link, path, nbytes, block, depth) as fh:
            while fh.read(block):
                pass
        return nbytes / (time.perf_counter() - t0) / 1e6

    serial_mbps = drain_serial()
    pipe_mbps = drain_pipelined()
    fsys.remove(path)
    return {
        # simulated-link streaming rates (MB/s) and the pipelining win;
        # link ceiling = block*depth/RTT, serial floor = block/RTT
        "remote_sim_rtt_ms": rtt_s * 1e3,
        "remote_sim_serial_mbps": round(serial_mbps, 1),
        "remote_sim_pipelined_mbps": round(pipe_mbps, 1),
        "remote_sim_speedup": round(pipe_mbps / serial_mbps, 2),
        "remote_sim_link_ceiling_mbps": round(block * depth / rtt_s / 1e6, 1),
        "remote_prefetch_depth": depth,
    }


def _remote_http_probe() -> dict:
    """Real-network remote evidence (ISSUE 9 / ROADMAP #3): the depth
    sweep and the remote->cache->mmap number over a REAL threaded HTTP
    backend — genuinely independent TCP connections per block fetch, with
    a fixed server-side per-request latency as the simulated link RTT
    (the sim-link probe above plateaued at 76% of the depth-4 ceiling;
    this finds the knee on real sockets).

    - ``remote_http_depth_sweep``: MB/s streaming one object through
      PrefetchReader at depth 1/2/4/8; ``remote_http_knee_depth`` is the
      smallest depth within 85% of the best rate (the knee DISCLOSED,
      not assumed).
    - ``remote_http_cold_value`` / ``remote_http_cached_value``: ex/s of
      a full epoch over HTTP populating the columnar cache, then the
      same epoch served from the mmap cache (zero file GETs — the link
      paid once); ``remote_cold_vs_cached`` is the ratio.

    Device-free, runs pre-backend-init, so a dead TPU tunnel still
    certifies it.
    """
    import shutil
    import tempfile

    import tpu_tfrecord.io as tfio
    from tpu_tfrecord import fs as tfs, httpfs
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.metrics import METRICS
    from tpu_tfrecord.schema import (
        LongType, StringType, StructField, StructType,
    )

    rtt_s = float(os.environ.get("TFR_BENCH_HTTP_RTT_S", 0.008))
    block = int(os.environ.get("TFR_BENCH_HTTP_BLOCK", 1 << 20))
    nbytes = int(os.environ.get("TFR_BENCH_HTTP_BYTES", 16 << 20))
    depths = [1, 2, 4, 8]
    root = tempfile.mkdtemp(prefix="tfr_bench_http_")
    try:
        payload = np.random.default_rng(9).integers(0, 256, nbytes, np.uint8)
        with open(os.path.join(root, "sweep.bin"), "wb") as fh:
            fh.write(payload.tobytes())
        schema = StructType([
            StructField("id", LongType(), nullable=False),
            StructField("s", StringType()),
        ])
        ds_dir = os.path.join(root, "ds")
        n_rows = int(os.environ.get("TFR_BENCH_HTTP_ROWS", 120_000))
        per = n_rows // 4
        for s in range(4):
            tfio.write(
                [[i, f"v{i % 97}"] for i in range(s * per, (s + 1) * per)],
                schema, ds_dir, mode="append" if s else "overwrite",
            )
        with httpfs.serve_directory(root, latency_s=rtt_s) as srv:
            sweep_url = srv.url_for("sweep.bin")
            fsys = tfs.filesystem_for(sweep_url)
            sweep = {}
            saved = {
                k: os.environ.get(k)
                for k in ("TFR_REMOTE_BLOCK_BYTES", "TFR_REMOTE_PREFETCH_DEPTH")
            }
            try:
                os.environ["TFR_REMOTE_BLOCK_BYTES"] = str(block)
                for depth in depths:
                    os.environ["TFR_REMOTE_PREFETCH_DEPTH"] = str(depth)
                    t0 = time.perf_counter()
                    with tfs.open_for_read(fsys, sweep_url) as fh:
                        while fh.read(block):
                            pass
                    sweep[str(depth)] = round(
                        nbytes / (time.perf_counter() - t0) / 1e6, 1
                    )
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            best = max(sweep.values())
            knee = next(
                d for d in depths if sweep[str(d)] >= 0.85 * best
            )

            def epoch_ex_s(**kw):
                ds = TFRecordDataset(
                    srv.url_for("ds"), batch_size=4096, schema=schema,
                    drop_remainder=False, **kw,
                )
                t0 = time.perf_counter()
                rows = 0
                with ds.batches() as it:
                    for cb in it:
                        rows += cb.num_rows
                return rows / (time.perf_counter() - t0)

            cache_dir = os.path.join(root, "cache")
            srv.set_latency(0.0)  # rate the pipeline, not the injected RTT
            hits0 = METRICS.counter("cache.hits")
            cold = epoch_ex_s(cache="auto", cache_dir=cache_dir)
            gets_cold = srv.file_get_count
            cached = epoch_ex_s(cache="auto", cache_dir=cache_dir)
            link_repaid = srv.file_get_count - gets_cold
            hits = METRICS.counter("cache.hits") - hits0
        return {
            # real-socket streaming rates per prefetch depth (MB/s at
            # rtt_ms of injected server latency) and the disclosed knee
            "remote_http_rtt_ms": rtt_s * 1e3,
            "remote_http_depth_sweep": sweep,
            "remote_http_knee_depth": knee,
            "remote_http_pipelined_mbps": best,
            # remote -> CachePopulator -> mmap, end to end: one epoch
            # paying the link + populating, then the same epoch from the
            # cache (file GETs during it disclosed — 0 = link paid once)
            "remote_http_cold_value": round(cold, 1),
            "remote_http_cached_value": round(cached, 1),
            "remote_cold_vs_cached": round(cached / cold, 2) if cold else None,
            "remote_http_cached_refetches": link_repaid,
            "remote_http_cache_hits": hits,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def seq_schema():
    from tpu_tfrecord.schema import (
        ArrayType, FloatType, LongType, StructField, StructType,
    )

    return StructType([
        StructField("label", LongType(), nullable=False),
        StructField("frames", ArrayType(ArrayType(FloatType()))),
    ])


def ensure_seq_dataset(data_dir: str) -> str:
    """Ragged SequenceExample dataset (long-doc shape: variable-length
    frame lists of SEQ_DIM floats); generated once and cached. The cache
    key includes the SEQ_* generation parameters — changing them must
    regenerate, not silently benchmark stale data of the wrong shape."""
    data_dir = os.path.join(
        data_dir,
        f"s{SEQ_SHARDS}d{SEQ_DOCS_PER_SHARD}l{SEQ_MAX_LEN}f{SEQ_DIM}",
    )
    if os.path.exists(os.path.join(data_dir, "_SUCCESS")):
        return data_dir
    from tpu_tfrecord.io.writer import DatasetWriter
    from tpu_tfrecord.options import TFRecordOptions

    rng = np.random.default_rng(7)
    rows = []
    for _ in range(SEQ_SHARDS * SEQ_DOCS_PER_SHARD):
        n = int(rng.integers(8, SEQ_MAX_LEN + 1))
        frames = rng.normal(size=(n, SEQ_DIM)).astype(np.float32)
        rows.append([int(n), [row.tolist() for row in frames]])
    writer = DatasetWriter(
        data_dir,
        seq_schema(),
        TFRecordOptions.from_map(recordType="SequenceExample"),
        mode="overwrite",
        max_records_per_file=SEQ_DOCS_PER_SHARD,
    )
    writer.write_rows(rows)
    return data_dir


def _seq_pipeline():
    """Dataset + host-side produce fn for the ragged² SequenceExample leg
    (decode 2-level FeatureLists, pad/bucket to dense [B, Lo, Li], cast
    frames to bfloat16 — fused in the native kernel, so the dense f32
    batch never materializes host-side). Shared by the device-free host
    leg and the device leg."""
    import ml_dtypes
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.tpu import host_batch_from_columnar

    data_dir = ensure_seq_dataset(
        os.environ.get("TFR_BENCH_SEQ_DIR", "/tmp/tpu_tfrecord_bench_seq")
    )
    ds = TFRecordDataset(
        data_dir,
        batch_size=SEQ_BATCH,
        schema=seq_schema(),
        prefetch=4,
        num_epochs=None,
        recordType="SequenceExample",
    )
    pad_to = {"frames": (SEQ_MAX_LEN, SEQ_DIM)}
    cast = {"frames": ml_dtypes.bfloat16}

    def produce(cb):
        hb = host_batch_from_columnar(cb, ds.schema, pad_to=pad_to, cast=cast)
        return {
            "frames": hb["frames"],
            "frames_len": hb["frames_len"],
            "label": hb["label"],
        }

    return ds, produce


def _seq_host_throughput(seconds=2.0) -> dict:
    """Device-free seq leg: decode+pad+bf16 rate with no device anywhere.
    Runs BEFORE backend init (ROADMAP #5: two of five rounds lost ALL host
    evidence to a dead TPU tunnel because this measurement sat behind
    jax.devices()) — so ``seq_host_value`` lands in the artifact on every
    run, rc=3 included."""
    ds, produce = _seq_pipeline()
    with ds.batches() as it:
        for _ in range(2):
            produce(next(it))
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < seconds:
            produce(next(it))
            n += SEQ_BATCH
        value = n / (time.perf_counter() - t0)
    return {
        "seq_host_value": round(value, 1),
        "seq_shape": f"[{SEQ_BATCH}, {SEQ_MAX_LEN}, {SEQ_DIM}] ragged->padded",
        "seq_frames_dtype": "bfloat16",
    }


def _seq_device_throughput(mesh, sharding_3d, seconds=4.0) -> dict:
    """Secondary disclosed metric (verdict r3): the ragged² SequenceExample
    path end-to-end — decode, pad, bf16, transfer to the mesh, block.
    Reported as seq_value so the long-doc path's throughput is tracked
    round over round, not just unit-tested. (The device-free half of this
    leg is ``_seq_host_throughput``, measured pre-backend.)"""
    import jax

    from tpu_tfrecord.tpu import data_sharding

    ds, produce = _seq_pipeline()
    sharding_1d = data_sharding(mesh, ndim=1)
    with ds.batches() as it:

        def put(hb):
            gb = {
                "frames": jax.device_put(hb["frames"], sharding_3d),
                "frames_len": jax.device_put(hb["frames_len"], sharding_1d),
                "label": jax.device_put(hb["label"], sharding_1d),
            }
            jax.block_until_ready(gb)

        for _ in range(2):
            put(produce(next(it)))
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < seconds:
            put(produce(next(it)))
            n += SEQ_BATCH
        value = n / (time.perf_counter() - t0)
    per_ex = SEQ_MAX_LEN * SEQ_DIM * 2 + 8 + 4  # bf16 frames + i64 + i32
    return {
        "seq_value": round(value, 1),
        "seq_link_bytes_per_example": per_ex,
    }


def _autotune_probe(data_dir, schema, hash_buckets, pack) -> dict:
    """Closed-loop autotune convergence (ISSUE 6 acceptance): the SAME
    device-free host loop measured (a) with HAND-TUNED fixed knobs
    (workers=2/prefetch=4 on this 2-vCPU box; override with
    TFR_BENCH_AUTOTUNE_FIXED_WORKERS) and (b) starting from
    deliberately-wrong knobs (workers=1, prefetch=1) with
    ``autotune="on"``, where the controller must climb back at pulse
    boundaries. Reports the convergence trajectory (the controller's
    decision log), the final knob set, and autotune_vs_fixed. Both runs
    share the box state, and the registry is RESET between them — the
    metrics quantiles are process-global and cumulative, so without the
    reset the controller would derive thresholds from the fixed leg's
    (and earlier bench phases') latency regimes instead of its own."""
    from tpu_tfrecord.metrics import METRICS
    from tpu_tfrecord.tpu import host_batch_from_columnar

    seconds = float(os.environ.get("TFR_BENCH_AUTOTUNE_SECONDS", 4.0))
    interval = float(os.environ.get("TFR_BENCH_AUTOTUNE_INTERVAL", 0.25))
    fixed_workers = int(os.environ.get("TFR_BENCH_AUTOTUNE_FIXED_WORKERS", 2))
    fixed = _host_side_throughput(
        data_dir, schema, hash_buckets, pack, seconds=seconds,
        num_workers=fixed_workers,
    )
    METRICS.reset()
    ds = _make_dataset(
        data_dir, schema, hash_buckets, pack,
        num_epochs=None, num_workers=1,
        autotune="on", autotune_interval_s=interval,
    )
    ds.prefetch = 1  # deliberately-wrong starting depth (ctor set 4)
    it = ds.batches()
    try:
        for _ in range(2):
            host_batch_from_columnar(
                next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
            )
        t0 = time.perf_counter()
        n = 0
        marks = []  # (elapsed, rows) after each batch: convergence evidence
        while time.perf_counter() - t0 < seconds:
            hb = host_batch_from_columnar(
                next(it), ds.schema, hash_buckets=hash_buckets, pack=pack
            )
            n += hb["packed"].shape[0]
            marks.append((time.perf_counter() - t0, n))
        tuned = n / (time.perf_counter() - t0)
        # converged rate: the tail half of the window — the head pays the
        # deliberate mis-configuration plus the controller's climb, which
        # the trajectory discloses; vs_fixed judges the CONVERGED regime
        half = seconds / 2.0
        head = next(((t, r) for t, r in marks if t >= half), None)
        tail_end = marks[-1] if marks else None
        converged = (
            (tail_end[1] - head[1]) / (tail_end[0] - head[0])
            if head and tail_end and tail_end[0] > head[0]
            else tuned
        )
        tuner = it.autotune
        return {
            "autotune": {
                "fixed_eps": round(fixed, 1),
                "autotune_eps": round(tuned, 1),
                "autotune_converged_eps": round(converged, 1),
                "vs_fixed": round(converged / fixed, 3) if fixed else None,
                "fixed_knobs": {"workers": fixed_workers, "prefetch": 4},
                "start_knobs": {"workers": 1, "prefetch": 1},
                "final_knobs": tuner.snapshot(),
                "trajectory": tuner.log[:64],
                "interval_s": interval,
            }
        }
    finally:
        it.close()


def _service_probe(data_dir, schema, hash_buckets, pack) -> dict:
    """Disaggregated data service leg (ISSUE 8): K decode-worker
    SUBPROCESSES (real processes — the consumer's GIL never pays for
    decode) leased by an in-process dispatcher feed ONE consumer running
    the SAME device-free host loop as host_side_value, so
    service_value / host_side_value reads directly as "what does moving
    decode off-host cost/buy on this box". Device-free by construction:
    runs in the pre-backend-init block, so a dead TPU tunnel still
    certifies the service path. Workers inherit K from
    TFR_BENCH_SERVICE_WORKERS (default 2)."""
    import subprocess
    import sys as _sys

    from tpu_tfrecord import service
    from tpu_tfrecord.metrics import METRICS

    seconds = float(os.environ.get("TFR_BENCH_SERVICE_SECONDS", 4.0))
    n_workers = int(os.environ.get("TFR_BENCH_SERVICE_WORKERS", 2))
    d = service.ServiceDispatcher(lease_ttl_s=10.0).start()
    procs = []
    try:
        for _ in range(n_workers):
            procs.append(subprocess.Popen(
                [_sys.executable, "-m", "tpu_tfrecord.service", "worker",
                 "--dispatcher", d.addr],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ))
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            if len(d.status()["workers"]) >= n_workers:
                break
            time.sleep(0.05)
        registered = len(d.status()["workers"])
        before = METRICS.counter("service.fallbacks")
        value = _host_side_throughput(
            data_dir, schema, hash_buckets, pack, seconds=seconds,
            service=d.addr,
        )
        fallbacks = METRICS.counter("service.fallbacks") - before
        return {
            "service_value": round(value, 1),
            "service": {
                "workers": registered,
                "seconds": seconds,
                "fallbacks": fallbacks,  # >0 = some shards read locally:
                # the number above partly measured the fallback, not the
                # service — disclosed, not hidden
            },
        }
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        d.stop()


def _elastic_probe() -> dict:
    """Elastic decode fleet leg (ISSUE 12): worker count vs offered load.
    A dedicated small dataset is served through the data service while
    every worker-side read pays a seeded 10ms injected stall — one worker
    cannot keep the consumer fed, the consumer's spool says
    producer_bound, and the FleetScaler must GROW the fleet; when the
    consumer closes (load removed, its spool lands a final snapshot) the
    verdict goes idle and the scaler must DRAIN back toward the floor.
    Reports ``elastic_value`` (examples/s through the elastic fleet) plus
    the workers-vs-time load table and the scaler's decision trajectory.
    Device-free by construction: runs in the pre-backend-init block, so a
    dead tunnel still certifies the elastic layer."""
    import tempfile

    import tpu_tfrecord.io as tfio
    from tpu_tfrecord import elastic, service
    from tpu_tfrecord.faults import FaultPlan, FaultRule, install_chaos
    from tpu_tfrecord.io.dataset import TFRecordDataset
    from tpu_tfrecord.metrics import METRICS
    from tpu_tfrecord.schema import LongType, StructField, StructType

    seconds = float(os.environ.get("TFR_BENCH_ELASTIC_SECONDS", 6.0))
    root = tempfile.mkdtemp(prefix="tfr_bench_elastic_")
    out_dir = os.path.join(root, "ds")
    schema = StructType([StructField("id", LongType(), nullable=False)])
    for s in range(6):
        tfio.write([[i] for i in range(s * 2000, (s + 1) * 2000)], schema,
                   out_dir, mode="append" if s else "overwrite")
    spool = os.path.join(root, "spool")
    ups0 = METRICS.counter("elastic.scale_ups")
    downs0 = METRICS.counter("elastic.scale_downs")
    drains0 = METRICS.counter("elastic.drains")
    d = service.ServiceDispatcher(lease_ttl_s=2.0).start()
    workers = []

    def spawn():
        workers.append(
            service.DecodeWorker(d.addr, drain_grace_s=0.2).start()
        )

    scaler = elastic.FleetScaler(
        d, spawn, spool_dir=spool,
        policy=elastic.ScalerPolicy(
            hysteresis=2, cooldown_s=0.5, min_workers=1, max_workers=3
        ),
        interval_s=0.25,
    ).start()
    plan = FaultPlan(
        [FaultRule(op="read", kind="stall", path="part-", times=None,
                   stall_ms=10)],
        seed=5,
    )
    samples = []  # (elapsed_s, active_workers): the load table
    n = 0
    try:
        with install_chaos(plan):
            ds = TFRecordDataset(
                out_dir, batch_size=256, schema=schema, num_epochs=None,
                service=d.addr, service_deadline_ms=15000,
                telemetry_spool_dir=spool, spool_interval_s=0.1,
            )
            t0 = time.perf_counter()
            with ds.batches() as it:
                for b in it:
                    n += b.num_rows
                    el = time.perf_counter() - t0
                    if not samples or el - samples[-1][0] >= 0.5:
                        samples.append((
                            round(el, 2),
                            int(METRICS.gauge_value("elastic.workers", 1) or 1),
                        ))
                    if el >= seconds:
                        break
            value = n / (time.perf_counter() - t0)
        plan.release()
        peak = max((w for _t, w in samples), default=1)
        # load removed: the consumer's spool said goodbye (final), the
        # verdict goes idle, and the fleet must shrink toward the floor
        deadline = time.perf_counter() + 10.0
        after = peak
        while time.perf_counter() < deadline:
            st = d.status()
            after = sum(
                1 for w in st["workers"]
                if w["alive"] and not w["draining"]
            )
            if after <= 1:
                break
            time.sleep(0.2)
        return {
            "elastic_value": round(value, 1),
            "elastic": {
                "seconds": seconds,
                "workers_start": 1,
                "workers_peak": peak,
                "workers_after_load_removed": after,
                "scale_ups": METRICS.counter("elastic.scale_ups") - ups0,
                "scale_downs": METRICS.counter("elastic.scale_downs") - downs0,
                "drains_completed": METRICS.counter("elastic.drains") - drains0,
                "load_table": samples,
                "trajectory": scaler.log[:32],
            },
        }
    finally:
        scaler.stop()
        for w in workers:
            w.stop()
        d.stop()


def _lease_throughput_probe() -> dict:
    """Aggregate lease throughput vs partition count K (ISSUE 17): the
    scale half of killing the dispatcher SPOF. K journaled dispatcher
    SUBPROCESSES (real process parallelism — the probe measures the
    service tier, not this process's GIL), one registered worker each,
    and a fixed pool of hammer threads driving route + shard_done pairs
    over persistent sockets — each thread a distinct tenant routed by
    the same ``PartitionMap`` consumers use, every pair two fsynced
    journal appends (the mutation path as deployed). Reports ops/s at
    K=1 and K=2 and whether aggregate throughput grew. Device-free:
    runs in the pre-backend block."""
    import subprocess
    import tempfile
    import threading

    from tpu_tfrecord import service
    from tpu_tfrecord import service_protocol as sp

    seconds = float(os.environ.get("TFR_BENCH_LEASE_SECONDS", 2.0))
    procs_n = int(os.environ.get("TFR_BENCH_LEASE_PROCS", 4))
    threads_n = int(os.environ.get("TFR_BENCH_LEASE_THREADS", 8))
    root = tempfile.mkdtemp(prefix="tfr_bench_lease_")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    pkg_parent = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = (
        pkg_parent + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else pkg_parent
    )

    # hammer CLIENTS are subprocesses too — client-side GIL must not be
    # what one measures when asking whether the SERVICE tier scales.
    # Each runs threads_n synchronous route+shard_done loops, one tenant
    # per thread, routed by the same PartitionMap consumers use, and
    # prints its completed-pair count.
    hammer_src = """
import json, sys, threading, time
from tpu_tfrecord import service
from tpu_tfrecord import service_protocol as sp

spec, proc_i, threads_n, start_at, stop_at = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
    float(sys.argv[4]), float(sys.argv[5]),
)
pmap = service.PartitionMap.parse(spec)
counts = [0] * threads_n

def hammer(ti):
    tenant = f"bench-tenant-{proc_i}-{ti}"
    addr = pmap.addrs(pmap.partition_for(tenant))[0]
    s = sp.connect(addr, timeout=10.0)
    try:
        s.settimeout(10.0)
        # sockets up, imports paid: wait for the fleet-wide start line so
        # interpreter startup never dilutes the measured window
        while time.time() < start_at:
            time.sleep(0.005)
        i = 0
        while time.time() < stop_at:
            path = f"/bench/{proc_i}/{ti}/shard-{i:06d}"
            base = {"proto": service.PROTO_VERSION, "tenant": tenant,
                    "job": tenant, "consumer": tenant, "path": path}
            r = sp.request(s, addr, {"op": "route", "shard_index": i,
                                     **base})
            if r.get("ok"):
                sp.request(s, addr, {"op": "shard_done",
                                     "worker_id": r["worker_id"], **base})
                counts[ti] += 1
            i += 1
    finally:
        s.close()

ths = [threading.Thread(target=hammer, args=(ti,))
       for ti in range(threads_n)]
for t in ths:
    t.start()
for t in ths:
    t.join()
print(json.dumps({"pairs": sum(counts)}), flush=True)
"""

    def run_k(k: int) -> float:
        procs = []
        addrs = []
        try:
            for i in range(k):
                p = subprocess.Popen(
                    [sys.executable, "-m", "tpu_tfrecord.service",
                     "dispatcher", "--partition", str(i), "--journal",
                     os.path.join(root, f"journal-k{k}-p{i}.json")],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, env=env,
                )
                procs.append(p)
                ready = json.loads(p.stdout.readline())
                addrs.append(ready["addr"])
            spec = ",".join(addrs)
            for a in addrs:
                # one registered (never-fetched-from) worker per
                # partition so routes have something to grant
                s = sp.connect(a, timeout=5.0)
                try:
                    s.settimeout(5.0)
                    sp.request(s, a, {"op": "register_worker",
                                      "proto": service.PROTO_VERSION,
                                      "worker_id": f"bench-{a}",
                                      "addr": a, "pid": 0})
                finally:
                    s.close()
            # start line 2s out: every child is connected and waiting
            # before the window opens, so startup cost is outside it
            start_at = time.time() + 2.0
            stop_at = start_at + seconds
            hammers = [
                subprocess.Popen(
                    [sys.executable, "-c", hammer_src, spec, str(pi),
                     str(threads_n), str(start_at), str(stop_at)],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True, env=env,
                )
                for pi in range(procs_n)
            ]
            pairs = 0
            for h in hammers:
                out, _ = h.communicate(timeout=seconds * 10 + 30)
                pairs += json.loads(out)["pairs"]
            return pairs / seconds if seconds > 0 else 0.0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=5.0)
                except Exception:  # noqa: BLE001 — shutdown safety net
                    p.kill()

    k1 = run_k(1)
    k2 = run_k(2)
    return {
        "lease_throughput_vs_k": {
            "client_procs": procs_n,
            "threads_per_proc": threads_n,
            "seconds": seconds,
            "k1_ops_s": round(k1, 1),
            "k2_ops_s": round(k2, 1),
            "speedup": round(k2 / k1, 3) if k1 else None,
            "grows": k2 > k1,
        }
    }


def _decode_scaling_trend(data_dir, schema, hash_buckets, pack) -> dict:
    """Workers -> ex/s sweep, committed to PARITY.md every round (ROADMAP
    #1 / VERDICT #8): one round's scaling sample is an anecdote; the
    appended table is the TREND multi-core extrapolations need. Each
    point is the same device-free host loop host_side_value uses, at
    num_workers = 1/2/4. Runs pre-backend."""
    secs = float(os.environ.get("TFR_BENCH_SCALING_SECONDS", 1.5))
    series = {}
    for w in (1, 2, 4):
        series[w] = round(_host_side_throughput(
            data_dir, schema, hash_buckets, pack, seconds=secs,
            num_workers=w,
        ), 1)
    try:
        _append_parity_scaling_row(series)
    except Exception as e:  # noqa: BLE001 — a malformed/hand-edited
        # PARITY.md must cost the trend row, never the bench artifact
        print(f"bench: PARITY.md decode-scaling append failed: {e}",
              file=sys.stderr, flush=True)
    return {"decode_scaling_ex_s": {str(k): v for k, v in series.items()}}


_PARITY_SCALING_HEADER = "## Decode-scaling trend (bench-appended)"


def _append_parity_scaling_row(series: dict, path: Optional[str] = None) -> None:
    """Append one round's workers->ex/s row under the trend table in
    PARITY.md (creating the section on first use). Rows are inserted at
    the end of the section, before any later section. ``path`` overrides
    the repo PARITY.md (test seam)."""
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    parity = path or os.path.join(here, "PARITY.md")
    rounds = [
        int(m.group(1))
        for name in os.listdir(here)
        for m in [re.match(r"BENCH_r(\d+)\.json$", name)]
        if m
    ]
    label = f"r{(max(rounds) + 1 if rounds else 1):02d}"
    date = time.strftime("%Y-%m-%d")
    row = (
        f"| {label} | {date} | {series[1]:.0f} | {series[2]:.0f} "
        f"| {series[4]:.0f} | {series[2] / series[1]:.2f}x "
        f"| {series[4] / series[1]:.2f}x |"
    )
    with open(parity) as fh:
        content = fh.read()
    if _PARITY_SCALING_HEADER not in content:
        block = (
            f"\n{_PARITY_SCALING_HEADER}\n\n"
            "One row per bench round (appended by `bench.py`, device-free,\n"
            "pre-backend): sustained decode throughput of the Criteo-shaped\n"
            "host loop at num_workers = 1/2/4 on the round's box. On the\n"
            "2-vCPU bench box ratios ~<=1 are the documented contention\n"
            "negative control (PARITY round 7); the trend is what multi-core\n"
            "extrapolations should be anchored to.\n\n"
            "| round | date | 1w ex/s | 2w ex/s | 4w ex/s | 2w/1w | 4w/1w |\n"
            "|---|---|---|---|---|---|---|\n"
            f"{row}\n"
        )
        content = content.rstrip("\n") + "\n" + block
    else:
        head, _, tail = content.partition(_PARITY_SCALING_HEADER)
        # the section runs to the next "## " heading (or EOF); the new
        # row lands right after the LAST table row, so trailing prose
        # (the basis-row footnote) stays below the table
        m = re.search(r"\n## ", tail)
        if m is None:
            section, rest = tail, ""
        else:
            section, rest = tail[: m.start()], tail[m.start():]
        lines = section.split("\n")
        # insert after the last table line of ANY kind — data row, the
        # "|---|" separator, or the header — so a table stripped down to
        # header+separator gets its new row BELOW the separator, never
        # wedged between header and separator
        rows = [i for i, line in enumerate(lines) if line.startswith("|")]
        if rows:
            lines.insert(rows[-1] + 1, row)
        else:
            # header survived a hand edit but the table didn't: rebuild
            # the table head in place rather than dying row-less
            lines.extend([
                "",
                "| round | date | 1w ex/s | 2w ex/s | 4w ex/s | 2w/1w | 4w/1w |",
                "|---|---|---|---|---|---|---|",
                row,
            ])
        content = head + _PARITY_SCALING_HEADER + "\n".join(lines) + rest
    with open(parity, "w") as fh:
        fh.write(content)


def _attach_regression_verdict(out: dict) -> None:
    """vs_previous + the FIRST-CLASS ``regression_verdict`` (ROADMAP #1):
    a banded-field drop is a loud top-level verdict plus a nonzero stderr
    line, never just a buried list a reader has to know to look for.
    Attached on every artifact path — success and both degraded shapes —
    so an rc!=0 round still self-flags."""
    vs_prev = _vs_previous(out)
    if vs_prev is not None:
        out["vs_previous"] = vs_prev
    regressions = (vs_prev or {}).get("regressions") or []
    out["regression_verdict"] = (
        "no_previous" if vs_prev is None
        else ("regression" if regressions else "ok")
    )
    if regressions:
        fields = vs_prev["fields"]
        print(
            "bench REGRESSION vs " + vs_prev["previous_round"] + ": "
            + ", ".join(
                f"{f} {fields[f]['previous']} -> {fields[f]['current']} "
                f"({fields[f]['delta_pct']:+}%)"
                for f in regressions
            ),
            file=sys.stderr, flush=True,
        )


def _model_parallel_child() -> None:
    """Subprocess body (CPU 8-device env forced by the parent): measure the
    model-parallel memory shape + a causal-LM train rate, print ONE JSON
    line. Device-free from the PARENT's point of view — the ambient
    backend (and any dead TPU tunnel) is never touched."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import optax

    from tpu_tfrecord.models import lm, pipeline
    from tpu_tfrecord.tpu import create_mesh

    out = {}
    # --- pipeline memory shape at bench scale: what ONE device holds of
    # the microbatch stream, vs the old replicated-[M, mb, ...] layout
    s_axis, m, mb = 8, 32, (8, 128)
    mesh = create_mesh({"pipe": s_axis})
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(
            rng.normal(size=(s_axis, mb[1], mb[1])) * 0.1, jnp.float32
        )
    }

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    xs = jnp.zeros((m,) + mb, jnp.float32)
    xs_sh = jax.device_put(xs, pipeline.microbatch_sharding(mesh, ndim=xs))
    p_sh = jax.device_put(params, NamedSharding(mesh, P("pipe")))
    comp = (
        jax.jit(lambda p, xs: pipeline.pipeline_apply(stage_fn, p, xs, mesh))
        .lower(p_sh, xs_sh)
        .compile()
    )
    hlo = comp.as_text()
    mb_bytes = int(np.prod(mb)) * 4
    new_bytes = (m // s_axis) * mb_bytes       # the shard one device holds
    old_bytes = m * mb_bytes                   # the replicated layout held M
    ma = comp.memory_analysis()
    out["pipeline_input_bytes_per_device_old"] = old_bytes
    out["pipeline_input_bytes_per_device_new"] = new_bytes
    out["pipeline_input_shrink"] = round(old_bytes / new_bytes, 2)
    out["pipeline_shape"] = f"M={m} stages={s_axis} mb={list(mb)} f32"
    out["pipeline_hlo_pins"] = {
        "collective_permute": "collective-permute" in hlo,
        "all_gather": "all-gather" in hlo,       # must be False
        "all_reduce": "all-reduce" in hlo,       # must be False
    }
    if ma is not None:
        out["pipeline_compiled_arg_bytes_per_device"] = int(
            ma.argument_size_in_bytes
        )

    # --- causal-LM train rate: the examples/train_lm.py default shape
    # (dp×sp zigzag causal ring) on synthetic packed batches
    mesh2 = create_mesh({"data": 4, "seq": 2})
    cfg = lm.LMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, max_len=64
    )
    lm_params = lm.init_params(jax.random.key(0), cfg)
    tx = optax.adam(3e-3)
    opt = tx.init(lm_params)
    step = jax.jit(
        functools.partial(
            lm.train_step, cfg=cfg, tx=tx, mesh=mesh2, data_axis="data",
            seq_axis="seq",
        ),
        donate_argnums=(0, 1),
    )
    toks = jnp.asarray(lm.make_synthetic_tokens(cfg, 32, seed=0))
    for _ in range(2):  # compile + warm
        lm_params, opt, loss = step(lm_params, opt, toks)
    jax.block_until_ready(loss)
    seconds = float(os.environ.get("TFR_BENCH_LM_SECONDS", 3.0))
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        lm_params, opt, loss = step(lm_params, opt, toks)
        n += 1
    jax.block_until_ready(loss)
    out["lm_steps_per_s"] = round(n / (time.perf_counter() - t0), 2)
    out["lm_shape"] = "B=32 L=64 d=64 2L zigzag-ring dp4xsp2"

    # --- MULTICHIP partial (ROADMAP #4): per-device compiled-memory bytes
    # for the SAME LM step, from memory_analysis() via the shared
    # tests/hlo_util compiled handle, labeled with the backend — the
    # eventual real-device round records the same fields
    from tests.hlo_util import compiled_memory_bytes

    mem = compiled_memory_bytes(step, lm_params, opt, toks)
    if mem:
        out["lm_compiled_memory"] = mem

    # --- fsdp weight sharding (full GSPMD mesh, PR 19): per-device
    # at-rest bytes (params + opt state + inputs = compiled argument
    # bytes) for the SAME LM step under dp×fsdp vs pure dp — the number
    # the gather-on-use layout exists to shrink — plus the best-fit
    # packer's density on a ragged corpus (what segment-masked packing
    # buys over padding each document to L)
    from tpu_tfrecord.tpu import TokenPacker

    def _arg_bytes(mesh_axes, fsdp_axis):
        m = create_mesh(mesh_axes)
        p = lm.init_params(jax.random.key(0), cfg)
        p = jax.device_put(
            p, lm.param_shardings(m, p, fsdp_axis=fsdp_axis)
        )
        o = tx.init(p)
        t = jax.device_put(toks, NamedSharding(m, P("data", None)))
        s = jax.jit(
            functools.partial(
                lm.train_step, cfg=cfg, tx=tx, mesh=m,
                data_axis="data", fsdp_axis=fsdp_axis,
            )
        )
        ma_s = s.lower(p, o, t).compile().memory_analysis()
        return (
            int(ma_s.argument_size_in_bytes) if ma_s is not None else None
        )

    b_dp = _arg_bytes({"data": 8}, None)
    b_fsdp = _arg_bytes({"data": 2, "fsdp": 4}, "fsdp")
    if b_dp and b_fsdp:
        out["lm_dp_param_bytes_per_device"] = b_dp
        out["lm_fsdp_param_bytes_per_device"] = b_fsdp
        out["lm_fsdp_param_shrink"] = round(b_dp / b_fsdp, 2)
        out["lm_fsdp_shape"] = "dp2xfsdp4 vs dp8, same step"

    prng = np.random.default_rng(15)
    packer = TokenPacker(4, 32, packing="best_fit")
    packer.feed_docs(
        np.ones(int(s), np.int32)
        for s in prng.choice([2, 6, 10, 15, 16, 21, 25, 31], size=300)
    )
    while packer.pop() is not None:
        pass
    out["pack_density"] = round(packer.density(), 4)
    out["pack_shape"] = "B=4 L=32 best_fit ragged[2..31]x300"

    # --- training flight recorder (ISSUE 13): the REAL harness loop
    # (StepPhases + DeviceIterator) over device-fed synthetic batches —
    # the per-step phase decomposition + training verdict, measured, not
    # asserted
    sys_path_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "examples"
    )
    import sys as _sys

    _sys.path.insert(0, sys_path_dir)
    import _harness

    from tpu_tfrecord.tpu import DeviceIterator

    rec = _harness.StepPhases(window=8)
    toks_np = np.asarray(toks)
    dev_it = DeviceIterator(
        iter([{"tokens": toks_np}] * 16), mesh2, axis="data"
    )
    def _sfn(state, gb):
        p, o = state
        p, o, loss = step(p, o, gb["tokens"])
        return (p, o), loss

    (lm_params, opt), _, _ = _harness.run_train_loop(
        dev_it, produce=lambda gb: gb, step_fn=_sfn,
        state=(lm_params, opt), phases=rec, max_steps=16, log_every=1000,
    )
    out["lm_step_breakdown"] = {
        "shares": {k: round(v, 4) for k, v in rec.shares().items()},
        "verdict": rec.verdict(),
        "steps": rec.steps,
    }

    # --- in-jit model diagnostics: measured pipeline bubble at the bench
    # shape (vs the analytic (S-1)/(M+S-1) the interleaved-V work must
    # beat) + MoE imbalance through the pinned EP dispatch
    _, pdiag = pipeline.pipeline_apply(
        stage_fn, p_sh, xs_sh, mesh, diagnostics=True
    )
    out["pipeline_bubble_fraction"] = round(float(pdiag["bubble_fraction"]), 4)
    out["pipeline_bubble_analytic"] = round((s_axis - 1) / (m + s_axis - 1), 4)

    # --- bubble-vs-V sweep (ISSUE 15): the interleaved schedule's bubble
    # MEASURED by the same per-tick occupancy counter at fixed S and M,
    # V in {1, 2, 4}, against the interleaved analytic (S-1)/(V·M+S-1) —
    # the number ROADMAP #2 asked to shrink, shrinking
    v_s, v_m, v_d = 4, 8, 64
    v_mesh = create_mesh({"pipe": v_s}, jax.devices()[:v_s])
    xs_v = jnp.zeros((v_m, 4, v_d), jnp.float32)
    xs_v_sh = jax.device_put(
        xs_v, pipeline.microbatch_sharding(v_mesh, ndim=xs_v)
    )
    for v in (1, 2, 4):
        shape = (v_s, v, v_d, v_d) if v > 1 else (v_s, v_d, v_d)
        pv_sh = jax.device_put(
            {"w": jnp.asarray(rng.normal(size=shape) * 0.1, jnp.float32)},
            NamedSharding(v_mesh, P("pipe")),
        )
        _, dv = pipeline.pipeline_apply(
            stage_fn, pv_sh, xs_v_sh, v_mesh, n_virtual=v, diagnostics=True
        )
        out[f"pipeline_bubble_v{v}"] = round(float(dv["bubble_fraction"]), 4)
        out[f"pipeline_bubble_v{v}_analytic"] = round(
            (v_s - 1) / (v * v_m + v_s - 1), 4
        )
    out["pipeline_bubble_v_shape"] = f"M={v_m} stages={v_s} mb=[4,{v_d}] f32"

    # --- microbatch-streamed serving (ISSUE 15): requests/s through the
    # persistent per-tick PipelineStream step (per-call feed = ONE
    # [mb, ...] slice; outputs pop with S·V-tick latency), interleaved
    # V=2 — the heavy-traffic serving path's headline number
    sv_s, sv_v, sv_mb = 4, 2, (8, 128)
    sv_mesh = create_mesh({"pipe": sv_s}, jax.devices()[:sv_s])
    sp_sh = jax.device_put(
        {"w": jnp.asarray(
            rng.normal(size=(sv_s, sv_v) + (sv_mb[1], sv_mb[1])) * 0.1,
            jnp.float32,
        )},
        NamedSharding(sv_mesh, P("pipe")),
    )
    stream = pipeline.PipelineStream(
        stage_fn, sp_sh, sv_mesh, n_virtual=sv_v, microbatch_shape=sv_mb
    )
    req = rng.normal(size=sv_mb).astype(np.float32)
    for _ in range(sv_s * sv_v + 4):  # warm: compile + one pipeline fill
        stream.push(req)
    stream.flush()
    stream.reset()
    serve_seconds = float(os.environ.get("TFR_BENCH_SERVE_SECONDS", 1.5))
    t0 = time.perf_counter()
    n_req = 0
    while time.perf_counter() - t0 < serve_seconds:
        stream.push(req)
        n_req += 1
    # outputs are device-resident: block on the drained tail so the
    # wall-clock covers the actual compute, not just dispatch
    jax.block_until_ready(stream.flush())
    # raw per-tick stream rate (the transport under the serving tier);
    # the serving-tier request numbers are _serving_probe's
    out["stream_requests_per_s"] = round(
        n_req / (time.perf_counter() - t0), 1
    )
    out["stream_shape"] = f"mb={list(sv_mb)} S={sv_s} V={sv_v} f32"

    from tpu_tfrecord.models import moe as _moe_mod

    moe_cfg = _moe_mod.MoEConfig(
        d_model=64, d_ff=128, n_experts=8, top_k=2, capacity_factor=1.25
    )
    moe_mesh = create_mesh({"expert": 8})
    moe_params = _moe_mod.init_params(jax.random.key(1), moe_cfg)
    moe_x = jnp.asarray(
        rng.normal(size=(512, 64)).astype(np.float32)
    )
    _, _, mdiag = jax.jit(
        lambda p, x: _moe_mod.moe_apply_ep(
            p, x, moe_cfg, moe_mesh, diagnostics=True
        )
    )(moe_params, moe_x)
    tokens_per_expert = np.asarray(mdiag["expert_tokens"], dtype=float)
    out["moe_imbalance"] = round(
        float(tokens_per_expert.max() / max(tokens_per_expert.mean(), 1e-9)), 3
    )
    out["moe_dropped_fraction"] = round(float(mdiag["dropped_fraction"]), 4)
    out["moe_shape"] = "T=512 d=64 E=8 top2 ep8"

    # --- diagnostics overhead A/B (same <=2% bar as the PR 5 tracing
    # overhead): the MoE LM step with in-jit diagnostics OFF vs ON
    # (including the per-step host fold the instrumented trainer pays).
    # Fixed-step interleaved windows, MIN seconds-per-step each arm — the
    # one-sided-noise estimator every perf leg on this box uses; the B=8
    # shape keeps one step well under a window so the ratio is not
    # quantization noise
    cfg_ab = lm.LMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, max_len=64,
        moe_experts=4, moe_top_k=2,
    )
    toks_ab = jnp.asarray(lm.make_synthetic_tokens(cfg_ab, 8, seed=0))
    arms = {}
    for diag_on in (False, True):
        params_ab = lm.init_params(jax.random.key(2), cfg_ab)
        opt_ab = tx.init(params_ab)
        fn = jax.jit(
            functools.partial(
                lm.train_step, cfg=cfg_ab, tx=tx, mesh=mesh2,
                data_axis="data", seq_axis="seq", diagnostics=diag_on,
            ),
            donate_argnums=(0, 1),
        )
        res = fn(params_ab, opt_ab, toks_ab)  # compile + warm
        params_ab, opt_ab = res[0], res[1]
        jax.block_until_ready(res[2])
        arms[diag_on] = [fn, params_ab, opt_ab, float("inf")]
    ab_steps = int(os.environ.get("TFR_BENCH_LM_AB_STEPS", 10))
    for _ in range(4):  # interleaved windows, best (min s/step) per arm
        for diag_on, arm in arms.items():
            fn, p_ab, o_ab, best = arm
            t0 = time.perf_counter()
            for _ in range(ab_steps):
                res = fn(p_ab, o_ab, toks_ab)
                p_ab, o_ab, loss = res[0], res[1], res[2]
                jax.block_until_ready(loss)
                if diag_on:
                    _harness.fold_model_diagnostics(res[3])
            arm[1], arm[2] = p_ab, o_ab
            arm[3] = min(best, (time.perf_counter() - t0) / ab_steps)
    off_spp, on_spp = arms[False][3], arms[True][3]
    out["lm_diagnostics_overhead_pct"] = round(
        (on_spp / off_spp - 1.0) * 100.0, 2
    )
    print(json.dumps(out), flush=True)


def _model_parallel_probe() -> dict:
    """Model-parallel leg (ISSUE 10): per-device input-buffer bytes for the
    pipelined step (old replicated shape vs the new O(mb) shard) and a
    train_lm steps/s number, measured in a SUBPROCESS that forces an
    8-device CPU backend — pre-backend-init in the parent, so a dead TPU
    tunnel still certifies the memory shape (same pattern as the service
    probe's worker subprocesses)."""
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    here = os.path.abspath(__file__)
    try:
        proc = subprocess.run(
            [_sys.executable, here, "--model-parallel-child"],
            env=env,
            cwd=os.path.dirname(here),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=600,
        )
    except subprocess.TimeoutExpired:
        # a hung child (stuck compile on a loaded box) must land as a
        # structured error field, not crash the whole artifact
        return {"model_parallel_error": "child exceeded 600s"}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return {
        "model_parallel_error": (
            f"child rc={proc.returncode}: {proc.stdout[-500:]}"
        )
    }


def _serving_child() -> None:
    """Subprocess body (CPU env forced by the parent): the overload-proof
    serving tier (ISSUE 18) under seeded OPEN-LOOP load — arrivals fire on
    a seeded Poisson clock whether or not the engine keeps up, which is
    what makes the overload leg's shed rate an honest number rather than
    closed-loop backpressure hiding it. Three legs, ONE JSON line:

      1. calibrate: closed-loop saturation -> capacity (requests/s)
      2. steady:    open-loop at 0.5x capacity -> serve_p99_ms (the
                    SLO-relevant latency: queue wait + compute)
      3. overload:  open-loop at 3x capacity -> serve_requests_per_s
                    (throughput AT saturation) + the DISCLOSED shed rate
                    (admission control sheds the excess loudly; a shed
                    rate near 2/3 here is the design working, not a bug)
    """
    import jax

    from tpu_tfrecord.metrics import Metrics
    from tpu_tfrecord.models import lm
    from tpu_tfrecord.serving import (
        ServePolicy, ServeRejected, ServingEngine,
    )
    from tpu_tfrecord.tpu import create_mesh

    cfg = lm.LMConfig(
        vocab_size=96, d_model=32, n_heads=2, n_layers=4, max_len=16,
        n_micro=4, n_virtual=1,
    )
    params = lm.init_params(jax.random.key(0), cfg)
    mesh = create_mesh({"pipe": 2}, jax.devices()[:2])
    rng = np.random.default_rng(0)
    windows = [
        rng.integers(1, cfg.vocab_size, size=cfg.max_len).astype(np.int32)
        for _ in range(64)
    ]
    n_new = 2

    def engine(max_queue):
        return ServingEngine(
            params, cfg, mesh,
            policy=ServePolicy(mb=4, max_queue=max_queue),
            metrics=Metrics(),
        ).start()

    # --- calibrate: saturate the batch, capacity = completed/s. The
    # first request also pays the per-tick compile, so warm separately.
    eng = engine(max_queue=64)
    eng.submit(windows[0], n_new).result(timeout=300)
    t0 = time.perf_counter()
    handles = [eng.submit(windows[i % 64], n_new) for i in range(48)]
    for h in handles:
        h.result(timeout=300)
    capacity = 48 / (time.perf_counter() - t0)
    eng.stop()

    def open_loop(rate, seconds, max_arrivals=2000):
        """Seeded Poisson arrivals at `rate` for `seconds`; returns the
        leg's completed/s, latency quantiles, and shed accounting."""
        e = engine(max_queue=16)
        # a fresh engine is a fresh LMStream: its first request pays the
        # per-tick compile (~0.5s) — warm it off the clock or that stall
        # IS the leg's p99 and the queue sheds behind it
        e.submit(windows[0], n_new).result(timeout=300)
        e._metrics = Metrics()  # drop the warmup's latency sample
        gaps = rng.exponential(1.0 / rate, size=max_arrivals)
        live, shed, i = [], 0, 0
        t0 = time.perf_counter()
        t_next = t0
        while i < max_arrivals:
            now = time.perf_counter()
            if now - t0 >= seconds:
                break
            if now < t_next:
                time.sleep(min(t_next - now, 0.002))
                continue
            t_next += gaps[i]
            try:
                live.append(e.submit(windows[i % 64], n_new))
            except ServeRejected:
                shed += 1
            i += 1
        for h in live:
            h.result(timeout=300)
        wall = time.perf_counter() - t0
        rep = e.report()
        e.stop()
        offered = len(live) + shed
        return {
            "offered": offered,
            "offered_per_s": round(rate, 1),
            "completed": len(live),
            "requests_per_s": round(len(live) / wall, 1),
            "shed": shed,
            "shed_rate": round(shed / max(1, offered), 3),
            "p50_ms": round(rep["p50_ms"], 2),
            "p99_ms": round(rep["p99_ms"], 2),
            # request-latency decomposition from the serve.queue_wait /
            # serve.service spans (ISSUE 20): where the p99 lives —
            # waiting for a slot, or being computed
            "queue_wait_p99_ms": (
                round(rep["queue_wait_p99_ms"], 2)
                if rep.get("queue_wait_p99_ms") is not None else None
            ),
            "service_p99_ms": (
                round(rep["service_p99_ms"], 2)
                if rep.get("service_p99_ms") is not None else None
            ),
            "verdict": rep["verdict"],
        }

    # overload FIRST: its completed/s is the SUSTAINED capacity with the
    # open-loop driver thread contending for the GIL — the closed-loop
    # calibration number above overstates it. The steady leg then sits
    # UNDER the sparse-packing floor: a tick costs the same wall-clock
    # whether 1 or mb slots are valid, so at low concurrency the engine
    # serves ~1/(mb/n_new) of its saturation rate — a steady rate sized
    # off saturation throughput sheds when it should cruise
    overload = open_loop(3.0 * capacity, 2.0)
    steady = open_loop(0.2 * overload["requests_per_s"], 2.5)
    from tpu_tfrecord.slo import burn_rate

    out = {
        # headline pair (banded in _PREV_NOISE_BANDS): latency where the
        # SLO lives, throughput where the capacity lives
        "serve_p99_ms": steady["p99_ms"],
        "serve_requests_per_s": overload["requests_per_s"],
        # the p99 decomposed: queue wait vs service time at steady state
        "serve_queue_wait_p99_ms": steady["queue_wait_p99_ms"],
        "serve_service_p99_ms": steady["service_p99_ms"],
        # availability (0.999) burn rate at steady state — ~0 when the
        # engine cruises at 0.5x capacity; any sustained value means the
        # steady leg started shedding, a capacity regression the p99
        # alone can hide (the overload leg's ~2/3 shed rate is design,
        # so only the steady leg's burn is a signal)
        "serve_error_budget_burn": round(
            burn_rate(steady["shed"], steady["offered"], 0.999), 2
        ),
        "serving": {
            "capacity_requests_per_s": round(capacity, 1),
            "steady": steady,
            "overload": overload,
            "shape": (
                f"mb=4 n_new={n_new} L={cfg.max_len} "
                f"d={cfg.d_model} S=2 V=1 f32"
            ),
        },
    }
    print(json.dumps(out), flush=True)


def _serving_probe() -> dict:
    """Serving-tier leg (ISSUE 18), measured in a CPU-forced SUBPROCESS
    (same pattern as _model_parallel_probe: pre-backend-init in the
    parent, so a dead TPU tunnel still lands the serving numbers)."""
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    here = os.path.abspath(__file__)
    try:
        proc = subprocess.run(
            [_sys.executable, here, "--serving-child"],
            env=env,
            cwd=os.path.dirname(here),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=600,
        )
    except subprocess.TimeoutExpired:
        return {"serving_error": "child exceeded 600s"}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return {
        "serving_error": f"child rc={proc.returncode}: {proc.stdout[-500:]}"
    }


def _ckpt_probe() -> dict:
    """Async vs sync checkpointing A/B (ISSUE 16, device-free, ~3s).

    A synthetic train loop (fixed busy-compute per step, fixed save
    cadence) checkpoints a model-shaped pytree through AsyncCheckpointer
    twice under a SEEDED commit throttle (commit_delay_s — the slow-disk
    fault): the sync twin pays the throttle on the step path and must
    verdict ckpt_bound; the async path pays only the snapshot and must
    stay compute_bound, with the restored state byte-identical between
    the two. Then the real (unthrottled) commit p99 on all three artifact
    paths: the sharded model pytree, the train_lm-shaped npz twin
    (params+opt leaves + input/packer payload), and the O(1) input-state
    JSON (AsyncStateSaver)."""
    import shutil
    import sys as _sys
    import tempfile

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "examples"
    ))
    import _harness

    from tpu_tfrecord.checkpoint import AsyncCheckpointer, AsyncStateSaver
    from tpu_tfrecord.io.dataset import IteratorState
    from tpu_tfrecord.metrics import Metrics

    rng = np.random.default_rng(0)
    state = {
        "w": rng.standard_normal((128, 256)).astype(np.float32),
        "b": rng.standard_normal(256).astype(np.float32),
    }
    throttle = float(os.environ.get("TFR_BENCH_CKPT_THROTTLE_S", 0.03))
    steps = int(os.environ.get("TFR_BENCH_CKPT_STEPS", 24))
    cadence = 4
    spin = rng.standard_normal((160, 160)).astype(np.float32)
    compute_s = 0.010

    def busy():
        # fixed-duration host compute (the "device step" stand-in)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < compute_s:
            np.dot(spin, spin)

    def leg(sync: bool, root: str):
        m = Metrics()
        ck = AsyncCheckpointer(
            os.path.join(root, "sync" if sync else "async"),
            process_index=0, process_count=1, sync=sync,
            commit_delay_s=throttle, metrics=m,
        )
        rec = _harness.StepPhases(window=16)
        for step in range(1, steps + 1):
            with rec.phase("compute"):
                busy()
            if step % cadence == 0:
                with rec.phase("ckpt"):
                    ck.save(step, state, {"step": step})
            rec.end_step()
        ck.wait()
        restored = ck.restore({k: np.zeros_like(v) for k, v in state.items()})
        ck.close()
        return rec, m, restored

    root = tempfile.mkdtemp(prefix="tfr_bench_ckpt_")
    try:
        sync_rec, _, sync_restored = leg(True, root)
        async_rec, async_m, async_restored = leg(False, root)
        resume_equal = sync_restored[0] == async_restored[0] and all(
            np.array_equal(sync_restored[1][k], async_restored[1][k])
            for k in state
        )

        def commit_p99_ms(m: Metrics) -> float:
            q = m.quantiles("ckpt.commit").get("ckpt.commit")
            return round(q["p99_s"] * 1000.0, 2) if q else 0.0

        # unthrottled commit p99 per artifact path
        m_pytree = Metrics()
        with AsyncCheckpointer(
            os.path.join(root, "p_pytree"), process_index=0,
            process_count=1, commit_delay_s=0.0, metrics=m_pytree,
        ) as ck:
            for step in range(1, 9):
                ck.save(step * cadence, state, None)
            ck.wait()
        lm_state = (state, {"mu": np.zeros_like(state["w"])})
        m_npz = Metrics()
        with AsyncCheckpointer(
            os.path.join(root, "p_npz"), process_index=0,
            process_count=1, commit_delay_s=0.0, metrics=m_npz,
        ) as ck:
            for step in range(1, 9):
                ck.save(
                    step * cadence, lm_state,
                    {"input": {"epoch": 0, "shard_cursor": step},
                     "packer": {"carry": [step]}},
                )
            ck.wait()
        m_state = Metrics()
        with AsyncStateSaver(
            os.path.join(root, "p_state"), process_index=0,
            commit_delay_s=0.0, metrics=m_state,
        ) as saver:
            for step in range(1, 9):
                saver.save(
                    IteratorState(shard_cursor=step, record_offset=step * 7),
                    step=step * cadence,
                )
            saver.wait()

        wait_stats = async_m.snapshot().get("ckpt.commit_wait", {})
        return {
            "ckpt_sync_share": round(sync_rec.shares().get("ckpt", 0.0), 4),
            "ckpt_async_share": round(async_rec.shares().get("ckpt", 0.0), 4),
            "ckpt_commit_p99_ms_pytree": commit_p99_ms(m_pytree),
            "ckpt_commit_p99_ms_npz": commit_p99_ms(m_npz),
            "ckpt_commit_p99_ms_state": commit_p99_ms(m_state),
            "ckpt": {
                "sync_verdict": sync_rec.verdict(),
                "async_verdict": async_rec.verdict(),
                "resume_equal": resume_equal,
                "commit_throttle_s": throttle,
                "cadence": cadence,
                "steps": steps,
                "async_commit_wait_ms": round(
                    wait_stats.get("seconds", 0.0) * 1000.0, 2
                ),
                "async_commit_waits": int(wait_stats.get("records", 0)),
            },
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


# Self-flagging regression check (ROADMAP #5): the artifact compares its
# own numbers against the previous round's and flags anything outside a
# per-field noise band — r5's host_side 1.32M vs r4's 1.51M went
# un-diagnosed because nothing in the artifact said "this moved".
# Bands reflect each number's observed round-over-round variance on this
# shared box: host-side decode numbers are fairly stable; anything with
# the disk (cold) or the shaped tunnel (value/sustained) swings wildly.
_PREV_NOISE_BANDS = {
    "host_side_value": 0.15,
    # model-parallel leg: the memory-shape ratio is deterministic (a drop
    # means the pipeline regressed to a replicated layout), the LM rate is
    # a compiled CPU loop on a shared box
    "pipeline_input_shrink": 0.10,
    "lm_steps_per_s": 0.50,
    # fsdp leg (PR 19): both deterministic — per-device at-rest bytes
    # (smaller is better: a rise means weights stopped living sharded)
    # and the best-fit packer density on the fixed ragged corpus (a drop
    # means the binning regressed toward greedy/padding)
    "lm_fsdp_param_bytes_per_device": 0.10,
    "pack_density": 0.05,
    # streamed serving: a compiled CPU per-tick loop on a shared box (the
    # bubble sweep itself is deterministic and not banded — smaller is
    # better, the tests pin it against the analytic)
    "stream_requests_per_s": 0.50,
    # serving tier (ISSUE 18): request throughput at saturation and the
    # steady-state p99 through the continuous-batching engine. NOTE:
    # before ISSUE 18, serve_requests_per_s was the RAW PipelineStream
    # push rate (now stream_requests_per_s) — the first round after the
    # rename diffs across meanings and will flag; ignore that one flag.
    "serve_requests_per_s": 0.50,
    "serve_p99_ms": 0.50,
    # ISSUE 20: the p99 decomposition (same shared-box noise as the p99
    # itself) and the steady-leg error-budget burn — the burn sits at 0
    # when healthy, so ratio noise is meaningless; the wide band only
    # fires when steady-state shedding appears outright
    "serve_queue_wait_p99_ms": 0.50,
    "serve_service_p99_ms": 0.50,
    "serve_error_budget_burn": 2.00,
    "remote_http_cold_value": 0.50,
    "remote_http_cached_value": 0.35,
    "seq_host_value": 0.25,
    "service_value": 0.25,
    # elastic leg: throttled-decode throughput through a resizing fleet —
    # wide band, the injected stalls + scaling transient dominate
    "elastic_value": 0.50,
    "warm_epoch_value": 0.25,
    "cold_value": 0.50,
    "value": 0.35,
    "sustained_value": 0.50,
    # async checkpointing A/B (ISSUE 16). ckpt_sync_share is the CONTRAST
    # guard (bigger is better: a drop means the seeded throttle stopped
    # biting and the A/B lost its meaning); the async share and the
    # commit p99s are smaller-is-better (see _SMALLER_IS_BETTER) — a rise
    # is the regression. The async share sits near 0 so its ratio noise
    # is huge; the wide band only fires when it blows up outright.
    "ckpt_sync_share": 0.50,
    "ckpt_async_share": 2.00,
    "ckpt_commit_p99_ms_pytree": 0.50,
    "ckpt_commit_p99_ms_npz": 0.50,
    "ckpt_commit_p99_ms_state": 0.50,
}

#: Fields where SMALLER is better: _vs_previous inverts the flag logic
#: (delta above the band = regression, below = improvement).
_SMALLER_IS_BETTER = {
    "lm_fsdp_param_bytes_per_device",
    "ckpt_async_share",
    "ckpt_commit_p99_ms_pytree",
    "ckpt_commit_p99_ms_npz",
    "ckpt_commit_p99_ms_state",
    "serve_p99_ms",
    "serve_queue_wait_p99_ms",
    "serve_service_p99_ms",
    "serve_error_budget_burn",
}


def _load_previous_artifact():
    """(filename, artifact dict) of the newest BENCH_r*.json in the repo
    root, or None. Round files are either the raw artifact or the
    harness's {n, cmd, rc, tail[, parsed]} wrapper — the artifact is the
    wrapper's ``parsed`` dict or the last JSON line of ``tail``."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))

    def round_no(path: str) -> int:
        # numeric round order: lexicographic sort would put r99 after
        # r100 and silently diff against a stale round
        m = re.search(r"r(\d+)", os.path.basename(path))
        return int(m.group(1)) if m else -1

    candidates = sorted(
        glob.glob(os.path.join(here, "BENCH_r*.json")), key=round_no,
        reverse=True,
    )
    for path in candidates:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        if "metric" in doc:
            return os.path.basename(path), doc
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            return os.path.basename(path), parsed
        for line in reversed((doc.get("tail") or "").splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                return os.path.basename(path), cand
    return None


def _vs_previous(current: dict):
    """The vs-previous-round delta block: per tracked field, previous vs
    current with a noise band and a flag (regression | within_noise |
    improvement). ``regressions`` lists the flagged fields so a reader —
    or the round harness — sees a drop without diffing artifacts by
    hand."""
    prev = _load_previous_artifact()
    if prev is None:
        return None
    name, art = prev
    fields = {}
    regressions = []
    for field, band in _PREV_NOISE_BANDS.items():
        p, c = art.get(field), current.get(field)
        if not isinstance(p, (int, float)) or not isinstance(c, (int, float)) or not p:
            continue
        delta = c / p - 1.0
        if field in _SMALLER_IS_BETTER:
            flag = (
                "regression"
                if delta > band
                else ("improvement" if delta < -band else "within_noise")
            )
        else:
            flag = (
                "regression"
                if delta < -band
                else ("improvement" if delta > band else "within_noise")
            )
        if flag == "regression":
            regressions.append(field)
        fields[field] = {
            "previous": p,
            "current": c,
            "delta_pct": round(delta * 100.0, 1),
            "noise_band_pct": round(band * 100.0),
            "flag": flag,
        }
    return {"previous_round": name, "fields": fields, "regressions": regressions}


def main() -> None:
    import threading

    import jax

    import tpu_tfrecord

    # With a dead device tunnel, backend discovery hangs regardless of the
    # env var; see ensure_jax_platform. (With no JAX_PLATFORMS set, the
    # watchdog below still guards the TPU path.)
    tpu_tfrecord.ensure_jax_platform()

    from tpu_tfrecord.tpu import (
        DeviceIterator,
        HostPrefetcher,
        create_mesh,
        host_batch_from_columnar,
    )
    from tpu_tfrecord.tracing import DutyCycle

    data_dir = os.environ.get("TFR_BENCH_DIR", "/tmp/tpu_tfrecord_bench_v2")
    data_dir = ensure_dataset(data_dir)
    schema = criteo_read_schema()
    hash_buckets = {f"C{i}": HASH_BUCKETS for i in range(1, 27)}

    # One group = one [B, 40] i32 host matrix = ONE device transfer; the
    # consumer jit splits label/dense/cat on device (free under XLA fusion).
    pack = {
        "packed": ["label"]
        + [f"I{i}" for i in range(1, 14)]
        + [f"C{i}" for i in range(1, 27)],
    }

    # Device-free phases FIRST: they need no backend, so they complete even
    # when the tunnel is dead and ride along in the watchdog's error output.
    host_side_value = _host_side_throughput(
        data_dir, schema, hash_buckets, pack,
        seconds=float(os.environ.get("TFR_BENCH_HOST_SECONDS", 4.0)),
    )
    cold_info = None
    if os.environ.get("TFR_BENCH_COLD", "1") != "0":
        # ON by default so every round's artifact includes a number with
        # real disk IO in it (raw disk probe + one dropped-page-cache
        # pipeline pass, ~2s); set TFR_BENCH_COLD=0 to skip.
        cold_info = _cold_io_throughput(data_dir, schema, hash_buckets, pack)
    remote_info = None
    if os.environ.get("TFR_BENCH_REMOTE", "1") != "0":
        # simulated-link remote readahead evidence (~2s, device-free)
        remote_info = _remote_prefetch_probe()
    remote_http_info = None
    if os.environ.get("TFR_BENCH_HTTP", "1") != "0":
        # REAL-socket remote tier: depth sweep + remote->cache->mmap over
        # the threaded HTTP backend (~6s, device-free) — ISSUE 9
        remote_http_info = _remote_http_probe()
    stall_info = None
    if os.environ.get("TFR_BENCH_STALL", "1") != "0":
        # fault-free deadline+watchdog bookkeeping overhead (~8s, device-free)
        stall_info = _stall_guard_overhead(data_dir, schema, hash_buckets, pack)
    warm_info = None
    if os.environ.get("TFR_BENCH_WARM", "1") != "0":
        # columnar epoch cache: populate once, measure the mmap-served
        # warm-epoch rate (~6s, device-free)
        warm_info = _warm_epoch_throughput(data_dir, schema, hash_buckets, pack)
        if host_side_value:
            warm_info["warm_vs_decode"] = round(
                warm_info["warm_epoch_value"] / host_side_value, 3
            )
    telemetry_info = None
    if os.environ.get("TFR_BENCH_TELEMETRY", "1") != "0":
        # flight-recorder overhead A/B + the telemetry block (quantiles +
        # bound-ness verdict) (~12s, device-free)
        telemetry_info = _tracing_overhead(data_dir, schema, hash_buckets, pack)
    seq_host_info = None
    if os.environ.get("TFR_BENCH_SEQ", "1") != "0":
        # device-free seq leg FIRST (ROADMAP #5): seq_host_value must land
        # in the artifact even when the tunnel is dead (~3s)
        seq_host_info = _seq_host_throughput(
            seconds=float(os.environ.get("TFR_BENCH_SEQ_HOST_SECONDS", 2.0))
        )
    autotune_info = None
    if os.environ.get("TFR_BENCH_AUTOTUNE", "1") != "0":
        # closed-loop autotune convergence vs the fixed-knob reference
        # (~8s, device-free)
        autotune_info = _autotune_probe(data_dir, schema, hash_buckets, pack)
    service_info = None
    if os.environ.get("TFR_BENCH_SERVICE", "1") != "0":
        # disaggregated data service: K worker subprocesses -> 1 consumer,
        # vs host_side_value (~6s, device-free)
        service_info = _service_probe(data_dir, schema, hash_buckets, pack)
        if host_side_value:
            service_info["service"]["vs_host_side"] = round(
                service_info["service_value"] / host_side_value, 3
            )
    elastic_info = None
    if os.environ.get("TFR_BENCH_ELASTIC", "1") != "0":
        # elastic decode fleet: worker count tracks offered load, drains
        # on load removal (~16s, device-free) — ISSUE 12
        elastic_info = _elastic_probe()
    lease_info = None
    if os.environ.get("TFR_BENCH_LEASE", "1") != "0":
        # partitioned dispatchers: aggregate lease throughput K=1 vs K=2
        # (~6s, device-free) — ISSUE 17
        lease_info = _lease_throughput_probe()
    ckpt_info = None
    if os.environ.get("TFR_BENCH_CKPT", "1") != "0":
        # async vs sync checkpoint A/B under a seeded commit throttle +
        # unthrottled commit p99 per artifact path (~3s, device-free)
        ckpt_info = _ckpt_probe()
    scaling_info = None
    if os.environ.get("TFR_BENCH_SCALING", "1") != "0":
        # workers->ex/s sweep, appended to PARITY.md as the round trend
        # (~6s, device-free)
        scaling_info = _decode_scaling_trend(data_dir, schema, hash_buckets, pack)
    model_parallel_info = None
    if os.environ.get("TFR_BENCH_MODEL", "1") != "0":
        # model-parallel memory shape + LM train rate in a CPU-forced
        # subprocess (~15s incl. compiles, device-free for the parent)
        model_parallel_info = _model_parallel_probe()
    serving_info = None
    if os.environ.get("TFR_BENCH_SERVING", "1") != "0":
        # serving tier under seeded open-loop load: steady p99 + capacity
        # at saturation + disclosed overload shed rate, in a CPU-forced
        # subprocess (~20s incl. compiles, device-free for the parent) —
        # ISSUE 18
        serving_info = _serving_probe()

    # Measurement attempts land here the moment they complete, so a guard
    # firing later (e.g. the train phase hanging on a dead tunnel) still
    # emits the real, already-measured headline instead of discarding it.
    completed_attempts: list = []

    def _fail_degraded(msg: str) -> None:
        """One owner for the guard-fired artifact. If the measurement
        attempts already completed, emit the REAL headline (best attempt)
        with the failure noted — only the phases after the measurement were
        lost. Otherwise emit the device-free evidence plus the reason.

        Runs on watchdog/deadline daemon threads while the main thread may
        still be appending: snapshot the list once and read only the
        snapshot (r3 advisor — unsynchronized shared state before os._exit)."""
        attempts_snap = list(completed_attempts)
        if attempts_snap:
            best = max(attempts_snap, key=lambda a: a["value"])
            out = {
                "metric": "criteo_tf_example_ingest_to_device",
                "value": best["value"],
                "unit": "examples/sec/host",
                "vs_baseline": round(best["value"] / 1_000_000, 4),
                "windows": best["windows"],
                "sustained_value": best["sustained_value"],
                "link_probe_mbps": best["link_probe_mbps"],
                "ingest_duty_cycle": best["ingest_duty_cycle"],
                "host_side_value": round(host_side_value, 1),
                "attempts": attempts_snap,
                "error": msg,
            }
            for extra in (cold_info, remote_info, remote_http_info,
                          stall_info, warm_info, telemetry_info,
                          seq_host_info, autotune_info, service_info,
                          elastic_info, lease_info, ckpt_info, scaling_info,
                          model_parallel_info, serving_info):
                if extra is not None:
                    out.update(extra)
            _attach_regression_verdict(out)
            print(json.dumps(out), flush=True)
            os._exit(0)
        err = {
            "metric": "criteo_tf_example_ingest_to_device",
            "error": msg,
            # degraded-mode evidence: the device-free pipeline number
            "host_side_value": round(host_side_value, 1),
            "host_side_unit": "examples/sec/host (decode+hash+pack, no device)",
        }
        for extra in (cold_info, remote_info, remote_http_info,
                      stall_info, warm_info, telemetry_info,
                      seq_host_info, autotune_info, service_info,
                      elastic_info, lease_info, ckpt_info, scaling_info,
                      model_parallel_info, serving_info):
            if extra is not None:
                err.update(extra)
        _attach_regression_verdict(err)
        print(json.dumps(err), flush=True)
        # exit 0: the artifact carries valid host-side metrics plus the
        # structured `error` field — the perf harness records the run
        # instead of marking it failed (BENCH_r05 lost a round to rc 3)
        os._exit(0)

    # Backend-init watchdog: a dead TPU tunnel makes jax.devices() block
    # forever inside C (observed on this box) — fail loudly with a
    # diagnosable message instead of hanging the harness. Armed only around
    # backend init — dataset generation and the host-side phase above must
    # not count against the tunnel timeout.
    backend_up = threading.Event()

    def _watchdog():
        if not backend_up.wait(float(os.environ.get("TFR_BENCH_INIT_TIMEOUT", 300))):
            _fail_degraded(
                "TPU backend initialization timed out "
                "(device tunnel unreachable?) — no device measurement taken"
            )

    threading.Thread(target=_watchdog, daemon=True).start()
    mesh = create_mesh()  # all available devices on the 'data' axis
    backend_up.set()

    # Whole-run deadline: backend init succeeding doesn't mean the tunnel
    # stays alive — a device_put after a mid-run tunnel death blocks forever
    # inside C (observed), which would end the round with NO artifact at
    # all. Default derives from the configured schedule (rests, attempts,
    # windows, sustain, train) so env overrides keep the guard honest.
    # n_attempts/attempt_rest are parsed HERE, once, and reused by the
    # measurement loop below — two parse sites would let the derived
    # deadline drift out of sync with the actual schedule.
    run_done = threading.Event()
    n_attempts = max(1, int(os.environ.get("TFR_BENCH_ATTEMPTS", 3)))
    attempt_rest = float(os.environ.get("TFR_BENCH_ATTEMPT_REST", 20))
    attempt_cost = MEASURE_SECONDS + SUSTAIN_SECONDS + 30  # probes + slack
    default_deadline = (
        REST_SECONDS
        + n_attempts * attempt_cost
        + (n_attempts - 1) * attempt_rest
        + 420  # train phases (two model regimes) incl. compiles/recompiles
        + 90   # seq phase incl. one-time ragged dataset generation
    )
    total_timeout = float(
        os.environ.get("TFR_BENCH_TOTAL_TIMEOUT", default_deadline)
    )

    def _deadline():
        if not run_done.wait(total_timeout):
            _fail_degraded(
                f"device phase exceeded {total_timeout:.0f}s "
                "(tunnel died mid-run?) — no device measurement taken"
            )

    threading.Thread(target=_deadline, daemon=True).start()
    if REST_SECONDS > 0:
        # Open the link (one tiny warm transfer), then let it sit quiet:
        # the shaper's burst budget accrues against the OPEN connection —
        # resting before backend init buys nothing.
        jax.block_until_ready(jax.device_put(np.zeros(8, np.int32), jax.devices()[0]))
        time.sleep(REST_SECONDS)
    ds = _make_dataset(data_dir, schema, hash_buckets, pack, num_epochs=None)

    import statistics

    from tpu_tfrecord.tpu import data_sharding, pack_mixed, packed_width

    link_bytes = 4 * (14 + packed_width(26, CAT_BITS))
    n_windows = max(1, int(os.environ.get("TFR_BENCH_WINDOWS", 4)))
    window_seconds = MEASURE_SECONDS / n_windows
    sharding = data_sharding(mesh, ndim=2)
    # On a single-core host the background-thread machinery (HostPrefetcher
    # + DeviceIterator) only adds GIL hand-offs — there is no second core
    # for it to win; a serial produce->transfer loop measures faster and is
    # what a 1-core host would deploy. Multi-core hosts keep the overlap
    # machinery (decode thread + prefetcher + dispatch-ahead).
    try:
        n_cpus = len(os.sched_getaffinity(0))  # cgroup/affinity-aware
    except AttributeError:  # non-Linux
        n_cpus = os.cpu_count() or 1
    serial = n_cpus == 1

    # Deliberate pack-slowdown injection for validating the attribution
    # protocol (see PARITY.md): a busy-wait of this many ms rides EVERY call
    # through _pack_one — so a genuine pack regression elevates BOTH the
    # in-loop pack stage and the no-transfer pack_floor below, while shaper
    # interference (a concurrent transfer burning the single core) elevates
    # only the in-loop number. That asymmetry is what makes attempts[]
    # self-explaining.
    pack_spin_s = float(os.environ.get("TFR_BENCH_PACK_SPIN_MS", 0)) / 1e3

    def _pack_one(cb):
        hb = host_batch_from_columnar(
            cb, ds.schema, hash_buckets=hash_buckets, pack=pack
        )
        m = pack_mixed(hb["packed"], 14, CAT_BITS)
        if pack_spin_s:
            spin_until = time.perf_counter() + pack_spin_s
            while time.perf_counter() < spin_until:
                pass
        return m

    def _pack_floor_ms(cb, iters: int = 5) -> float:
        """Best-of-N of the full pack stage (host batch assembly + 20-bit
        bit-pack) with NO transfer in flight: the attempt's clean-core
        reference for its in-loop pack number."""
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            _pack_one(cb)
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    # One decoded chunk reused by every attempt's pack floor (decoding it
    # fresh would measure the decode thread, not the pack stage).
    _floor_it = ds.batches()
    try:
        _floor_cb = next(iter(_floor_it))
    finally:
        _floor_it.close()

    def measure_attempt(attempt: int = 0) -> dict:
        """Link probe + measurement windows + sustained phase: one attempt."""
        # Raw-link probe: 8 transfers of one wire-batch-sized array, fresh
        # random content (the shaper treats repeated payloads differently).
        # Recorded in the artifact so the headline number can be read
        # against the link state it was measured under — on this box the
        # device sits behind a shaped tunnel whose bandwidth swings
        # 130MB/s..1.4GB/s independent of this pipeline (PARITY.md
        # "Device link").
        # Clean-core pack floor FIRST (before the probe opens the link): the
        # reference its in-loop pack number is judged against.
        pack_floor_ms = _pack_floor_ms(_floor_cb)
        probe_rng = np.random.default_rng(123 + attempt)  # fresh bytes per attempt
        probe_arrs = [
            probe_rng.integers(0, 1 << 20, size=(BATCH_SIZE, 31), dtype=np.int32)
            for _ in range(8)
        ]
        t_probe = time.perf_counter()
        for pa in probe_arrs:
            jax.block_until_ready(jax.device_put(pa, jax.devices()[0]))
        link_probe_mbps = (
            sum(pa.nbytes for pa in probe_arrs) / (time.perf_counter() - t_probe) / 1e6
        )

        it = ds.batches()
        # Per-attempt stage decomposition (verdict r3): decode_wait =
        # blocked on the decode thread; pack = view assembly + 20-bit
        # bit-pack; transfer = device_put dispatch (synchronous at dispatch
        # on this tunneled link; completion is blocked in the consume loop
        # and lands in the duty accounting). Accumulated over windows
        # AND sustain so a future headline swing is attributable to a stage
        # instead of read as a mystery. Only the serial path decomposes —
        # with the overlap machinery the stages run on other threads.
        stage = {"decode_wait_s": 0.0, "pack_s": 0.0, "transfer_s": 0.0, "batches": 0}
        raw_it = iter(it)

        def wire_batches():
            # decode thread -> dense [B, 40] i32 host batches -> transfer
            # form: label+dense stay 32-bit lanes, the 26 hashed cats
            # bit-pack to their 20 significant bits -> [B, 31] i32,
            # 124B/example on the link instead of 160 (the consumer unpacks
            # in its jit for free — tpu/bitpack.py, exactness pinned in
            # tests/test_bitpack.py).
            while True:
                t0 = time.perf_counter()
                try:
                    cb = next(raw_it)
                except StopIteration:
                    return
                t1 = time.perf_counter()
                m = _pack_one(cb)
                stage["decode_wait_s"] += t1 - t0
                stage["pack_s"] += time.perf_counter() - t1
                stage["batches"] += 1
                yield m

        src = wire_batches()
        prefetcher = None
        if serial:
            def get():
                m = next(src)
                t0 = time.perf_counter()
                gb = jax.device_put(m, sharding)
                stage["transfer_s"] += time.perf_counter() - t0
                return gb
        else:
            # DeviceIterator transfers pytrees — wrap the bare wire matrix
            prefetcher = HostPrefetcher({"wire": m} for m in src)
            feed = DeviceIterator(prefetcher, mesh)
            get = lambda: next(feed)  # noqa: E731

        duty = DutyCycle()

        def consume_one():
            with duty.wait():
                gb = get()
            with duty.step():
                jax.block_until_ready(gb)

        # This is a SHARED box: other tenants' load swings any single
        # window by +-25%. Measure N windows back-to-back and report the
        # MEDIAN (the standard interference-robust estimator); every window
        # is disclosed, and a separate steady-state phase right after the
        # windows reports the link-shaped sustained rate.
        windows = []
        sustained_value = None
        import resource

        r0 = resource.getrusage(resource.RUSAGE_SELF)
        t_attempt0 = time.perf_counter()
        try:
            for _ in range(WARMUP_BATCHES):
                consume_one()
            duty = DutyCycle()
            for _ in range(n_windows):
                t_start = time.perf_counter()
                examples = 0
                while True:
                    consume_one()
                    examples += BATCH_SIZE
                    t_end = time.perf_counter()
                    if t_end - t_start >= window_seconds:
                        break
                windows.append(examples / (t_end - t_start))
            ingest_duty = duty.value() or 0.0  # windows only, not sustain
            if SUSTAIN_SECONDS > 0:
                # keep hammering: the link's burst budget is long gone by
                # the end of this phase, so this is the shaped steady state
                t_start = time.perf_counter()
                examples = 0
                while time.perf_counter() - t_start < SUSTAIN_SECONDS:
                    consume_one()
                    examples += BATCH_SIZE
                sustained_value = examples / (time.perf_counter() - t_start)
        finally:
            if prefetcher is not None:
                prefetcher.close()
            it.close()
        r1 = resource.getrusage(resource.RUSAGE_SELF)
        attempt_wall = time.perf_counter() - t_attempt0
        attempt_cpu = (r1.ru_utime - r0.ru_utime) + (r1.ru_stime - r0.ru_stime)
        out = {
            "value": round(statistics.median(windows), 1),
            "windows": [round(w, 1) for w in windows],
            "sustained_value": round(sustained_value, 1) if sustained_value else None,
            "link_probe_mbps": round(link_probe_mbps, 1),
            "ingest_duty_cycle": round(ingest_duty, 4),
            # Attribution context (verdict r4 item 4): pack_floor_ms is the
            # SAME pack code path timed with no transfer in flight, fresh
            # each attempt — in-loop pack >> floor while the floor holds
            # steady means a concurrent transfer was burning the core
            # (shaper busy-wait), NOT a pack regression (which would raise
            # the floor too; validate with TFR_BENCH_PACK_SPIN_MS).
            # cpu_frac near 1.0 says the wall went to CPU work on this
            # 1-core host; well under 1.0 says blocked on the link.
            "pack_floor_ms": round(pack_floor_ms, 2),
            "attempt_cpu_frac": round(attempt_cpu / attempt_wall, 3)
            if attempt_wall > 0
            else None,
            "attempt_nivcsw": r1.ru_nivcsw - r0.ru_nivcsw,
        }
        if stage["batches"]:
            nb = stage["batches"]
            out["stage_ms_per_batch"] = {
                "decode_wait": round(stage["decode_wait_s"] / nb * 1e3, 2),
                "pack": round(stage["pack_s"] / nb * 1e3, 2),
                "transfer": round(stage["transfer_s"] / nb * 1e3, 2),
            }
        return out

    # Interference on this box is strictly ONE-directional: the shaped
    # tunnel and the other tenants on the shared core can only SLOW the
    # pipeline down, never speed it up. Under one-sided noise the standard
    # estimator of the noise-free rate is the best of a FIXED number of
    # draws (the same argument behind timeit's min-of-repeats rule: the
    # high throughputs are the signal, the low ones are other processes).
    # The attempt count is fixed up front — never conditioned on an
    # attempt's outcome or on the link probe — so there is no re-roll bias:
    # every run takes exactly TFR_BENCH_ATTEMPTS draws and EVERY attempt
    # (value, windows, its own link probe) is disclosed in attempts[].
    # (An earlier revision selected by best link probe; a captured run
    # showed the probe inverting — probe 498MB/s paired with 518k ex/s
    # while probe 204MB/s paired with 992k — because the instantaneous
    # probe does not predict link state over the following 14s.)
    attempts = completed_attempts  # shared with _fail_degraded (see above)
    for i in range(n_attempts):
        if i:
            time.sleep(attempt_rest)  # let the link's burst budget refill
        attempts.append(measure_attempt(i))
    best = max(attempts, key=lambda a: a["value"])
    value = best["value"]
    windows = best["windows"]
    sustained_value = best["sustained_value"]
    link_probe_mbps = best["link_probe_mbps"]
    ingest_duty = best["ingest_duty_cycle"]

    # Secondary disclosed metric: the ragged SequenceExample (long-doc)
    # path — decode->pad->bf16->device (verdict r3 item 8). The host-only
    # half already ran pre-backend (seq_host_info).
    seq_info = None
    if os.environ.get("TFR_BENCH_SEQ", "1") != "0":
        seq_info = _seq_device_throughput(mesh, data_sharding(mesh, ndim=3))

    # Phase 2 — the BASELINE.md duty-cycle metric measured the way it is
    # defined: a real DLRM training step on the device consuming ingested
    # batches, busy = device step time, wait = time blocked on input. The
    # producer thread decodes (GIL released) while the device computes, so
    # overlap is real even on this 1-core host. Two regimes:
    # - duty_cycle: a modest DLRM. Even this step is device-bound on one
    #   chip (XLA's embedding gather/scatter over a 2^20-row table costs
    #   ~100-200ms at B=16384 — the classic TPU embedding bottleneck that
    #   SparseCore hardware exists for), so the pipeline keeps it >=0.999
    #   fed; a host with more cores per chip or a lighter model could flip
    #   this regime production-bound.
    # - duty_cycle_heavy: the top MLP sized so the device step exceeds host
    #   batch time regardless of embedding-op cost (the north-star regime:
    #   BASELINE.md defines >=95% as "input pipeline never the
    #   bottleneck"). This is the red/green machine check of the >=95%
    #   claim on real hardware.
    train_duty = heavy_duty = None
    if os.environ.get("TFR_BENCH_TRAIN", "1") != "0":
        train_duty = _train_duty_cycle(ds, mesh, hash_buckets, pack, top_mlp=(64, 1))
        heavy_top = tuple(
            int(w) for w in os.environ.get("TFR_BENCH_HEAVY_TOP", "8192,8192,1").split(",")
        )
        heavy_duty = _train_duty_cycle(ds, mesh, hash_buckets, pack, top_mlp=heavy_top)

    # Fields from `best` are already rounded/filtered by measure_attempt —
    # formatting lives in ONE place.
    out = {
        "metric": "criteo_tf_example_ingest_to_device",
        "value": value,
        "unit": "examples/sec/host",
        "vs_baseline": round(value / 1_000_000, 4),
        # all measurement windows (median is the reported value)
        "windows": windows,
        # steady-state rate after the link's burst budget drains — on this
        # box that is the tunnel's token-bucket shaping (~130-250MB/s), not
        # the pipeline (see host_side_value and PARITY.md "Device link")
        "sustained_value": sustained_value,
        # bytes/example on the link (cats bit-packed to 20-bit lanes)
        "link_bytes_per_example": link_bytes,
        # raw link bandwidth measured just before the windows (device_put
        # of wire-batch-sized fresh arrays, no pipeline) — the ceiling the
        # shaped tunnel granted THIS run
        "link_probe_mbps": link_probe_mbps,
        # transfer-hidden fraction of the ingest-only loop (phase 1,
        # measurement windows only — the sustain phase is excluded)
        "ingest_duty_cycle": ingest_duty,
        # device-free pipeline throughput (decode+hash+pack, no device)
        "host_side_value": round(host_side_value, 1),
    }
    if attempts:
        # full disclosure: every measurement attempt with its link state and
        # attribution context (pack_floor_ms / cpu_frac / nivcsw) — emitted
        # even for a single attempt, which carries the same context
        out["attempts"] = attempts
    if cold_info is not None:
        # dropped-page-cache pass + raw-disk disclosure (TFR_BENCH_COLD=1)
        out.update(cold_info)
    if remote_info is not None:
        # simulated-link remote readahead evidence (TFR_BENCH_REMOTE=1)
        out.update(remote_info)
    if remote_http_info is not None:
        # real-socket remote tier: depth sweep + remote->cache->mmap over
        # the threaded HTTP backend (TFR_BENCH_HTTP=1)
        out.update(remote_http_info)
    if stall_info is not None:
        # fault-free stall-defense bookkeeping overhead (TFR_BENCH_STALL=1)
        out.update(stall_info)
    if warm_info is not None:
        # columnar epoch cache: mmap-served warm-epoch rate vs the decode
        # path (TFR_BENCH_WARM=1)
        out.update(warm_info)
    if telemetry_info is not None:
        # flight-recorder overhead A/B + latency quantiles + bound-ness
        # verdict (TFR_BENCH_TELEMETRY=1)
        out.update(telemetry_info)
    if seq_host_info is not None:
        # device-free seq leg, measured pre-backend (TFR_BENCH_SEQ=1)
        out.update(seq_host_info)
    if autotune_info is not None:
        # autotune convergence trajectory + final knobs vs fixed-knob
        # (TFR_BENCH_AUTOTUNE=1)
        out.update(autotune_info)
    if service_info is not None:
        # disaggregated data service leg: K worker subprocesses -> 1
        # consumer vs host_side_value (TFR_BENCH_SERVICE=1)
        out.update(service_info)
    if elastic_info is not None:
        # elastic fleet: worker count vs offered load + drain-back
        # (TFR_BENCH_ELASTIC=1)
        out.update(elastic_info)
    if lease_info is not None:
        # partitioned-dispatcher lease throughput K=1 vs K=2
        # (TFR_BENCH_LEASE=1)
        out.update(lease_info)
    if ckpt_info is not None:
        # async vs sync checkpoint A/B + per-artifact commit p99
        # (TFR_BENCH_CKPT=1)
        out.update(ckpt_info)
    if scaling_info is not None:
        # workers->ex/s sweep (also appended to PARITY.md as the trend)
        out.update(scaling_info)
    if model_parallel_info is not None:
        # model-parallel memory shape (per-device pipeline input bytes,
        # old replicated vs new O(mb) shard) + LM train rate
        # (TFR_BENCH_MODEL=1)
        out.update(model_parallel_info)
    if serving_info is not None:
        # serving tier: steady p99 + saturation throughput + disclosed
        # overload shed rate (TFR_BENCH_SERVING=1)
        out.update(serving_info)
    if seq_info is not None:
        # ragged SequenceExample decode->pad->device secondary metric
        out.update(seq_info)
    if train_duty is not None:
        # realistic-model regime (device-bound on one chip — see comment
        # at the measurement site)
        out["duty_cycle"] = round(train_duty, 4)
    if heavy_duty is not None:
        # the BASELINE.md >=95% target metric, measured in its own regime
        # (device step >= host batch time by model size)
        out["duty_cycle_heavy"] = round(heavy_duty, 4)
    # self-flagging regression check vs the previous round's artifact,
    # with the first-class top-level verdict + loud stderr line
    _attach_regression_verdict(out)
    run_done.set()
    print(json.dumps(out))


def _train_duty_cycle(ds, mesh, hash_buckets, pack, top_mlp, seconds=6.0):
    """Duty cycle of a DLRM train loop fed by the live pipeline.

    Sparse embedding updates (models.dlrm.sparse_train_step) make the FULL
    2^20-bucket vocabulary trainable — the table gradient never
    materializes, so hashed indices feed the real-size table with no
    on-device folding. The transfer runs on DeviceIterator's worker thread
    (transfer_thread=True): on this tunneled device the H2D copy is
    synchronous at dispatch, so the worker does its blocking while the
    device computes — that overlap, not dispatch-ahead, is what keeps the
    device fed."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from tpu_tfrecord.models import DLRMConfig, init_params, sparse_opt_init, sparse_train_step
    from tpu_tfrecord.tpu import DeviceIterator, HostPrefetcher, host_batch_from_columnar
    from tpu_tfrecord.tracing import DutyCycle

    # TFR_BENCH_VOCAB scales the trainable table down for CPU smoke runs
    # (indices fold on device when it is below the hashed space); on the
    # real chip the default is the FULL 2^20 hashed vocabulary.
    vocab = int(os.environ.get("TFR_BENCH_VOCAB", HASH_BUCKETS))
    cfg = DLRMConfig(
        num_dense=13,
        num_categorical=26,
        vocab_size=vocab,
        embed_dim=32,
        bottom_mlp=(64, 32),
        top_mlp=top_mlp,
        interaction="dot",
    )
    params = init_params(jax.random.key(0), cfg)
    tx = optax.sgd(1e-3)
    opt_state = sparse_opt_init(params, cfg, tx)
    step = jax.jit(
        functools.partial(sparse_train_step, cfg=cfg, tx=tx), donate_argnums=(0, 1)
    )

    from tpu_tfrecord.tpu import pack_mixed, unpack_bits

    @jax.jit
    def split(gb):
        # consume the bit-packed wire form end-to-end: the 20-bit cat
        # unpack fuses into THIS jit (the train step is a separate program —
        # its donated params preclude merging here)
        m = gb["wire"]
        return {
            "label": m[:, 0].astype(jnp.float32),
            "dense": m[:, 1:14].astype(jnp.float32),
            # no fold at the default vocab (the full hashed space); CPU
            # smoke runs shrink the table via TFR_BENCH_VOCAB and fold
            "cat": unpack_bits(m[:, 14:], 26, CAT_BITS) % vocab
            if vocab < HASH_BUCKETS
            else unpack_bits(m[:, 14:], 26, CAT_BITS),
        }

    it = ds.batches()  # phase 1 closed its iterator; epochs are infinite

    def host_batches():
        for cb in it:
            hb = host_batch_from_columnar(
                cb, ds.schema, hash_buckets=hash_buckets, pack=pack
            )
            yield {"wire": pack_mixed(hb["packed"], 14, CAT_BITS)}

    # Both constructors spawn worker threads: build them INSIDE the try so a
    # ctor failure still reaches the finally and nothing leaks (r3 advisor).
    prefetcher = dev_it = None
    try:
        prefetcher = HostPrefetcher(host_batches())
        dev_it = DeviceIterator(prefetcher, mesh, transfer_thread=True)
        duty = DutyCycle()
        # warm THREE full iterations: the first call compiles, and the
        # second can recompile (donated outputs come back device-resident
        # with different layouts) — a compile leaking into the measured
        # window would report compile time as device "busy" (observed: a
        # 26s recompile turned the duty cycle into a meaningless 0.999)
        #
        # busy is forced with a SCALAR FETCH of the loss, not
        # block_until_ready: on this tunneled client block_until_ready
        # returns before the computation actually finishes (measured: a
        # chain of twenty 4096^2 matmuls "completed" in ~0ms; the 4-byte
        # d2h fetch waits for true execution). With block_until_ready the
        # device's real step time silently lands in the NEXT iteration's
        # input-wait, inverting the duty cycle.
        for _ in range(3):
            batch = split(next(dev_it))
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        t_end = time.perf_counter() + seconds
        while time.perf_counter() < t_end:
            with duty.wait():
                gb = next(dev_it)
            with duty.step():
                params, opt_state, loss = step(params, opt_state, split(gb))
                float(loss)  # force true completion (see note above)
        return duty.value()
    finally:
        if dev_it is not None:
            dev_it.close()
        if prefetcher is not None:
            prefetcher.close()
        it.close()


if __name__ == "__main__":
    if "--model-parallel-child" in sys.argv:
        # subprocess entry for _model_parallel_probe: env already forces
        # the 8-device CPU backend
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
        _model_parallel_child()
        sys.exit(0)
    if "--serving-child" in sys.argv:
        # subprocess entry for _serving_probe: env already forces the
        # 8-device CPU backend
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
        _serving_child()
        sys.exit(0)
    main()
