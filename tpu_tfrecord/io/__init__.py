"""IO layer: the 'tfrecord' data source (read/write/infer).

The DefaultSource equivalent (reference DefaultSource.scala:23-143 +
SURVEY.md §2.1): registered under the short name ``tfrecord`` in the format
registry (the ServiceLoader analog, §2.10), planning reads (schema inference,
per-shard readers, partition merging) and writes (save modes, partitionBy,
codecs, atomic commit).

High-level API::

    import tpu_tfrecord.io as tfio

    tfio.write(rows, schema, "/data/out", mode="overwrite",
               partition_by=["date"], codec="gzip")
    table = tfio.read("/data/out")            # schema inferred
    table = tfio.read("/data/out", schema=my_schema, columns=["x", "y"])
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence

from tpu_tfrecord.io.paths import Shard, discover_shards, has_success_marker
from tpu_tfrecord.io.reader import DatasetReader, ShardReader
from tpu_tfrecord.io.table import Table
from tpu_tfrecord.io.writer import DatasetWriter, ShardWriter, write_dataset
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.registry import register_format
from tpu_tfrecord.schema import StructType


class TFRecordDataSource:
    """Format plugin: name + planning entry points (ref DefaultSource)."""

    short_name = "tfrecord"

    def infer_schema(self, paths, **options: Any) -> StructType:
        return DatasetReader(paths, **options).schema()

    def reader(self, paths, **options: Any) -> DatasetReader:
        return DatasetReader(paths, **options)

    def writer(
        self,
        path: str,
        schema: StructType,
        mode: str = "error",
        partition_by: Optional[List[str]] = None,
        **options: Any,
    ) -> DatasetWriter:
        opts = TFRecordOptions.from_map(options)
        return DatasetWriter(path, schema, opts, partition_by=partition_by, mode=mode)

    # Class-based identity like the reference's equals/hashCode
    # (DefaultSource.scala:140-142) so registry lookups dedupe.
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, TFRecordDataSource)

    def __hash__(self) -> int:
        return hash(TFRecordDataSource)


register_format(TFRecordDataSource.short_name, TFRecordDataSource)


# read() materializes EVERYTHING as Python row lists — ~10-50x the on-disk
# bytes in memory. Refuse datasets beyond this size unless the caller opts
# in (limit=, bigger max_bytes=, or max_bytes=None).
_READ_MAX_BYTES_DEFAULT = 4 << 30


def read(
    paths,
    columns: Optional[List[str]] = None,
    options: Optional[TFRecordOptions] = None,
    *,
    limit: Optional[int] = None,
    max_bytes: Optional[int] = _READ_MAX_BYTES_DEFAULT,
    **option_kwargs: Any,
) -> Table:
    """Read a TFRecord dataset fully into a Table (schema inferred unless
    given). For streaming/TPU ingestion use ``reader()`` / tpu_tfrecord.tpu.

    ``limit`` caps the number of materialized rows (a cheap head over a big
    dataset). Without a limit, datasets whose on-disk size exceeds
    ``max_bytes`` (default 4 GiB) are refused with guidance — Python row
    lists cost an order of magnitude more RAM than the files themselves.
    """
    r = (
        DatasetReader(paths, options=options)
        if options is not None
        else DatasetReader(paths, **option_kwargs)
    )
    if limit is None and max_bytes is not None:
        total = sum(sh.size for sh in r.shards)
        if total > max_bytes:
            raise ValueError(
                f"dataset is {total / (1 << 30):.1f} GiB on disk, over the "
                f"read() guard of {max_bytes / (1 << 30):.1f} GiB; "
                "materializing it as Python rows would need far more RAM. "
                "Use tpu_tfrecord.io.reader() or "
                "tpu_tfrecord.io.dataset.TFRecordDataset to stream, pass "
                "limit=N for a head, or raise/disable with max_bytes="
            )
    schema = r.schema() if columns is None else r.schema().select(columns)
    out: List[List[Any]] = []
    rows_it = r.rows(columns)
    try:
        for row in rows_it:
            if limit is not None and len(out) >= limit:
                break
            out.append(list(row))
    finally:
        rows_it.close()  # early break mid-shard: close the file now, not at GC
    return Table(schema, out)


def reader(paths, options: Optional[TFRecordOptions] = None, **option_kwargs: Any) -> DatasetReader:
    if options is not None:
        return DatasetReader(paths, options=options)
    return DatasetReader(paths, **option_kwargs)


def write(
    rows: Iterable[Sequence[Any]],
    schema: StructType,
    path: str,
    mode: str = "error",
    partition_by: Optional[List[str]] = None,
    options: Optional[TFRecordOptions] = None,
    **option_kwargs: Any,
) -> List[str]:
    if isinstance(rows, Table):
        schema = rows.schema if schema is None else schema
        rows = rows.rows
    return write_dataset(
        rows, schema, path, mode=mode, partition_by=partition_by,
        options=options, **option_kwargs,
    )


__all__ = [
    "TFRecordDataSource",
    "DatasetReader",
    "DatasetWriter",
    "ShardReader",
    "ShardWriter",
    "Shard",
    "Table",
    "read",
    "write",
    "reader",
    "write_dataset",
    "discover_shards",
    "has_success_marker",
]
