"""Shard discovery and Hive-style partition-directory layout.

Covers what the reference delegates to Hadoop/Spark path machinery: glob
expansion (README.md: "can accept standard Hadoop globbing expressions"),
`col=value` partition directories produced by ``partitionBy`` (README.md
partitionBy example: output dirs ``number=1  number=2  number=8`` plus
``_SUCCESS``), partition-column value escaping, and partition-column type
inference on read (Spark's partition discovery infers long/double/string).
"""

from __future__ import annotations

import glob as _glob
import os
import re
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_tfrecord.schema import DataType, DoubleType, LongType, StringType

SUCCESS_FILE = "_SUCCESS"
HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"
TEMP_PREFIX = "_temporary"

# Characters that must be %-escaped in partition directory names (the set
# Hive/Spark escape in ExternalCatalogUtils).
_ESCAPE_CHARS = set('"#%\'*/:=?\\\x7f{[]^')


def escape_partition_value(value: str) -> str:
    out = []
    for ch in value:
        if ch in _ESCAPE_CHARS or ord(ch) < 0x20:
            out.append(f"%{ord(ch):02X}")
        else:
            out.append(ch)
    return "".join(out)


def unescape_partition_value(value: str) -> str:
    return re.sub("%([0-9A-Fa-f]{2})", lambda m: chr(int(m.group(1), 16)), value)


def format_partition_value(value: Any) -> str:
    """Render a partition value the way Spark renders it into a dir name."""
    if value is None:
        return HIVE_DEFAULT_PARTITION
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        # Spark uses Java Double.toString; Python repr matches for typicals.
        return repr(value)
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


def partition_dir(columns: Sequence[str], values: Sequence[Any]) -> str:
    """Relative directory path ``c1=v1/c2=v2/...`` for one partition tuple."""
    parts = []
    for col, val in zip(columns, values):
        rendered = format_partition_value(val)
        if rendered != HIVE_DEFAULT_PARTITION:
            rendered = escape_partition_value(rendered)
        parts.append(f"{escape_partition_value(col)}={rendered}")
    return os.path.join(*parts) if parts else ""


def parse_partition_component(component: str) -> Optional[Tuple[str, Optional[str]]]:
    """Parse one ``col=value`` path component; None if not partition-shaped."""
    if "=" not in component:
        return None
    col, _, raw = component.partition("=")
    if not col:
        return None
    if raw == HIVE_DEFAULT_PARTITION:
        return unescape_partition_value(col), None
    return unescape_partition_value(col), unescape_partition_value(raw)


# Strict numeric shapes for partition-value classification, mirroring the
# JVM parses Spark's inference rides on: Long.parseLong (no trimming, no
# underscore separators, no 'inf') and Double.parseDouble (trims whitespace,
# accepts exact-case 'NaN'/'Infinity'). Python's int()/float() are more
# permissive ('1_0', lowercase 'inf'/'nan') — those must classify as
# strings, or mixed datasets silently coerce. The Java FloatTypeSuffix
# ('1.5f') is deliberately not accepted: the read-side cast uses Python
# float(), which cannot parse it.
_PARTITION_LONG_RE = re.compile(r"[+-]?\d+\Z")
_PARTITION_DOUBLE_RE = re.compile(
    r"[+-]?(NaN|Infinity|(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?)\Z"
)


def infer_partition_type(values: Iterable[Optional[str]]) -> DataType:
    """Spark-style partition column type inference: long -> double -> string."""
    saw_long, saw_double = True, True
    for v in values:
        if v is None:
            continue
        if _PARTITION_LONG_RE.match(v):
            continue
        saw_long = False
        if not _PARTITION_DOUBLE_RE.match(v.strip()):
            saw_double = False
            break
    if saw_long:
        return LongType()
    if saw_double:
        return DoubleType()
    return StringType()


def cast_partition_value(raw: Optional[str], dtype: DataType):
    if raw is None:
        return None
    if isinstance(dtype, LongType):
        return int(raw)
    if isinstance(dtype, DoubleType):
        return float(raw)
    return raw


@dataclass(frozen=True)
class Shard:
    """One TFRecord file plus the partition values encoded in its path.

    The unit of parallelism: the reference reads one Spark task per file
    (isSplitable=false, DefaultSource.scala:26-29); here one shard maps to
    one slot of the data-parallel mesh axis / one decode worker.
    """

    path: str
    size: int
    partition_values: Tuple[Tuple[str, Optional[str]], ...] = ()

    @property
    def partitions(self) -> Dict[str, Optional[str]]:
        return dict(self.partition_values)


def is_data_file(name: str) -> bool:
    """Hidden/metadata files (_SUCCESS, _temporary, .crc...) are not data."""
    return not (name.startswith("_") or name.startswith("."))


def expand_paths(paths) -> List[str]:
    """Expand files/dirs/globs into a flat list of concrete roots. Scheme'd
    URLs (``gs://``, ``memory://``, ...) expand through the pluggable FS
    layer (the reference gets this from Hadoop's FileSystem.globStatus)."""
    from tpu_tfrecord import fs as _fs

    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        fsys = _fs.filesystem_for(p)
        if _glob.has_magic(p):
            matches = fsys.glob(p)
            if not matches:
                raise FileNotFoundError(f"Path does not match any files: {p}")
            out.extend(matches)
        else:
            if not fsys.exists(p):
                raise FileNotFoundError(f"Path does not exist: {p}")
            out.append(p)
    return out


def discover_shards(paths) -> List[Shard]:
    """Find all data files under the input paths, with partition values
    parsed from ``col=value`` directory components below each root.

    Deterministic order (sorted walk) — the global shard order every host
    must agree on for multi-host ingestion (SURVEY.md §5 checkpoint plan).
    """
    from tpu_tfrecord import fs as _fs

    shards: List[Shard] = []
    for root in expand_paths(paths):
        fsys = _fs.filesystem_for(root)
        if fsys.isfile(root):
            shards.append(Shard(root, fsys.size(root)))
            continue
        root_norm = fsys.normalize(root).rstrip("/")
        for fpath, fsize in fsys.walk_files(root, is_data_file):
            rel = os.path.dirname(fpath)[len(root_norm) :].strip("/")
            pvals: List[Tuple[str, Optional[str]]] = []
            for comp in rel.split("/"):
                parsed = parse_partition_component(comp) if comp else None
                if parsed is not None:
                    pvals.append(parsed)
            shards.append(Shard(fpath, fsize, tuple(pvals)))
    return shards


def interleave(items: Sequence, slot: int, count: int) -> List:
    """The ONE owner of the deterministic interleaved assignment: slot ``s``
    of ``count`` takes items ``i`` with ``i % count == s``. Every layer that
    splits the global shard order — per-host assignment (tpu.mesh), the
    dataset's process slot, and the data-service dispatcher's shard→worker
    leases — routes through this so they can never disagree about who owns
    what."""
    if count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    if not 0 <= slot < count:
        raise ValueError(f"slot must be in [0, {count}), got {slot}")
    return [it for i, it in enumerate(items) if i % count == slot]


def interleave_owner(index: int, count: int) -> int:
    """The inverse view of ``interleave``: which slot owns item ``index``."""
    if count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    return index % count


def partition_columns_of(shards: Sequence[Shard]) -> List[str]:
    """Union of partition column names across shards, in first-seen order."""
    cols: List[str] = []
    for sh in shards:
        for col, _ in sh.partition_values:
            if col not in cols:
                cols.append(col)
    return cols


def new_shard_filename(task_id: int, ext: str, job_uuid: Optional[str] = None) -> str:
    """Spark-style part-file name: ``part-00000-<uuid>.tfrecord[.gz]``."""
    job_uuid = job_uuid or uuid.uuid4().hex
    return f"part-{task_id:05d}-{job_uuid}{ext}"


def has_success_marker(path: str) -> bool:
    from tpu_tfrecord import fs as _fs

    target = os.path.join(path, SUCCESS_FILE)
    return _fs.filesystem_for(target).exists(target)


def write_success_marker(path: str) -> None:
    from tpu_tfrecord import fs as _fs

    target = os.path.join(path, SUCCESS_FILE)
    _fs.filesystem_for(target).touch(target)
