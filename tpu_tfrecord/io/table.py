"""A minimal in-memory table: rows + schema.

The DataFrame stand-in for tests and small jobs — the reference's user-facing
currency is a Spark DataFrame; the TPU framework's real currency is columnar
batches feeding jax.Array (tpu_tfrecord.columnar / tpu_tfrecord.tpu), but a
row-oriented Table keeps API parity for the long tail of uses (round-trip
tests, inspection, small exports).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, List, Sequence

from tpu_tfrecord.schema import StructType


@dataclass
class Table:
    schema: StructType
    rows: List[List[Any]]

    def __len__(self) -> int:
        return len(self.rows)

    def __iter__(self) -> Iterator[List[Any]]:
        return iter(self.rows)

    def column(self, name: str) -> List[Any]:
        idx = self.schema.field_index(name)
        return [row[idx] for row in self.rows]

    def select(self, names: Sequence[str]) -> "Table":
        selected = self.schema.select(list(names))  # validates, names available
        idxs = [self.schema.field_index(n) for n in names]
        return Table(selected, [[r[i] for i in idxs] for r in self.rows])

    def sort_by(self, name: str) -> "Table":
        idx = self.schema.field_index(name)
        return Table(self.schema, sorted(self.rows, key=lambda r: (r[idx] is None, r[idx])))

    def to_dicts(self) -> List[dict]:
        names = self.schema.names
        return [dict(zip(names, row)) for row in self.rows]
