"""Dataset writer: save modes, Hive-style partitionBy, atomic commit.

TPU-native re-implementation of the reference's write path (SURVEY.md §3.2):
what Spark's FileFormatWriter + DefaultSource.prepareWrite +
TFRecordOutputWriter do together —

- save modes overwrite / append / ignore / error  (Spark semantics, pinned by
  reference TFRecordIOSuite.scala:184-237)
- ``partitionBy`` routes rows into ``col=value`` directories with the
  partition columns STRIPPED from the written records (README.md:195-207)
- per-shard writers with codec-compressed streams and '.tfrecord' + codec
  extension file names (DefaultSource.scala:105-114,
  TFRecordOutputWriter.scala:12-43)
- job-level atomicity: shards are written under ``_temporary/<job>/`` and
  moved into place on commit, then a ``_SUCCESS`` marker is written — the
  idempotent-commit plan from SURVEY.md §5 (the reference gets this from
  Spark's commit protocol).

Parallel write pipeline (``write_workers`` / ``num_shards`` options): the
reference gets write-side parallelism for free from Spark's one-writer-per-
task FileFormatWriter; with no executor underneath, this writer pipelines
within the task instead. Worker threads do the CPU-heavy stages — partition
slicing, native batch encode (GIL released), TFRecord framing + CRC, and
per-slab codec compression (wire.compress_chunk) — while the planner thread
routes slabs round-robin over per-partition shard streams and a FIFO
committer appends finished slabs in plan order. The bounded in-flight queue
provides backpressure; the plan-order sequencer makes the PIPELINE's output
bytes a pure function of (rows, options) — never of worker timing or worker
count (write_workers=1 vs N with fixed num_shards is byte-identical). The
default configuration (write_workers=1, num_shards unset) instead takes the
legacy single-threaded path and stays byte-identical to older releases;
with a codec its single-stream output legitimately differs from the
pipeline's per-slab chunked streams.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpu_tfrecord import fs as _fs, telemetry, wire
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.metrics import METRICS, logger, timed
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import StructType
from tpu_tfrecord.serde import TFRecordSerializer, encode_row
from tpu_tfrecord.tracing import trace

SAVE_MODES = ("error", "errorifexists", "overwrite", "append", "ignore")

# Rows buffered per partition before a slab is handed to the worker pool
# (write_rows pipelined path). Big enough that framing/compression dominate
# the per-slab overhead; small enough that depth*slab memory stays modest.
_ROW_SLAB = 4096

# Max rows per pipeline work item: large submissions are split so the pool
# load-balances (a handful of whole-batch slabs over N workers leaves
# workers idle in the tail round). A plan-time constant, so chunk
# boundaries — and therefore compressed bytes — stay deterministic.
_PIPE_SLAB = 8192


class ShardWriter:
    """Per-shard output file: serialize each row, frame it, stream it out.

    The TFRecordOutputWriter equivalent (reference TFRecordOutputWriter.scala:
    12-44): one instance per (task, partition-dir), owning one output stream.
    """

    def __init__(self, path: str, schema: StructType, options: TFRecordOptions):
        self.path = path
        self._serializer = TFRecordSerializer(schema)
        self._record_type = options.record_type
        self._fh = wire.open_compressed(path, "wb", options.codec)
        self._writer = wire.RecordWriter(self._fh)

    def write(self, row: Sequence[Any]) -> None:
        self._writer.write(encode_row(self._serializer, self._record_type, row))

    def write_serialized(self, record: bytes) -> None:
        self._writer.write(record)

    def write_framed(self, framed: "bytes | memoryview", n_records: int) -> None:
        """Write an already-framed record stream (native encoder output)."""
        self._fh.write(framed)
        self._writer.records_written += n_records
        self._writer.bytes_written += len(framed)

    @property
    def records_written(self) -> int:
        return self._writer.records_written

    def close(self) -> None:
        self._fh.close()


class DatasetWriter:
    """Partition-aware, save-mode-aware dataset writer."""

    def __init__(
        self,
        output_path: str,
        schema: StructType,
        options: Optional[TFRecordOptions] = None,
        partition_by: Optional[List[str]] = None,
        mode: str = "error",
        max_records_per_file: Optional[int] = None,
        write_success: bool = True,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        mode = (mode or "error").lower()
        if mode not in SAVE_MODES:
            raise ValueError(f"Unknown save mode {mode!r}; one of {SAVE_MODES}")
        self.output_path = os.fspath(output_path)
        # The pluggable FS (the reference's Hadoop FileSystem seam): local
        # paths use the standard library; URLs go through fsspec. On object
        # stores without atomic rename the commit is copy+delete (see
        # tpu_tfrecord.fs docstring).
        self.fs = _fs.filesystem_for(self.output_path)
        self.options = options or TFRecordOptions()
        self.mode = mode
        self.partition_by = list(partition_by or [])
        # ctor arg wins over the option-level spelling (max_records_per_shard)
        self.max_records_per_file = (
            max_records_per_file
            if max_records_per_file is not None
            else self.options.max_records_per_shard
        )
        self.write_workers = max(1, int(self.options.write_workers))
        self.num_shards = self.options.num_shards
        # Flight recorder opt-in (same process-global contract as the
        # dataset side: "on" enables, "off" leaves it alone).
        if self.options.trace == "on":
            telemetry.enable()
        if self.options.telemetry_port is not None:
            telemetry.ensure_exporter(self.options.telemetry_port)
        # Transient-fault policy for commit-side filesystem ops (shard open,
        # rename into place, _SUCCESS marker) — the remote-FS path is
        # demonstrably flaky (tests/test_fs_faults.py). An explicit policy
        # wins (injectable sleep/clock for tests); write_retries is the
        # option-level spelling; the default stays fail-fast.
        self.retry_policy = (
            retry_policy
            if retry_policy is not None
            else RetryPolicy(max_retries=int(self.options.write_retries))
        )
        # Multi-host jobs: each host commits its own shards with
        # write_success=False and a distinct task_id, then
        # tpu.distributed.finalize_distributed_write barriers and writes the
        # dataset-level marker once (host 0).
        self.write_success = write_success
        self.schema = schema
        for col in self.partition_by:
            if col not in schema:
                raise ValueError(f"partitionBy column {col!r} not in schema")
            from tpu_tfrecord.schema import ArrayType as _AT

            if isinstance(schema[col].data_type, _AT):
                raise ValueError(f"partition column {col!r} cannot be an array")
        if self.partition_by and len(self.partition_by) == len(schema):
            raise ValueError("cannot use all columns as partition columns")
        # Partition columns are stripped from the written records — the data
        # schema is the remainder (Spark strips them before the writer;
        # SURVEY.md §3.2 process-boundary note).
        self.data_schema = schema.drop(self.partition_by)
        self._pidx = [schema.field_index(c) for c in self.partition_by]
        self._didx = [
            i for i in range(len(schema)) if i not in set(self._pidx)
        ]

    # -- save-mode gate -----------------------------------------------------

    def _prepare_output(self) -> bool:
        """Apply save-mode semantics. Returns False if the write is a no-op
        (mode=ignore with existing output). Existence means PATH existence —
        an empty directory counts, matching Spark's save-mode checks."""
        out = self.output_path
        fs = self.fs
        exists = fs.exists(out)
        if exists:
            if self.mode in ("error", "errorifexists"):
                raise FileExistsError(
                    f"path {out} already exists (save mode: ErrorIfExists)"
                )
            if self.mode == "ignore":
                return False
            if self.mode == "overwrite":
                if fs.isdir(out):
                    # delete data and markers but PRESERVE the _temporary
                    # subtree: other jobs may have shards in flight there
                    for entry in fs.listdir(out):
                        if entry == p.TEMP_PREFIX:
                            continue
                        fp = os.path.join(out, entry)
                        if fs.isdir(fp):
                            fs.rmtree(fp)
                        else:
                            fs.remove(fp)
                else:
                    fs.remove(out)
        # remember whether THIS job created the output dir so abort() can
        # undo it — a leftover empty dir would flip error/ignore semantics
        # on retry now that existence is path-based
        self._created_output = not exists
        fs.makedirs(out)
        return True

    # -- the write job ------------------------------------------------------

    @property
    def use_pipeline(self) -> bool:
        """True when the slab pipeline handles this write. num_shards alone
        engages it (even at write_workers=1) so shard bytes depend only on
        the data and options, never on the worker count."""
        return self.write_workers > 1 or self.num_shards is not None

    def write_rows(self, rows: Iterable[Sequence[Any]], task_id: int = 0) -> List[str]:
        """Write all rows as one logical job; returns final shard paths."""
        if not self._prepare_output():
            return []
        job = _WriteJob(self, task_id)
        if self.use_pipeline:
            return _write_rows_pipelined(self, job, rows)
        writers: Dict[str, ShardWriter] = {}
        try:
            with timed("write", METRICS) as t:
                for row in rows:
                    rel = self._partition_rel_dir(row)
                    w = writers.get(rel)
                    if w is not None and (
                        self.max_records_per_file
                        and w.records_written >= self.max_records_per_file
                    ):
                        job.retire(writers.pop(rel))
                        w = None
                    if w is None:
                        w = writers[rel] = job.new_shard(rel)
                    w.write(self._strip_partitions(row))
                    t.records += 1
                    if t.records % 4096 == 0:
                        job.heartbeat()
            for w in writers.values():
                job.retire(w)
        except Exception:
            for w in writers.values():
                try:
                    w.close()
                except Exception:  # graftlint: swallow(close hygiene on the abort path; original error re-raised below)
                    pass
            job.abort()
            raise
        return job.commit()

    def _partition_rel_dir(self, row: Sequence[Any]) -> str:
        if not self.partition_by:
            return ""
        return p.partition_dir(self.partition_by, [row[i] for i in self._pidx])

    def _strip_partitions(self, row: Sequence[Any]) -> List[Any]:
        if not self.partition_by:
            return list(row)
        return [row[i] for i in self._didx]

    def write_batches(self, batches, task_id: int = 0) -> List[str]:
        """Write ColumnarBatches (the fast columnar path for Example and
        SequenceExample). With partition_by, batches must contain the
        partition columns; consecutive equal-key runs route to their
        ``col=value`` dirs. See module docstring for save-mode semantics."""
        return _write_batches(self, batches, task_id)


#: Name of the per-job liveness marker inside ``_temporary/<job>/``. It
#: records (pid, host) so a later job in the same output dir can tell a
#: CRASHED job's staging dir (same host, dead pid → sweep it) from a LIVE
#: concurrent writer's (leave it alone), plus a ``heartbeat`` timestamp the
#: job refreshes while writing — the lease that lets the sweep also reclaim
#: staging left by writers on OTHER hosts (where pid liveness is
#: unknowable): a heartbeat stale past the lease TTL means the writer
#: stopped stamping long ago.
_JOB_MARKER = "_JOB_META"

#: Seconds between heartbeat re-stamps of _JOB_META (throttle: one tiny
#: marker rewrite per interval, not per slab).
_HEARTBEAT_INTERVAL = 60.0

#: Default lease TTL for cross-host orphan sweeping: a staging dir whose
#: heartbeat is older than this is reclaimable from any host. Generous
#: (an hour) because false positives delete a LIVE job's staging — clock
#: skew across hosts must be far smaller than this for the lease to be
#: sound.
_LEASE_TTL = 3600.0


def job_marker_payload(task_id: int = 0, created: Optional[float] = None) -> bytes:
    """The ``_JOB_META`` liveness-marker JSON (pid/host/created/heartbeat)
    that ``sweep_orphan_jobs`` parses — ONE owner for the schema, shared by
    write jobs and cache populates (tpu_tfrecord.cache)."""
    now = time.time()
    return json.dumps(
        {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "created": created if created is not None else now,
            "heartbeat": now,
            "task_id": task_id,
        }
    ).encode("utf-8")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return True  # unknowable: err on the side of 'alive'
    return True


def sweep_orphan_jobs(
    fs,
    output_path: str,
    keep: Optional[str] = None,
    lease_ttl: float = _LEASE_TTL,
) -> List[str]:
    """Best-effort removal of ``_temporary/<job>`` staging dirs left by
    previous CRASHED jobs in ``output_path``. Two independent orphan
    tests, either is sufficient:

    - same host + dead pid (the PR 2 check: exact but local-only);
    - marker heartbeat stale past ``lease_ttl`` (works across hosts and on
      remote stores, where pid liveness is unknowable — live jobs re-stamp
      their heartbeat every ``_HEARTBEAT_INTERVAL`` seconds, so a lease
      this stale means the writer died or lost the volume long ago).

    Dirs without a readable marker, or stamped by another LIVE host within
    the lease, may belong to live writers — left alone. Returns the removed
    dirs. Never raises (hygiene must not fail a job)."""
    removed: List[str] = []
    root = os.path.join(output_path, p.TEMP_PREFIX)
    try:
        if not fs.isdir(root):
            return removed
        host = socket.gethostname()
        now = time.time()
        for entry in fs.listdir(root):
            if entry == keep:
                continue
            job_dir = os.path.join(root, entry)
            try:
                if not fs.isdir(job_dir):
                    continue
                with fs.open(os.path.join(job_dir, _JOB_MARKER), "rb") as fh:
                    meta = json.loads(fh.read().decode("utf-8"))
                pid = int(meta.get("pid", -1))
                beat = meta.get("heartbeat", meta.get("created"))
                lease_stale = (
                    beat is not None and now - float(beat) > lease_ttl
                )
                is_local = meta.get("host") == host and pid > 0
                if is_local and _pid_alive(pid):
                    # provably-live local writer: NEVER swept, even with a
                    # stale lease (heartbeat re-stamps are best-effort and
                    # can silently fail while the job keeps writing)
                    continue
                local_dead = is_local and not _pid_alive(pid)
                if not (local_dead or lease_stale):
                    continue
                why = "dead pid" if local_dead else "stale lease"
            except Exception:  # graftlint: swallow(no/unreadable marker: cannot judge ownership, leave the dir)
                continue  # no/unreadable marker: can't judge, leave it
            try:
                fs.rmtree(job_dir, ignore_errors=True)
                removed.append(job_dir)
                logger.warning(
                    "tfrecord.write swept orphaned staging dir %s "
                    "(crashed job, pid %s, %s)", job_dir, pid, why,
                )
            except Exception:  # graftlint: swallow(best-effort orphan staging sweep)
                pass
    except Exception:  # graftlint: swallow(best-effort orphan staging sweep)
        pass
    return removed


class _WriteJob:
    """Shared scaffolding for one logical write job: a job-scoped temp dir
    under ``_temporary/<job>/``, shard allocation, and the single end-of-job
    commit (rename into place + ``_SUCCESS``). A failed job leaves NOTHING in
    the final directory and never touches other LIVE jobs' temp dirs (it
    does sweep staging left by crashed jobs — see sweep_orphan_jobs)."""

    def __init__(self, writer: "DatasetWriter", task_id: int):
        self.writer = writer
        self.task_id = task_id
        self.job_id = uuid.uuid4().hex[:12]
        self.fs = writer.fs
        self.temp_root = os.path.join(writer.output_path, p.TEMP_PREFIX, self.job_id)
        # Concurrent jobs share the _temporary parent and a finishing job
        # opportunistically rmdirs it: makedirs can lose the race between
        # creating the parent and the job dir — retry, it converges.
        for _ in range(20):
            try:
                self.fs.makedirs(self.temp_root)
                break
            except FileNotFoundError:
                continue
        else:
            raise OSError(f"could not create job temp dir {self.temp_root}")
        self._created = time.time()
        self._last_beat = self._created
        self._write_marker()
        self.ext = writer.options.file_extension()
        self._seq: Dict[str, int] = {}
        self._final_of: Dict[str, str] = {}
        self._pending: List[str] = []
        # Directories known to exist (created by this job): partitioned
        # writes allocate many shards per partition dir, and on container
        # overlay filesystems each redundant makedirs costs a real syscall.
        self._made_dirs = {self.temp_root}

    def _write_marker(self) -> None:
        try:
            with self.fs.open(os.path.join(self.temp_root, _JOB_MARKER), "wb") as fh:
                fh.write(job_marker_payload(self.task_id, created=self._created))
        except OSError:
            pass  # marker is best-effort: its absence only disables sweeping

    def heartbeat(self) -> None:
        """Re-stamp the _JOB_META heartbeat (throttled to one marker write
        per _HEARTBEAT_INTERVAL): the lease the cross-host orphan sweep
        reads. Cheap enough to call per slab/batch."""
        now = time.time()
        if now - self._last_beat >= _HEARTBEAT_INTERVAL:
            self._last_beat = now
            self._write_marker()

    def _ensure_dir(self, path: str) -> None:
        if path not in self._made_dirs:
            self.fs.makedirs(path)
            self._made_dirs.add(path)

    def alloc_shard_path(self, rel: str = "") -> str:
        """Allocate the next shard file name under ``rel`` (``.c{n}`` counter
        per partition dir) WITHOUT opening it — the slab pipeline plans file
        identities on the planner thread and opens them commit-side."""
        n = self._seq.get(rel, 0)
        self._seq[rel] = n + 1
        fname = p.new_shard_filename(self.task_id, f".c{n:03d}{self.ext}", self.job_id)
        tmp_dir = os.path.join(self.temp_root, rel) if rel else self.temp_root
        self._ensure_dir(tmp_dir)
        tmp_path = os.path.join(tmp_dir, fname)
        final_dir = (
            os.path.join(self.writer.output_path, rel)
            if rel
            else self.writer.output_path
        )
        self._final_of[tmp_path] = os.path.join(final_dir, fname)
        return tmp_path

    def _commit_op(self, fn: Callable, recovered: Optional[Callable[[], bool]] = None):
        """One commit-side filesystem op under the writer's RetryPolicy.
        ``recovered()`` (optional) reports that a failed attempt actually
        took effect (e.g. the rename landed before the error surfaced) so
        the op is not blindly re-run."""
        pol = self.writer.retry_policy
        attempt = 0
        start = pol.clock()
        while True:
            try:
                return fn()
            except OSError:
                if recovered is not None:
                    try:
                        if recovered():
                            return None
                    except OSError:
                        pass
                attempt += 1
                if not pol.pause(attempt, start):
                    raise
                METRICS.count("write.commit_retries")

    def new_shard(self, rel: str = "") -> ShardWriter:
        # the open is a commit-side fs op (remote stores briefly refuse
        # creates): retryable — nothing is written yet
        path = self.alloc_shard_path(rel)
        return self._commit_op(
            lambda: ShardWriter(path, self.writer.data_schema, self.writer.options)
        )

    def retire(self, shard_writer: ShardWriter) -> None:
        """Close a finished shard; it stays in temp until commit()."""
        shard_writer.close()
        self._pending.append(shard_writer.path)
        self.heartbeat()

    def retire_path(self, path: str) -> None:
        """Register an already-closed temp file for the end-of-job commit."""
        self._pending.append(path)

    def commit(self) -> List[str]:
        with timed("write.commit", METRICS) as t, trace("tfr.write.commit"), \
                telemetry.span("write.commit", job=self.job_id) as sp:
            out = self._commit_inner()
            t.records = len(out)
            sp.set(shards=len(out))
        return out

    def _commit_inner(self) -> List[str]:
        # Pre-commit hygiene: staging left by a crashed previous job on this
        # host would pin the shared _temporary parent (the rmdir below would
        # fail forever) — sweep it before renaming into place.
        sweep_orphan_jobs(self.fs, self.writer.output_path, keep=self.job_id)
        written = []
        for tmp_path in self._pending:
            final_path = self._final_of[tmp_path]
            # inline _commit_shard with the job's dir cache: partitioned
            # jobs commit many shards into few directories

            def rename_one(tmp=tmp_path, final=final_path):
                self._ensure_dir(os.path.dirname(final))
                self.fs.rename(tmp, final)

            def rename_landed(tmp=tmp_path, final=final_path):
                # the failed attempt may have won anyway (remote stores can
                # error after the copy); don't re-run a landed rename
                self._made_dirs.discard(os.path.dirname(final))
                return self.fs.exists(final) and not self.fs.exists(tmp)

            self._commit_op(rename_one, recovered=rename_landed)
            written.append(final_path)
        self.fs.rmtree(self.temp_root, ignore_errors=True)
        try:
            # only removable once no other job is using the shared parent
            self.fs.rmdir(os.path.join(self.writer.output_path, p.TEMP_PREFIX))
        except OSError:
            pass
        if self.writer.write_success:
            self._commit_op(
                lambda: p.write_success_marker(self.writer.output_path)
            )
        return written

    def abort(self) -> None:
        self.fs.rmtree(self.temp_root, ignore_errors=True)
        # abort-side hygiene: also clear staging orphaned by CRASHED jobs so
        # a retry of this job starts from a clean _temporary
        sweep_orphan_jobs(self.fs, self.writer.output_path, keep=self.job_id)
        # if this job created the output dir, remove it again when empty so
        # a retry sees the same save-mode world as the first attempt
        if getattr(self.writer, "_created_output", False):
            try:
                self.fs.rmdir(os.path.join(self.writer.output_path, p.TEMP_PREFIX))
            except OSError:
                pass
            try:
                self.fs.rmdir(self.writer.output_path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Parallel slab pipeline (write_workers / num_shards)
# ---------------------------------------------------------------------------


def _payload_len(payload) -> int:
    """Byte length of a slab payload (bytes or a uint8 numpy array)."""
    return payload.nbytes if isinstance(payload, np.ndarray) else len(payload)


class _RawShardSink:
    """Commit-side output stream for one shard file of the slab pipeline.

    Receives finished slabs — framed records, already codec-compressed when
    the codec chunks (every supported codec today) — and appends them. With
    a hypothetical stream-only codec, ``codec`` is non-None and compression
    happens here on the committer instead."""

    def __init__(self, path: str, codec: Optional[str]):
        self.path = path
        self._fh = wire.open_compressed(path, "wb", codec)
        self.records_written = 0
        self.bytes_written = 0

    def write_slab(self, payload, n_records: int) -> None:
        self._fh.write(payload)
        self.records_written += n_records
        self.bytes_written += _payload_len(payload)

    def close(self) -> None:
        self._fh.close()


class _Stream:
    """One output stream of the pipeline: a (partition dir, shard index)
    slot. Plan side tracks allocated file paths and the record count of the
    current file (rollover); commit side tracks the open sink."""

    __slots__ = ("rel", "paths", "planned_records", "sink", "sink_path")

    def __init__(self, rel: str):
        self.rel = rel
        self.paths: List[str] = []
        self.planned_records = 0
        self.sink: Optional[_RawShardSink] = None
        self.sink_path: Optional[str] = None


class _SlabPipeline:
    """The parallel encode/compress/commit pipeline for one write job.

    Three roles, two thread groups:

    - PLANNER (caller thread): slices incoming work into slabs, assigns each
      slab a (partition dir, round-robin shard, file) target — including
      exact ``max_records_per_file`` rollover slicing, since the planner is
      the only place with deterministic running record counts — and submits
      encode+compress tasks to the pool. Submission blocks once ``depth``
      slabs are in flight (backpressure: memory stays ~depth slabs).
    - WORKERS (ThreadPoolExecutor): encode the slab to a framed TFRecord
      byte stream (native encoder releases the GIL; Python serde fallback
      otherwise) and compress it per-slab via wire.compress_chunk.
    - COMMITTER (caller thread, interleaved with planning): drains futures
      in FIFO submission order and appends payloads to their shard sinks.
      FIFO order + plan-time targets = byte-deterministic output for any
      worker count.
    """

    def __init__(self, writer: "DatasetWriter", job: "_WriteJob"):
        self.writer = writer
        self.job = job
        self.codec = writer.options.codec
        chunked = wire.codec_supports_chunks(self.codec)
        self._compress_in_worker = self.codec is not None and chunked
        self._sink_codec = None if chunked else self.codec
        self.num_shards = writer.num_shards or 1
        self.max_records = writer.max_records_per_file
        self.depth = max(4, 2 * writer.write_workers)
        self._pool = ThreadPoolExecutor(
            max_workers=writer.write_workers, thread_name_prefix="tfr-write"
        )
        self._inflight: Deque[Tuple[Future, _Stream, str]] = collections.deque()
        self._streams: Dict[Tuple[str, int], _Stream] = {}
        self._rr: Dict[str, int] = {}
        # EMA of the in-flight deque's fill fraction, sampled per submit:
        # ~1.0 means the planner keeps hitting the depth cap (the committer
        # is the bottleneck — "consumer_bound" for the write pipeline);
        # ~0.0 means slabs commit as fast as they are planned
        # (encode/planner-bound). telemetry.boundness_verdict reads it.
        self._occupancy = telemetry.OccupancyEma("write.occupancy")

    # -- planner side -------------------------------------------------------

    def submit(self, rel: str, start: int, stop: int, encode: Callable) -> None:
        """Plan rows [start, stop) of one slab source (``encode(s, e)`` must
        return the framed bytes for that half-open row range) onto ``rel``'s
        round-robin streams, splitting at _PIPE_SLAB and file-rollover
        points. The round-robin advances PER SLAB so even a single large
        batch spreads over all num_shards streams."""
        pos = start
        while pos < stop:
            shard = self._rr.get(rel, 0)
            self._rr[rel] = (shard + 1) % self.num_shards
            stream = self._streams.get((rel, shard))
            if stream is None:
                stream = self._streams[(rel, shard)] = _Stream(rel)
            if not stream.paths or (
                self.max_records and stream.planned_records >= self.max_records
            ):
                stream.paths.append(self.job.alloc_shard_path(rel))
                stream.planned_records = 0
            room = (
                self.max_records - stream.planned_records
                if self.max_records
                else stop - pos
            )
            take = min(room, stop - pos, _PIPE_SLAB)
            path = stream.paths[-1]
            self._occupancy.update(len(self._inflight) / self.depth)
            METRICS.gauge("write.inflight_slabs", len(self._inflight))
            if len(self._inflight) >= self.depth:
                METRICS.count("write.backpressure_waits")
            while len(self._inflight) >= self.depth:
                self._commit_one()
            fut = self._pool.submit(self._run_task, encode, pos, pos + take)
            self._inflight.append((fut, stream, path))
            stream.planned_records += take
            pos += take

    # -- worker side --------------------------------------------------------

    def _run_task(self, encode: Callable, start: int, stop: int):
        with trace("tfr.write.encode"), timed("write.encode", METRICS) as t, \
                telemetry.span("write.encode", rows=stop - start):
            framed = encode(start, stop)
            t.records = stop - start
            t.bytes = _payload_len(framed)
        if not self._compress_in_worker:
            return framed, stop - start
        with trace("tfr.write.compress"), timed("write.compress", METRICS) as t, \
                telemetry.span("write.compress", rows=stop - start):
            payload = wire.compress_chunk(self.codec, framed)
            t.records = stop - start
            t.bytes = len(payload)
        return payload, stop - start

    # -- committer side -----------------------------------------------------

    def _commit_one(self) -> None:
        fut, stream, path = self._inflight.popleft()
        payload, n_records = fut.result()  # re-raises worker errors
        self.job.heartbeat()  # lease stays fresh for long pipeline jobs
        with trace("tfr.write.io"), timed("write.io", METRICS) as t, \
                telemetry.span("write.io", rows=n_records):
            if stream.sink_path != path:
                # all slabs of a file precede slabs of the stream's next
                # file (FIFO commit of an in-order plan), so a path switch
                # means the previous file is complete
                if stream.sink is not None:
                    stream.sink.close()
                    self.job.retire_path(stream.sink_path)
                stream.sink = self.job._commit_op(
                    lambda: _RawShardSink(path, self._sink_codec)
                )
                stream.sink_path = path
            stream.sink.write_slab(payload, n_records)
            t.records = n_records
            t.bytes = _payload_len(payload)

    def finish(self) -> None:
        """Drain every in-flight slab in plan order and close all sinks."""
        while self._inflight:
            self._commit_one()
        self._pool.shutdown(wait=True)
        for stream in self._streams.values():
            if stream.sink is not None:
                stream.sink.close()
                self.job.retire_path(stream.sink_path)
                stream.sink = None

    def abort(self) -> None:
        """Best-effort teardown on error: cancel queued work, stop workers,
        close sinks. Every file lives under the job temp dir, so the
        caller's job.abort() removes all bytes written so far."""
        for fut, _, _ in self._inflight:
            fut.cancel()
        self._inflight.clear()
        self._pool.shutdown(wait=True, cancel_futures=True)
        for stream in self._streams.values():
            if stream.sink is not None:
                try:
                    stream.sink.close()
                except Exception:  # graftlint: swallow(abort hygiene: partial slabs already being discarded)
                    pass
                stream.sink = None


def _write_rows_pipelined(
    writer: "DatasetWriter", job: "_WriteJob", rows: Iterable[Sequence[Any]]
) -> List[str]:
    """Row-oriented slab pipeline: buffer ``_ROW_SLAB`` stripped rows per
    partition dir on the planner thread, serialize+frame+compress each slab
    on the workers. Buffer flush points depend only on row arrival order, so
    output is deterministic for any worker count."""
    record_type = writer.options.record_type
    buffers: Dict[str, List[Sequence[Any]]] = {}
    pipe = None
    try:
        # inside the try: a constructor error (unsupported schema, pool
        # limits) must still abort the job or it leaks temp/output dirs
        serializer = TFRecordSerializer(writer.data_schema)
        pipe = _SlabPipeline(writer, job)

        def row_task(buf: List[Sequence[Any]]) -> Callable:
            def encode(start: int, stop: int) -> bytes:
                return b"".join(
                    wire.encode_record(encode_row(serializer, record_type, row))
                    for row in buf[start:stop]
                )

            return encode

        with timed("write", METRICS) as t:
            for row in rows:
                rel = writer._partition_rel_dir(row)
                buf = buffers.setdefault(rel, [])
                buf.append(writer._strip_partitions(row))
                t.records += 1
                if len(buf) >= _ROW_SLAB:
                    pipe.submit(rel, 0, len(buf), row_task(buf))
                    buffers[rel] = []
            for rel, buf in buffers.items():
                if buf:
                    pipe.submit(rel, 0, len(buf), row_task(buf))
            pipe.finish()
    except Exception:
        if pipe is not None:
            pipe.abort()
        job.abort()
        raise
    return job.commit()


def _write_batches_pipelined(
    writer: "DatasetWriter", job: "_WriteJob", batches, encoder
) -> List[str]:
    """Columnar slab pipeline: the planner computes the vectorized partition
    plan per batch and submits one slab per (run, rollover slice); workers
    slice the batch and run the native encoder (GIL released; Python row
    fallback when the schema has no native encoder) plus per-slab codec
    compression."""
    from tpu_tfrecord.columnar import (
        ColumnarBatch, batch_to_rows, slice_batch, take_rows,
    )

    data_names = set(writer.data_schema.names)
    record_type = writer.options.record_type
    pipe = None
    try:
        # inside the try: a constructor error (unsupported schema, pool
        # limits) must still abort the job or it leaks temp/output dirs
        serializer = (
            TFRecordSerializer(writer.data_schema) if encoder is None else None
        )
        pipe = _SlabPipeline(writer, job)

        def batch_task(data_batch) -> Callable:
            def encode(start: int, stop: int):
                piece = (
                    data_batch
                    if start == 0 and stop == data_batch.num_rows
                    else slice_batch(data_batch, start, stop)
                )
                if encoder is not None:
                    return encoder.encode_batch(piece)
                return b"".join(
                    wire.encode_record(encode_row(serializer, record_type, row))
                    for row in batch_to_rows(piece, writer.data_schema)
                )

            return encode

        with timed("write", METRICS) as t:
            for batch in batches:
                if not writer.partition_by:
                    pipe.submit("", 0, batch.num_rows, batch_task(batch))
                    t.records += batch.num_rows
                    continue
                data_batch = ColumnarBatch(
                    {k: v for k, v in batch.columns.items() if k in data_names},
                    batch.num_rows,
                )
                order, runs = _partition_plan(batch, writer)
                if order is not None:
                    data_batch = take_rows(data_batch, order)
                task = batch_task(data_batch)
                for rel, start, stop in runs:
                    pipe.submit(rel, start, stop, task)
                t.records += batch.num_rows
            pipe.finish()
    except Exception:
        if pipe is not None:
            pipe.abort()
        job.abort()
        raise
    return job.commit()


def _partition_codes(batch, writer: "DatasetWriter") -> np.ndarray:
    """Factorize the partition-key tuple of every row into one int64 code
    per row (equal codes <=> equal key tuples, nulls distinct from every
    value). One vectorized np.unique pass per partition column — replaces
    the per-row Python comparisons that made interleaved-key routing
    row-at-a-time (VERDICT r4 item 6)."""
    n = batch.num_rows
    combined: Optional[np.ndarray] = None
    for name in writer.partition_by:
        col = batch[name]
        if col.blob is not None:
            vals = np.empty(n, dtype=object)
            vals[:] = col.blobs
        else:
            vals = col.values
        if col.mask is not None and not col.mask.all():
            valid = np.asarray(col.mask, dtype=bool)
            codes = np.empty(n, dtype=np.int64)
            uniq, inv = np.unique(vals[valid], return_inverse=True)
            codes[valid] = inv
            codes[~valid] = len(uniq)  # null: its own code
            k = len(uniq) + 1
        else:
            _, inv = np.unique(vals, return_inverse=True)
            codes = inv.astype(np.int64)
            k = max(1, int(codes.max()) + 1) if n else 1
        if combined is None:
            # first column: the per-column codes ARE the combination —
            # skipping the redundant re-factorization halves the unique()
            # work for the common single-column partitionBy
            combined = codes
            continue
        # re-factorize the running combination so codes stay compact (no
        # int64 overflow however many partition columns there are)
        _, combined = np.unique(combined * k + codes, return_inverse=True)
        combined = combined.astype(np.int64)
    assert combined is not None  # partition_by is non-empty at call sites
    return combined


def _partition_value_at(batch, writer: "DatasetWriter", row: int) -> list:
    """The partition-key values of one row, rendered like the row path
    (raw bytes for blob columns — p.format_partition_value applies the same
    lossy utf-8 handling; None for masked-out rows)."""
    values = []
    for name in writer.partition_by:
        col = batch[name]
        if col.mask is not None and not col.mask[row]:
            values.append(None)
        elif col.blob is not None:
            bo = col.blob_offsets
            values.append(bytes(col.blob[int(bo[row]) : int(bo[row + 1])]))
        else:
            values.append(col.values[row].item())
    return values


def _partition_plan(batch, writer: "DatasetWriter"):
    """Vectorized routing plan: (row_order, [(rel_dir, start, stop), ...]).

    Pre-clustered input (the common case for re-partition jobs) keeps its
    order (row_order None) and yields its few large contiguous runs.
    Interleaved keys would degenerate to per-row runs — and per-run encode
    calls — so when runs substantially exceed distinct keys the plan
    GROUPS instead: a stable argsort of the key codes clusters each key's
    rows (preserving their relative order), one gather reorders the batch,
    and each partition again emits as one large run. Either way the encoder
    sees big contiguous pieces, keeping interleaved-key partitionBy within
    a small factor of the unpartitioned columnar path."""
    n = batch.num_rows
    if n == 0:
        return None, []
    combined = _partition_codes(batch, writer)
    change = np.nonzero(combined[1:] != combined[:-1])[0] + 1
    starts = np.concatenate(([0], change))
    stops = np.concatenate((change, [n]))
    order = None
    # _partition_codes returns dense codes (its last step is a
    # return_inverse factorization), so the group count is just max+1
    n_groups = int(combined.max()) + 1
    if len(starts) > 2 * n_groups:
        order = np.argsort(combined, kind="stable")
        combined = combined[order]
        change = np.nonzero(combined[1:] != combined[:-1])[0] + 1
        starts = np.concatenate(([0], change))
        stops = np.concatenate((change, [n]))
    runs = []
    for s, e in zip(starts.tolist(), stops.tolist()):
        src_row = int(order[s]) if order is not None else s
        values = _partition_value_at(batch, writer, src_row)
        runs.append((p.partition_dir(writer.partition_by, values), s, e))
    return order, runs


def _write_batches(
    writer: "DatasetWriter", batches, task_id: int = 0
) -> List[str]:
    """Columnar write job: one native encode call per run (the fast write
    path for Example AND SequenceExample; falls back to per-row encoding
    when the schema has no native encoder). With partition_by, partition
    columns are stripped and consecutive equal-key runs route to their
    ``col=value`` directories."""
    from tpu_tfrecord import _native
    from tpu_tfrecord.columnar import ColumnarBatch, batch_to_rows, slice_batch

    # Config errors must raise BEFORE any filesystem mutation (overwrite
    # deletion, temp dirs): build the encoder and peek the first batch for
    # missing partition columns up front.
    encoder = _native.make_encoder(writer.data_schema, writer.options.record_type)
    import itertools

    batches = iter(batches)
    first = next(batches, None)
    if first is not None and writer.partition_by:
        missing = [c for c in writer.partition_by if c not in first.columns]
        if missing:
            raise ValueError(
                f"write_batches: partition columns {missing} not present in "
                f"the batch (have {sorted(first.columns)})"
            )
    batches = itertools.chain([first], batches) if first is not None else iter(())
    if not writer._prepare_output():
        return []
    job = _WriteJob(writer, task_id)
    if writer.use_pipeline:
        return _write_batches_pipelined(writer, job, batches, encoder)
    max_per_file = writer.max_records_per_file
    writers: Dict[str, ShardWriter] = {}
    data_names = set(writer.data_schema.names)

    def emit(rel: str, part, t) -> None:
        pos = 0
        while pos < part.num_rows:
            w = writers.get(rel)
            if w is not None and max_per_file and w.records_written >= max_per_file:
                job.retire(writers.pop(rel))
                w = None
            if w is None:
                w = writers[rel] = job.new_shard(rel)
            room = (
                max_per_file - w.records_written
                if max_per_file
                else part.num_rows - pos
            )
            take = min(room, part.num_rows - pos)
            piece = (
                part
                if (pos == 0 and take == part.num_rows)
                else slice_batch(part, pos, pos + take)
            )
            if encoder is not None:
                with timed("write.encode", METRICS) as te:
                    framed = encoder.encode_batch(piece)
                    te.records = piece.num_rows
                    te.bytes = framed.nbytes
                with timed("write.io", METRICS) as ti:
                    # zero-copy view; file objects accept any buffer
                    # (stream codecs compress inside this write, so the
                    # sequential path's io stage includes compression)
                    w.write_framed(framed.data, piece.num_rows)
                    ti.records = piece.num_rows
                    ti.bytes = framed.nbytes
            else:
                for row in batch_to_rows(piece, writer.data_schema):
                    w.write(row)
            t.records += piece.num_rows
            pos += take
        job.heartbeat()

    try:
        with timed("write", METRICS) as t:
            for batch in batches:
                if not writer.partition_by:
                    emit("", batch, t)
                    continue
                # strip partition columns; route runs to their directories
                data_batch = ColumnarBatch(
                    {k: v for k, v in batch.columns.items() if k in data_names},
                    batch.num_rows,
                )
                order, runs = _partition_plan(batch, writer)
                if order is not None:
                    from tpu_tfrecord.columnar import take_rows

                    data_batch = take_rows(data_batch, order)
                for rel, start, stop in runs:
                    emit(rel, slice_batch(data_batch, start, stop), t)
        for w in writers.values():
            job.retire(w)
    except Exception:
        for w in writers.values():
            try:
                w.close()
            except Exception:  # graftlint: swallow(close hygiene on the abort path; original error re-raised below)
                pass
        job.abort()
        raise
    return job.commit()


def write_dataset(
    rows: Iterable[Sequence[Any]],
    schema: StructType,
    path: str,
    mode: str = "error",
    partition_by: Optional[List[str]] = None,
    options: Optional[TFRecordOptions] = None,
    **option_kwargs: Any,
) -> List[str]:
    """One-call write API: ``write_dataset(rows, schema, path,
    mode='overwrite', partition_by=['date'], recordType='Example',
    codec='gzip')``."""
    opts = options or TFRecordOptions.from_map(option_kwargs)
    writer = DatasetWriter(path, schema, opts, partition_by=partition_by, mode=mode)
    return writer.write_rows(rows)
