"""Dataset reader: per-shard record iterators + partition-aware scans.

TPU-native re-implementation of the reference's read path (SURVEY.md §3.1):
DefaultSource.buildReader + TFRecordFileReader. One ShardReader per file
(the reference's one-Spark-task-per-file unit, isSplitable=false at
DefaultSource.scala:26-29), opened lazily, closed eagerly at EOF and
guaranteed closed via context-manager/close() (mirroring the task-completion
listener + early close at TFRecordFileReader.scala:34-57).

Partition columns parsed from ``col=value`` directories are appended to each
row (Spark does this in FileScanRDD outside the connector; here it is
explicit), with Spark-style type inference (long -> double -> string).
"""

from __future__ import annotations

import gzip
import time
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from tpu_tfrecord import telemetry, wire
from tpu_tfrecord.infer import infer_from_records, merge_type_maps, type_map_to_schema
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.io.paths import Shard
from tpu_tfrecord.metrics import METRICS, log_salvage_event, timed
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.schema import StructField, StructType
from tpu_tfrecord.serde import Row, TFRecordDeserializer, decode_record
from tpu_tfrecord.stall import StallError, guard_from_options
from tpu_tfrecord.tracing import trace


def _timed_open(open_fn, path: str, codec):
    """One owner for the shard-open instrumentation every span stream pays:
    the open's latency lands in the ``read.open`` histogram (shard opens
    are a classic straggler source on object stores) and, when the flight
    recorder is on, as an ``open`` span attributed to the shard."""
    with timed("read.open", METRICS), trace("tfr:open"), \
            telemetry.span("open", shard=path):
        return open_fn(path, codec)


def _timed_read(fh, want: int, path: str) -> bytes:
    """The read-side sibling of ``_timed_open``: one slab read under the
    ``read.io`` latency histogram and (recorder on) a ``read`` span. An
    exception propagates untouched — the span self-marks ``failed=1`` and
    no totals are recorded for the failed read."""
    with telemetry.span("read", shard=path) as sp:
        t0 = time.perf_counter()
        data = fh.read(want)
        dt = time.perf_counter() - t0
        sp.set(bytes=len(data))
    METRICS.add("read.io", nbytes=len(data), seconds=dt, latency=dt)
    return data


class CorruptQuotaError(Exception):
    """Internal escalation: a shard's ``max_corrupt_records`` quota is
    exhausted. Deliberately NOT a TFRecordCorruptionError/OSError subclass
    so it passes through the transient-retry nets untouched; the policy
    layer converts it to the configured ``corrupt_fallback`` behavior."""


class ShardSkip(Exception):
    """Internal signal: drop the rest of this shard (on_corrupt policy)."""


class SalvageTracker:
    """The ``on_event`` sink for one shard's salvage scan: logs each event
    as a structured warning, bumps the ``read.*`` counters, and enforces the
    per-shard policy (skip_shard escalates on the first event; skip_record
    escalates once ``max_corrupt_records`` is exceeded)."""

    def __init__(self, path: str, options: TFRecordOptions):
        self.path = path
        self.on_corrupt = options.on_corrupt
        self.quota = options.max_corrupt_records
        self.events = 0
        self._reported = 0  # high-water mark across transient-IO retries

    def reset(self) -> None:
        """Restart counting for a transient-IO retry re-scan: the same
        corrupt regions must not be double-counted against the quota, and
        (via the ``_reported`` high-water mark) must not re-increment the
        fleet counters or re-log — the salvage scan is deterministic, so
        event N of the re-scan is the same region as event N before."""
        self.events = 0

    def __call__(self, event: Dict[str, Any]) -> None:
        self.events += 1
        if self.events > self._reported:
            self._reported = self.events
            event = dict(event, path=self.path, policy=self.on_corrupt)
            log_salvage_event(**event)
            METRICS.count("read.corrupt_records")
            if event.get("resync_offset") is not None:
                METRICS.count("read.resyncs")
        if self.on_corrupt == "skip_shard":
            raise ShardSkip(
                f"corrupt frame at offset {event.get('offset')} in {self.path}"
            )
        if self.quota is not None and self.events > self.quota:
            raise CorruptQuotaError(
                f"{self.events} corrupt regions in {self.path} exceed "
                f"max_corrupt_records={self.quota}"
            )


# Codec-level decode failures that end a salvage scan: the TFRecord frames
# beyond a corrupt compressed region are unrecoverable (the decompressor
# loses sync), so these convert to one terminal 'codec' event instead of
# raising. Plain OSError is NOT here — it stays transient/retryable.
_CODEC_CORRUPTION = (
    wire.TFRecordCorruptionError,
    EOFError,
    zlib.error,
    gzip.BadGzipFile,
)


def salvage_spans_stream(
    path: str,
    on_event: Callable[[Dict[str, Any]], None],
    slab_bytes: int = 32 << 20,
    max_record_bytes: int = 1 << 30,
    codec: str = "auto",
    open_fn: Optional[Callable[[str, Optional[str]], Any]] = None,
) -> Iterator[tuple]:
    """Corruption-tolerant twin of ``scan_spans_stream``: yields
    (buf, offsets, lengths) span batches of VALID frames only, and instead
    of raising at the first bad frame, reports it through ``on_event`` and
    resyncs (wire.resync) to the next plausible header — every record
    before and after a corrupt region is salvaged. CRCs are always verified
    here: they are the detection mechanism.

    Events are dicts with ``offset`` (decoded-stream byte offset of the
    corrupt region), ``kind`` (``length_crc`` | ``data_crc`` | ``length`` |
    ``truncated`` | ``codec``), ``resync_offset`` (where scanning resumed;
    None when the rest of the stream was unrecoverable) and
    ``bytes_skipped``. ``on_event`` may raise to abort the scan (quota /
    skip-shard escalation); the exception propagates to the caller.

    Memory stays bounded exactly like the strict scanner: complete frames
    are yielded per slab and only a sub-frame tail (or the 11-byte resync
    window) carries between reads.
    """
    if codec == "auto":
        codec = wire.codec_from_path(path)
    if open_fn is None:
        open_fn = lambda p, c: wire.open_compressed(p, "rb", c)  # noqa: E731
    H, F = wire.HEADER_BYTES, wire.FOOTER_BYTES
    with _timed_open(open_fn, path, codec) as fh:
        buf = b""
        file_off = 0  # decoded-stream offset of buf[0]
        bad_at: Optional[int] = None  # absolute start of current corrupt region
        bad_kind = ""
        eof = False
        # An on_event exception mid-scan (quota / skip-shard escalation) is
        # DEFERRED until the current buffer's already-validated frames have
        # been yielded: everything salvaged before the escalation point is
        # delivered, and only then does the policy take over.
        escalate: Optional[BaseException] = None
        codec_dead = False  # a codec event already reported the stream loss
        while True:
            if not eof:
                want = slab_bytes
                if bad_at is None and len(buf) >= H:
                    # pending tail frame (header already CRC-validated and
                    # length-capped below): read enough to complete it
                    (declared,) = wire._LEN_STRUCT.unpack_from(buf, 0)
                    if declared <= max_record_bytes:
                        want = max(want, H + declared + F - len(buf))
                try:
                    data = _timed_read(fh, want, path)
                except _CODEC_CORRUPTION as e:
                    try:
                        on_event(
                            {
                                "kind": "codec",
                                "offset": file_off + len(buf),
                                "resync_offset": None,
                                "bytes_skipped": 0,
                                "error": str(e),
                            }
                        )
                    except BaseException as esc:  # graftlint: swallow(escalated after salvage accounting (escalate re-raised))
                        escalate = esc
                    data = b""
                    eof = True  # the decompressor lost sync: stream over
                    codec_dead = True
                if not data:
                    eof = True
                else:
                    buf += data
            spans: List[tuple] = []
            pos = 0
            n = len(buf)
            while escalate is None:
                if bad_at is not None:
                    r = wire.resync(buf, pos, max_record_bytes=max_record_bytes)
                    if r < 0:
                        # keep an 11-byte window: a header could straddle
                        # the slab boundary
                        pos = n if eof else max(pos, n - (H - 1))
                        break
                    try:
                        on_event(
                            {
                                "kind": bad_kind,
                                "offset": bad_at,
                                "resync_offset": file_off + r,
                                "bytes_skipped": file_off + r - bad_at,
                            }
                        )
                    except BaseException as esc:  # graftlint: swallow(escalated after salvage accounting (escalate re-raised))
                        escalate = esc
                        break
                    bad_at = None
                    pos = r
                if pos + H > n:
                    break
                (length,) = wire._LEN_STRUCT.unpack_from(buf, pos)
                (length_crc,) = wire._CRC_STRUCT.unpack_from(buf, pos + 8)
                if wire.masked_crc32c(buf[pos : pos + 8]) != length_crc:
                    bad_at, bad_kind = file_off + pos, "length_crc"
                    pos += 1
                    continue
                if length > max_record_bytes:
                    bad_at, bad_kind = file_off + pos, "length"
                    pos += 1
                    continue
                start = pos + H
                if start + length + F > n:
                    break  # tail: refill (or terminal truncation at EOF)
                (data_crc,) = wire._CRC_STRUCT.unpack_from(buf, start + length)
                if wire.masked_crc32c(buf[start : start + length]) != data_crc:
                    bad_at, bad_kind = file_off + pos, "data_crc"
                    pos += 1
                    continue
                spans.append((start, length))
                pos = start + length + F
            if spans:
                offsets = np.array([s for s, _ in spans], dtype=np.uint64)
                lengths = np.array([l for _, l in spans], dtype=np.uint64)
                yield buf, offsets, lengths
            if escalate is not None:
                raise escalate
            if pos:
                buf = buf[pos:]
                file_off += pos
            if eof:
                if bad_at is not None:
                    on_event(
                        {
                            "kind": bad_kind,
                            "offset": bad_at,
                            "resync_offset": None,
                            "bytes_skipped": file_off + len(buf) - bad_at,
                        }
                    )
                elif buf and not codec_dead:
                    # leftover partial frame after a codec failure is the
                    # SAME physical corruption the codec event already
                    # reported — a second event would double-charge the
                    # per-shard quota
                    on_event(
                        {
                            "kind": "truncated",
                            "offset": file_off,
                            "resync_offset": None,
                            "bytes_skipped": len(buf),
                        }
                    )
                return


class ShardReader:
    """Lazy iterator of rows from one TFRecord shard.

    The TFRecordFileReader equivalent: opens the (possibly compressed) stream
    on first ``next()``, decodes each record through the schema-driven
    deserializer, closes eagerly at EOF, and is safe to close twice.
    """

    def __init__(
        self,
        shard: Shard,
        data_schema: StructType,
        options: TFRecordOptions,
        partition_tail: Sequence[Any] = (),
    ):
        self.shard = shard
        self._options = options
        self._deserializer = TFRecordDeserializer(data_schema)
        self._partition_tail = list(partition_tail)
        self._guard = guard_from_options(options)
        self._fh = None
        self._reader = None
        self._closed = False

    def _open_stream(self, path: str, codec: Optional[str]):
        """Open a shard stream, under the stall guard when configured."""
        if self._guard is not None:
            return self._guard.open_compressed(path, codec)
        return wire.open_compressed(path, "rb", codec)

    def _ensure_open(self) -> None:
        if self._reader is None and not self._closed:
            codec = wire.codec_from_path(self.shard.path)
            self._fh = _timed_open(self._open_stream, self.shard.path, codec)
            self._reader = wire.RecordReader(self._fh, verify_crc=self._options.verify_crc)

    def close(self) -> None:
        self._closed = True
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None
                self._reader = None

    def __enter__(self) -> "ShardReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _stall_skipped(self, e: "StallError") -> bool:
        """Apply ``on_stall`` to a stall that escaped the retry nets: True
        when the policy says drop the rest of this shard (same accounting
        as on_corrupt='skip_shard', so epochs stay resumable), False when
        the caller must re-raise."""
        if self._options.on_stall != "skip_shard":
            return False
        log_salvage_event(
            path=self.shard.path, kind="shard_stalled", error=str(e)
        )
        METRICS.count("read.skipped_shards")
        return True

    def __iter__(self) -> Iterator[Row]:
        if self._options.on_corrupt != "raise":
            yield from self._iter_tolerant()
            return
        record_type = self._options.record_type
        tail = self._partition_tail
        # Time only the fetch+decode work, never the time the generator
        # spends suspended at yield (consumer compute is not read time).
        records = 0
        nbytes = 0
        seconds = 0.0
        clock = time.perf_counter
        try:
            self._ensure_open()
            if self._reader is None:
                return
            while True:
                t0 = clock()
                record = self._reader.read()
                if record is None:
                    seconds += clock() - t0
                    break
                row = decode_record(self._deserializer, record_type, record)
                seconds += clock() - t0
                records += 1
                nbytes += len(record)
                if tail:
                    row = row + tail
                yield row
        except StallError as e:
            if not self._stall_skipped(e):
                raise
        finally:
            self.close()
            METRICS.add("read", records=records, nbytes=nbytes, seconds=seconds)

    def _iter_tolerant(self) -> Iterator[Row]:
        """Row iteration under on_corrupt='skip_record'/'skip_shard': frames
        stream through the salvage scanner (which owns its file handle), so
        a corrupt frame costs one record — or, under skip_shard / quota
        escalation, the rest of this shard — never the whole read."""
        if self._closed:
            return
        opts = self._options
        tracker = SalvageTracker(self.shard.path, opts)
        record_type = opts.record_type
        deserializer = self._deserializer
        tail = self._partition_tail
        records = 0
        nbytes = 0
        seconds = 0.0
        clock = time.perf_counter
        try:
            # Same timing contract as the strict path: count fetch+decode,
            # never the time the generator spends suspended at yield.
            t0 = clock()
            open_fn = (
                self._guard.open_compressed if self._guard is not None else None
            )
            for buf, offsets, lengths in salvage_spans_stream(
                self.shard.path, on_event=tracker, open_fn=open_fn
            ):
                for o, l in zip(offsets.tolist(), lengths.tolist()):
                    record = bytes(buf[o : o + l])
                    row = decode_record(deserializer, record_type, record)
                    records += 1
                    nbytes += len(record)
                    if tail:
                        row = row + tail
                    seconds += clock() - t0
                    yield row
                    t0 = clock()
            seconds += clock() - t0
        except StallError as e:
            if not self._stall_skipped(e):
                raise
        except ShardSkip as e:
            log_salvage_event(
                path=self.shard.path, kind="shard_skipped", error=str(e)
            )
            METRICS.count("read.skipped_shards")
        except CorruptQuotaError as e:
            if opts.corrupt_fallback == "skip_shard":
                log_salvage_event(
                    path=self.shard.path, kind="shard_skipped", error=str(e)
                )
                METRICS.count("read.skipped_shards")
            else:
                raise wire.TFRecordCorruptionError(str(e)) from e
        finally:
            self.close()
            METRICS.add("read", records=records, nbytes=nbytes, seconds=seconds)


def scan_spans_stream(
    path: str,
    verify_crc: bool,
    slab_bytes: int = 32 << 20,
    max_record_bytes: int = 1 << 30,
    max_records: Optional[int] = None,
    make_hint=None,
    open_fn: Optional[Callable[[str, Optional[str]], Any]] = None,
) -> Iterator[tuple]:
    """Stream one shard as (buf, offsets, lengths) span batches — the ONE
    owner of the slab framing loop (bounded tail-carry: a partial trailing
    frame carries into the next slab; a declared length beyond
    max_record_bytes raises instead of buffering the rest of a corrupt
    shard). Used by io/dataset's two-pass decode path and by span-batch
    consumers like the native inference seqOp.

    ``max_records`` stops cleanly after that many records WITHOUT framing or
    CRC-checking the bytes beyond them — record-limited consumers (schema
    inference sampling) thereby match the lazy per-record reader on shards
    whose corruption lies past the limit. ``make_hint(fh)`` may return a
    ``hint(pos)`` readahead callback (io/dataset wires its sliding
    posix_fadvise window through this)."""
    from tpu_tfrecord import _native

    codec = wire.codec_from_path(path)
    if open_fn is None:
        open_fn = lambda p, c: wire.open_compressed(p, "rb", c)  # noqa: E731
    remaining = max_records
    with _timed_open(open_fn, path, codec) as fh:
        hint = make_hint(fh) if make_hint is not None else None
        carry = b""
        native = _native.available()
        while remaining is None or remaining > 0:
            if hint is not None:
                try:
                    hint(fh.tell())
                except (AttributeError, OSError, ValueError):
                    hint = None
            want = slab_bytes
            if len(carry) >= 8:
                declared = int.from_bytes(carry[:8], "little")
                if declared > max_record_bytes:
                    raise wire.TFRecordCorruptionError(
                        f"record length {declared} exceeds max_record_bytes "
                        f"({max_record_bytes}) in {path} — corrupt length field?"
                    )
                want = max(want, 16 + declared - len(carry))
            data = _timed_read(fh, want, path)
            if not data:
                if carry:
                    raise wire.TFRecordCorruptionError(
                        f"truncated TFRecord at end of {path}"
                    )
                return
            buf = carry + data if carry else data
            if native:
                offsets, lengths, consumed = _native.scan_partial(
                    buf, verify_crc, max_records=remaining
                )
            else:
                spans, consumed = wire.scan_buffer_partial(
                    buf, verify_crc, max_records=remaining
                )
                offsets = np.array([s for s, _ in spans], dtype=np.uint64)
                lengths = np.array([l for _, l in spans], dtype=np.uint64)
            if len(offsets) == 0:
                # not even one complete record yet: keep accumulating
                # (bounded by the declared-length check above)
                carry = buf
                continue
            carry = buf[consumed:]
            if remaining is not None:
                remaining -= len(offsets)
            yield buf, offsets, lengths


class DatasetReader:
    """Plan + execute a read over many shards with partition merging.

    The planning half mirrors DefaultSource.inferSchema/buildReader
    (DefaultSource.scala:31-39, 118-136); execution iterates shards in the
    deterministic discovery order.
    """

    def __init__(self, paths_in, options: Optional[TFRecordOptions] = None, **option_kwargs):
        self.options = options or TFRecordOptions.from_map(option_kwargs)
        self.shards = p.discover_shards(paths_in)
        self._partition_cols = p.partition_columns_of(self.shards)
        self._partition_types = {
            col: p.infer_partition_type(
                sh.partitions.get(col) for sh in self.shards
            )
            for col in self._partition_cols
        }
        self._schema: Optional[StructType] = None

    # -- schema -------------------------------------------------------------

    @property
    def partition_schema(self) -> StructType:
        return StructType(
            [
                StructField(c, self._partition_types[c], True)
                for c in self._partition_cols
            ]
        )

    def schema(self) -> StructType:
        """Full schema: data schema + appended partition columns.

        If the user supplied a schema it wins (reference: user schema skips
        inference, DefaultSource.scala:31-39); partition columns the user did
        not mention are appended.
        """
        if self._schema is not None:
            return self._schema
        if self.options.schema is not None:
            base = self.options.schema
        else:
            base = self._infer_data_schema()
        fields = list(base.fields)
        names = {f.name for f in fields}
        for col in self._partition_cols:
            if col not in names:
                fields.append(StructField(col, self._partition_types[col], True))
        self._schema = StructType(fields)
        return self._schema

    def data_schema(self) -> StructType:
        """Schema of what is physically inside the records (partition
        columns excluded)."""
        return self.schema().drop(self._partition_cols)

    _INFER_SLAB_BYTES = 32 << 20
    # effectively uncapped: the per-record reader this path replaces reads
    # records of ANY declared size, so inference must too — a real cap here
    # would make schema results depend on whether the native build is active
    _INFER_MAX_RECORD_BYTES = 1 << 62

    def _shard_type_map(self, shard: Shard) -> Dict[str, Any]:
        """One shard's seqOp: native wire-walk inference when available
        (GIL-released C++, ~80x the Python oracle and the thing that makes
        the thread-pooled all-files entry actually scale), Python oracle
        otherwise. Both honor infer_sample_limit identically — the limit is
        pushed into the span scan, so bytes past the sampled records are
        never framed or CRC-checked (exactly like the lazy per-record
        reader). Map parity pinned by tests/test_infer.py."""
        from tpu_tfrecord import _native

        limit = self.options.infer_sample_limit
        if (
            _native.available()
            and self.options.record_type != RecordType.BYTE_ARRAY
        ):
            from tpu_tfrecord.infer import type_map_from_precedences

            # With a small sample limit, a full-size slab would read (and on
            # a cold store, fetch) far more than the sample needs — size the
            # slab generously per record but keep the ceiling.
            slab = self._INFER_SLAB_BYTES
            if limit is not None:
                slab = min(slab, max(1 << 20, 4096 * limit))
            with _native.InferScanner(self.options.record_type) as scanner:
                for buf, offsets, lengths in scan_spans_stream(
                    shard.path,
                    self.options.verify_crc,
                    slab_bytes=slab,
                    max_record_bytes=self._INFER_MAX_RECORD_BYTES,
                    max_records=limit,
                ):
                    scanner.update(buf, offsets, lengths)
                return type_map_from_precedences(scanner.result())
        return infer_from_records(
            wire.read_records(shard.path, verify_crc=self.options.verify_crc),
            self.options.record_type,
            limit=limit,
        )

    def _salvage_type_map(self, shard: Shard) -> Dict[str, Any]:
        """Inference fallback over a corrupt shard: fold the type map over
        its salvageable records only. Events are deliberately NOT logged or
        counted here — the tolerant read that follows reports each region
        exactly once; inference double-counting would skew the fleet
        counters."""

        def records():
            for buf, offsets, lengths in salvage_spans_stream(
                shard.path, on_event=lambda _ev: None
            ):
                for off, length in zip(offsets.tolist(), lengths.tolist()):
                    yield bytes(buf[off : off + length])

        return infer_from_records(
            records(),
            self.options.record_type,
            limit=self.options.infer_sample_limit,
        )

    def _infer_data_schema(self) -> StructType:
        """First non-empty file whose records yield a non-empty schema —
        single scan per candidate file (the reference scans the winning file
        twice via hasSchema + getSchemaFromFile, DefaultSource.scala:36-37;
        we keep the first scan's result)."""
        if self.options.record_type == RecordType.BYTE_ARRAY:
            from tpu_tfrecord.infer import byte_array_schema

            return byte_array_schema()
        tolerant = self.options.on_corrupt != "raise"
        for shard in self.shards:
            if shard.size == 0:
                continue
            try:
                type_map = self._shard_type_map(shard)
            except wire.TFRecordCorruptionError:
                if not tolerant:
                    raise
                # under a tolerant read policy a corrupt candidate is not
                # fatal: infer from this shard's salvageable records (the
                # same frames the tolerant read will deliver)
                type_map = self._salvage_type_map(shard)
            if type_map:
                return type_map_to_schema(type_map)
        raise ValueError(
            "Could not infer schema: no non-empty TFRecord file found under "
            f"{[s.path for s in self.shards][:5]}..."
            if self.shards
            else "Could not infer schema: no input files"
        )

    def local_type_map(
        self, shards: Optional[Sequence[Shard]] = None, num_workers: int = 1
    ) -> Dict[str, Any]:
        """The per-host seqOp fold: type map over ``shards`` (default: all
        of this reader's shards).

        ``num_workers > 1`` runs the per-shard seqOp in a thread pool — the
        within-host analog of the reference's executor-parallel RDD
        aggregate (TensorFlowInferSchema.scala:40-43); the native wire walk
        releases the GIL, so shards scan concurrently on a multi-core host.
        Partials merge in shard order regardless of completion order, so
        the result is identical to the serial scan."""
        shards = self.shards if shards is None else list(shards)

        def seq_op(shard: Shard):
            try:
                return self._shard_type_map(shard)
            except Exception as e:
                # annotate WHICH shard failed (wire errors don't all carry
                # the path) without changing the exception type the callers
                # pin (corruption tests expect TFRecordCorruptionError)
                if (
                    e.args
                    and isinstance(e.args[0], str)
                    and shard.path not in e.args[0]
                ):
                    e.args = (f"{e.args[0]} (shard {shard.path})",) + e.args[1:]
                raise
        if num_workers > 1 and len(shards) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(num_workers, len(shards))
            ) as pool:
                partials = list(pool.map(seq_op, shards))
        else:
            partials = map(seq_op, shards)
        merged: Dict[str, Any] = {}
        for partial in partials:
            merged = merge_type_maps(merged, partial)
        return merged

    def infer_schema_all_files(self, num_workers: int = 1) -> StructType:
        """Inference over EVERY shard with the distributed merge algebra —
        the standalone TensorFlowInferSchema entry (SURVEY.md §3.3), and the
        per-host seqOp/combOp used by the multi-host path."""
        return type_map_to_schema(self.local_type_map(num_workers=num_workers))

    def infer_schema_multihost(self, num_workers: int = 1) -> StructType:
        """Full multi-host distributed inference, the reference's RDD
        ``aggregate`` end to end (TensorFlowInferSchema.scala:40-43): every
        process folds the seqOp over ITS deterministic shard slice (the
        same interleaved assignment the read path uses), then the partial
        type maps allgather-merge so all hosts return the identical schema.
        Requires jax.distributed to be initialized (single-process runs
        degrade to the local fold + identity merge). A local scan failure
        (corrupt shard, incompatible types within this slice) must NOT
        raise before the collective — that would leave every peer blocked
        in the allgather — so it rides the gather and re-raises on every
        host as DistributedInferenceError."""
        from tpu_tfrecord.tpu.distributed import merge_schema_across_hosts
        from tpu_tfrecord.tpu.mesh import assign_shards

        mine = assign_shards(self.shards)
        local: Dict[str, Any] = {}
        err: Optional[str] = None
        exc: Optional[BaseException] = None
        try:
            local = self.local_type_map(mine, num_workers=num_workers)
        except Exception as e:  # noqa: BLE001 — encoded into the collective  # graftlint: swallow(error encoded into the allgather, re-raised on every host)
            err = f"{type(e).__name__}: {e}"
            exc = e
        try:
            return merge_schema_across_hosts(local, local_error=err)
        except Exception as merged_err:
            if exc is not None:
                raise merged_err from exc  # keep the local traceback too
            raise

    # -- execution ----------------------------------------------------------

    def _shard_reader(
        self, shard: Shard, data_schema: StructType, required_partitions: List[str]
    ) -> ShardReader:
        tail = [
            p.cast_partition_value(
                shard.partitions.get(col), self._partition_types[col]
            )
            for col in required_partitions
        ]
        return ShardReader(shard, data_schema, self.options, tail)

    def readers(self, columns: Optional[List[str]] = None) -> List[ShardReader]:
        """One lazy reader per shard. ``columns`` prunes the schema the way
        Spark pushes requiredSchema into buildReader (DefaultSource.scala:131)."""
        full = self.schema()
        if columns is not None:
            required = full.select(columns)
        else:
            required = full
        part_set = set(self._partition_cols)
        data_schema = StructType([f for f in required if f.name not in part_set])
        required_partitions = [f.name for f in required if f.name in part_set]
        # Rows come out as data columns (in required order) + partition tail;
        # reorder to the exact required order if partitions interleave.
        readers = [
            self._shard_reader(sh, data_schema, required_partitions)
            for sh in self.shards
        ]
        out_order = [f.name for f in data_schema] + required_partitions
        want = [f.name for f in required]
        if out_order != want:
            perm = [out_order.index(n) for n in want]
            return [_ReorderingReader(r, perm) for r in readers]  # type: ignore[list-item]
        return readers

    def rows(self, columns: Optional[List[str]] = None) -> Iterator[Row]:
        for reader in self.readers(columns):
            yield from reader


class _ReorderingReader:
    """Wraps a ShardReader permuting each row to the required column order."""

    def __init__(self, inner: ShardReader, perm: List[int]):
        self._inner = inner
        self._perm = perm
        self.shard = inner.shard

    def close(self) -> None:
        self._inner.close()

    def __iter__(self) -> Iterator[Row]:
        perm = self._perm
        for row in self._inner:
            yield [row[i] for i in perm]
