"""Streaming dataset pipeline: shards -> columnar batches, with prefetch and
checkpoint/resume.

The reference is a batch connector with no resumability beyond the _SUCCESS
marker (SURVEY.md §5). The TPU-native pipeline adds what a training loop
needs (the Grain-style plan from SURVEY.md §5):

- deterministic global shard order + per-host assignment (the DP axis)
- batches that span shard boundaries (records/batch stays constant so the
  device-side step shape is static)
- a background prefetch thread with a bounded queue (decode overlaps the
  consumer's compute; with the C++ decoder the GIL is released during parse)
- O(1)-size iterator state: (epoch, shard cursor, record offset) — resuming
  re-opens one shard and skips ``record offset`` records, not the dataset.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import asdict, dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from tpu_tfrecord import _native, wire
from tpu_tfrecord.columnar import (
    Column,
    ColumnarBatch,
    ColumnarDecoder,
    concat_batches,
    slice_batch,
)
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.io.reader import DatasetReader
from tpu_tfrecord.metrics import METRICS, timed
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import StructType


@dataclass(frozen=True)
class IteratorState:
    """Grain-style resumable position. ``shard_cursor`` indexes THIS HOST's
    assigned shard list; ``record_offset`` counts records already consumed
    from that shard."""

    epoch: int = 0
    shard_cursor: int = 0
    record_offset: int = 0

    def to_json(self) -> Dict[str, int]:
        return asdict(self)

    @staticmethod
    def from_json(obj: Dict[str, int]) -> "IteratorState":
        return IteratorState(**obj)


class TFRecordDataset:
    """Plan a per-host streaming read of a TFRecord dataset.

    ``process_index/process_count`` select this host's shards from the
    deterministic global order (tpu.mesh.assign_shards semantics inline so
    this module stays importable without jax).
    """

    def __init__(
        self,
        paths,
        batch_size: int,
        options: Optional[TFRecordOptions] = None,
        columns: Optional[List[str]] = None,
        drop_remainder: bool = True,
        num_epochs: Optional[int] = 1,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        **option_kwargs: Any,
    ):
        self._reader = (
            DatasetReader(paths, options=options)
            if options is not None
            else DatasetReader(paths, **option_kwargs)
        )
        self.options = self._reader.options
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.num_epochs = num_epochs
        self.prefetch = prefetch
        full = self._reader.schema()
        part_cols = set(self._reader.partition_schema.names)
        wanted = full if columns is None else full.select(columns)
        # Columnar decode covers the physical record columns; requested
        # partition columns are materialized per row from shard metadata
        # (batches span shards, so this happens during batch assembly).
        self.schema: StructType = StructType(list(wanted.fields))
        self._data_schema = StructType([f for f in wanted if f.name not in part_cols])
        self._partition_fields = [f for f in wanted if f.name in part_cols]
        all_shards = self._reader.shards
        self.shards = [
            sh for i, sh in enumerate(all_shards) if i % process_count == process_index
        ]
        self._decoder = ColumnarDecoder(self._data_schema, self.options.record_type)
        self._native_decoder = _native.make_decoder(
            self._data_schema, self.options.record_type
        )

    # -- chunked decode stream with positional accounting --------------------
    #
    # Each shard is loaded (decompressed) into one buffer, frame-scanned in a
    # single native call, and decoded in large chunks (one C++ call per
    # chunk, GIL released). Chunks carry (epoch, cursor, start_offset) so any
    # row boundary maps back to an exact resume position.

    def _decode_chunk(self, buf, offsets, lengths) -> ColumnarBatch:
        if self._native_decoder is not None:
            return self._native_decoder.decode_spans(buf, offsets, lengths)
        records = [
            bytes(buf[o : o + l]) for o, l in zip(offsets.tolist(), lengths.tolist())
        ]
        return self._decoder.decode_batch(records)

    def _shard_spans(self, shard) -> tuple:
        """Load one shard fully and return (buf, offsets, lengths)."""
        codec = wire.codec_from_path(shard.path)
        with wire.open_compressed(shard.path, "rb", codec) as fh:
            buf = fh.read()
        if not buf:
            return buf, np.empty(0, np.uint64), np.empty(0, np.uint64)
        if _native.available():
            return (buf, *_native.scan(buf, self.options.verify_crc))
        spans = list(wire.scan_buffer(buf, self.options.verify_crc))
        offsets = np.array([s for s, _ in spans], dtype=np.uint64)
        lengths = np.array([l for _, l in spans], dtype=np.uint64)
        return buf, offsets, lengths

    def _chunk_stream(self, state: IteratorState) -> Iterator[tuple]:
        """Yield (chunk: ColumnarBatch, epoch, cursor, start_offset) from the
        resume point onward, across epochs."""
        chunk_records = max(self.batch_size, 2048)
        epoch = state.epoch
        while self.num_epochs is None or epoch < self.num_epochs:
            start_cursor = state.shard_cursor if epoch == state.epoch else 0
            for cursor in range(start_cursor, len(self.shards)):
                shard = self.shards[cursor]
                skip = (
                    state.record_offset
                    if (epoch == state.epoch and cursor == state.shard_cursor)
                    else 0
                )
                buf, offsets, lengths = self._shard_spans(shard)
                n = len(offsets)
                for start in range(skip, n, chunk_records):
                    stop = min(start + chunk_records, n)
                    with timed("decode", METRICS) as t:
                        chunk = self._decode_chunk(
                            buf, offsets[start:stop], lengths[start:stop]
                        )
                        t.records += chunk.num_rows
                        t.bytes += int(lengths[start:stop].sum())
                    if self._partition_fields:
                        self._attach_partition_chunk(chunk, cursor)
                    yield chunk, epoch, cursor, start
            epoch += 1

    def _attach_partition_chunk(self, chunk: ColumnarBatch, cursor: int) -> None:
        """Partition values are constant within a shard: materialize them as
        constant columns over the chunk."""
        from tpu_tfrecord.io.paths import cast_partition_value
        from tpu_tfrecord.schema import numpy_dtype

        n = chunk.num_rows
        for f in self._partition_fields:
            raw = self.shards[cursor].partitions.get(f.name)
            val = cast_partition_value(raw, f.data_type)
            col = Column(
                f.name,
                f.data_type,
                mask=np.full(n, val is not None, dtype=bool),
            )
            np_dt = numpy_dtype(f.data_type)
            if np_dt is None:
                item = val.encode("utf-8") if val is not None else b""
                col.blob = item * n
                col.blob_offsets = np.arange(n + 1, dtype=np.int64) * len(item)
            else:
                col.values = np.full(n, val if val is not None else 0, dtype=np_dt)
            chunk.columns[f.name] = col

    # -- batched iteration ---------------------------------------------------

    def batches(
        self, state: Optional[IteratorState] = None
    ) -> "CheckpointableIterator":
        return CheckpointableIterator(self, state or IteratorState())


class CheckpointableIterator:
    """Iterator of ColumnarBatch with a live, resumable ``state``.

    ``state()`` reflects the last batch YIELDED (not prefetched): restoring
    from it replays nothing and skips nothing, even though a background
    thread runs ahead of the consumer.
    """

    def __init__(self, dataset: TFRecordDataset, state: IteratorState):
        self._ds = dataset
        self._start = state
        self._consumed_state = state
        self._finished = None  # None=running, True=exhausted, Exception=failed
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, dataset.prefetch))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def _producer(self) -> None:
        ds = self._ds
        B = ds.batch_size
        try:
            # pending: [chunk, consumed_rows, epoch, cursor, chunk_start]
            pending: List[list] = []
            avail = 0
            for chunk, epoch, cursor, chunk_start in ds._chunk_stream(self._start):
                if self._stop.is_set():
                    return
                if chunk.num_rows == 0:
                    continue
                pending.append([chunk, 0, epoch, cursor, chunk_start])
                avail += chunk.num_rows
                while avail >= B:
                    if not self._emit_from(pending, B):
                        return
                    avail -= B
            if avail and not ds.drop_remainder:
                self._emit_from(pending, avail)
            self._queue.put(None)
        except BaseException as e:  # propagate to consumer
            self._queue.put(e)

    def _emit_from(self, pending: List[list], n: int) -> bool:
        """Assemble a batch of n rows from the front of the pending chunks;
        the resume state is the position after the batch's last row."""
        slices = []
        need = n
        end_pos = self._start
        while need:
            entry = pending[0]
            chunk, consumed, epoch, cursor, chunk_start = entry
            take = min(need, chunk.num_rows - consumed)
            slices.append(slice_batch(chunk, consumed, consumed + take))
            entry[1] = consumed + take
            need -= take
            end_pos = IteratorState(epoch, cursor, chunk_start + entry[1])
            if entry[1] >= chunk.num_rows:
                pending.pop(0)
        batch = concat_batches(slices)
        while not self._stop.is_set():
            try:
                self._queue.put((batch, end_pos), timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "CheckpointableIterator":
        return self

    def __next__(self) -> ColumnarBatch:
        if self._finished is not None:
            raise self._finished if not isinstance(self._finished, bool) else StopIteration
        item = self._queue.get()
        if item is None:
            self._finished = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._finished = item
            raise item
        batch, end_pos = item
        self._consumed_state = end_pos
        return batch

    def state(self) -> IteratorState:
        return self._consumed_state

    def close(self) -> None:
        self._stop.set()
        # Drain so the producer unblocks and exits.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self) -> "CheckpointableIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
