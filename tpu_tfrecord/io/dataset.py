"""Streaming dataset pipeline: shards -> columnar batches, with prefetch and
checkpoint/resume.

The reference is a batch connector with no resumability beyond the _SUCCESS
marker (SURVEY.md §5). The TPU-native pipeline adds what a training loop
needs (the Grain-style plan from SURVEY.md §5):

- deterministic global shard order + per-host assignment (the DP axis)
- batches that span shard boundaries (records/batch stays constant so the
  device-side step shape is static)
- a background prefetch thread with a bounded queue (decode overlaps the
  consumer's compute; with the C++ decoder the GIL is released during parse)
- O(1)-size iterator state: (epoch, shard cursor, record offset) — resuming
  re-opens one shard and skips ``record offset`` records, not the dataset.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import asdict, dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from tpu_tfrecord import wire
from tpu_tfrecord.columnar import Column, ColumnarBatch, ColumnarDecoder
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.io.reader import DatasetReader
from tpu_tfrecord.metrics import METRICS, timed
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.schema import StructType


@dataclass(frozen=True)
class IteratorState:
    """Grain-style resumable position. ``shard_cursor`` indexes THIS HOST's
    assigned shard list; ``record_offset`` counts records already consumed
    from that shard."""

    epoch: int = 0
    shard_cursor: int = 0
    record_offset: int = 0

    def to_json(self) -> Dict[str, int]:
        return asdict(self)

    @staticmethod
    def from_json(obj: Dict[str, int]) -> "IteratorState":
        return IteratorState(**obj)


class TFRecordDataset:
    """Plan a per-host streaming read of a TFRecord dataset.

    ``process_index/process_count`` select this host's shards from the
    deterministic global order (tpu.mesh.assign_shards semantics inline so
    this module stays importable without jax).
    """

    def __init__(
        self,
        paths,
        batch_size: int,
        options: Optional[TFRecordOptions] = None,
        columns: Optional[List[str]] = None,
        drop_remainder: bool = True,
        num_epochs: Optional[int] = 1,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        **option_kwargs: Any,
    ):
        self._reader = (
            DatasetReader(paths, options=options)
            if options is not None
            else DatasetReader(paths, **option_kwargs)
        )
        self.options = self._reader.options
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.num_epochs = num_epochs
        self.prefetch = prefetch
        full = self._reader.schema()
        part_cols = set(self._reader.partition_schema.names)
        wanted = full if columns is None else full.select(columns)
        # Columnar decode covers the physical record columns; requested
        # partition columns are materialized per row from shard metadata
        # (batches span shards, so this happens during batch assembly).
        self.schema: StructType = StructType(list(wanted.fields))
        self._data_schema = StructType([f for f in wanted if f.name not in part_cols])
        self._partition_fields = [f for f in wanted if f.name in part_cols]
        all_shards = self._reader.shards
        self.shards = [
            sh for i, sh in enumerate(all_shards) if i % process_count == process_index
        ]
        self._decoder = ColumnarDecoder(self._data_schema, self.options.record_type)

    # -- raw record stream with positional accounting -----------------------

    def _record_stream(self, state: IteratorState) -> Iterator[tuple]:
        """Yield (record_bytes, shard_cursor, record_offset_after) from the
        resume point onward, across epochs."""
        epoch = state.epoch
        while self.num_epochs is None or epoch < self.num_epochs:
            start_cursor = state.shard_cursor if epoch == state.epoch else 0
            for cursor in range(start_cursor, len(self.shards)):
                shard = self.shards[cursor]
                skip = (
                    state.record_offset
                    if (epoch == state.epoch and cursor == state.shard_cursor)
                    else 0
                )
                offset = 0
                for record in wire.read_records(
                    shard.path, verify_crc=self.options.verify_crc
                ):
                    offset += 1
                    if offset <= skip:
                        continue
                    yield record, epoch, cursor, offset
            epoch += 1

    # -- batched iteration ---------------------------------------------------

    def batches(
        self, state: Optional[IteratorState] = None
    ) -> "CheckpointableIterator":
        return CheckpointableIterator(self, state or IteratorState())


def _attach_partition_columns(
    batch: ColumnarBatch, cursors: List[int], ds: TFRecordDataset
) -> None:
    """Materialize requested partition columns per row: each record's value
    comes from the ``col=value`` path of the shard it was read from."""
    from tpu_tfrecord.io.paths import cast_partition_value
    from tpu_tfrecord.schema import numpy_dtype

    for f in ds._partition_fields:
        raw = [ds.shards[c].partitions.get(f.name) for c in cursors]
        vals = [cast_partition_value(r, f.data_type) for r in raw]
        mask = np.array([v is not None for v in vals], dtype=bool)
        col = Column(f.name, f.data_type, mask=mask)
        np_dt = numpy_dtype(f.data_type)
        if np_dt is None:  # string partition column
            col.blobs = [(v.encode("utf-8") if v is not None else b"") for v in vals]
        else:
            col.values = np.array(
                [v if v is not None else 0 for v in vals], dtype=np_dt
            )
        batch.columns[f.name] = col


class CheckpointableIterator:
    """Iterator of ColumnarBatch with a live, resumable ``state``.

    ``state()`` reflects the last batch YIELDED (not prefetched): restoring
    from it replays nothing and skips nothing, even though a background
    thread runs ahead of the consumer.
    """

    def __init__(self, dataset: TFRecordDataset, state: IteratorState):
        self._ds = dataset
        self._start = state
        self._consumed_state = state
        self._finished = None  # None=running, True=exhausted, Exception=failed
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, dataset.prefetch))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def _producer(self) -> None:
        ds = self._ds
        try:
            buf: List[bytes] = []
            cursors: List[int] = []
            end_pos = self._start
            for record, epoch, cursor, offset in ds._record_stream(self._start):
                buf.append(record)
                cursors.append(cursor)
                end_pos = IteratorState(epoch, cursor, offset)
                if len(buf) >= ds.batch_size:
                    if not self._emit(buf, cursors, end_pos):
                        return
                    buf, cursors = [], []
            if buf and not ds.drop_remainder:
                self._emit(buf, cursors, end_pos)
            self._queue.put(None)
        except BaseException as e:  # propagate to consumer
            self._queue.put(e)

    def _emit(
        self, records: List[bytes], cursors: List[int], end_pos: IteratorState
    ) -> bool:
        ds = self._ds
        with timed("decode", METRICS) as t:
            batch = ds._decoder.decode_batch(records)
            t.records += batch.num_rows
            t.bytes += sum(len(r) for r in records)
        if ds._partition_fields:
            _attach_partition_columns(batch, cursors, ds)
        while not self._stop.is_set():
            try:
                self._queue.put((batch, end_pos), timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "CheckpointableIterator":
        return self

    def __next__(self) -> ColumnarBatch:
        if self._finished is not None:
            raise self._finished if not isinstance(self._finished, bool) else StopIteration
        item = self._queue.get()
        if item is None:
            self._finished = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._finished = item
            raise item
        batch, end_pos = item
        self._consumed_state = end_pos
        return batch

    def state(self) -> IteratorState:
        return self._consumed_state

    def close(self) -> None:
        self._stop.set()
        # Drain so the producer unblocks and exits.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self) -> "CheckpointableIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
