"""Streaming dataset pipeline: shards -> columnar batches, with prefetch and
checkpoint/resume.

The reference is a batch connector with no resumability beyond the _SUCCESS
marker (SURVEY.md §5). The TPU-native pipeline adds what a training loop
needs (the Grain-style plan from SURVEY.md §5):

- deterministic global shard order + per-host assignment (the DP axis)
- batches that span shard boundaries (records/batch stays constant so the
  device-side step shape is static)
- a background prefetch thread with a bounded queue (decode overlaps the
  consumer's compute; with the C++ decoder the GIL is released during parse)
- O(1)-size iterator state: (epoch, shard cursor, record offset) — resuming
  re-opens one shard and skips ``record offset`` records, not the dataset.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
import weakref
from dataclasses import dataclass, field, fields, replace
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from tpu_tfrecord import _native, wire
from tpu_tfrecord.columnar import (
    Column,
    ColumnarBatch,
    ColumnarDecoder,
    concat_batches,
    slice_batch,
    take_rows,
)
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.io.reader import (
    CorruptQuotaError,
    DatasetReader,
    SalvageTracker,
    _timed_open,
    salvage_spans_stream,
)
from tpu_tfrecord import telemetry
from tpu_tfrecord.metrics import METRICS, log_salvage_event, timed
from tpu_tfrecord.options import TFRecordOptions
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.schema import StructType
from tpu_tfrecord.stall import (
    StallError,
    StallGuard,
    WatchdogError,
    guard_from_options,
)


# Injectable opener for the mmap fast path (it bypasses wire.open_compressed,
# so fault-injection tests patch THIS seam).
_open_local = open


class _ResizableQueue(queue.Queue):
    """queue.Queue whose maxsize can change while producers/consumers are
    live — the prefetch queue under autotune. Growing wakes blocked
    putters immediately; shrinking below the current fill simply blocks
    new puts until the consumer drains (items are never dropped)."""

    def resize(self, maxsize: int) -> None:
        with self.mutex:
            self.maxsize = max(1, int(maxsize))
            self.not_full.notify_all()


def _noop_hint(_pos: int) -> None:
    return


def _make_readahead(fh, size: int, window: int):
    """Sliding posix_fadvise(WILLNEED) hinter for a local file object.

    ``hint(pos)`` keeps [pos, pos + window) in flight: WILLNEED is
    asynchronous, so the kernel streams the window from the store while the
    decoder works the current chunk — cold reads run at streaming bandwidth
    instead of fault-per-page latency (see readahead_bytes in
    TFRecordDataset). Degrades to a no-op for objects without a real fd
    (fault-injection fakes, remote wrappers) or platforms without fadvise."""
    if not window or size <= 0:
        return _noop_hint
    try:
        fd = fh.fileno()
        os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_SEQUENTIAL)
    except (AttributeError, OSError, ValueError):
        return _noop_hint
    cursor = [0]

    def hint(pos: int) -> None:
        want_end = min(size, pos + window)
        if want_end > cursor[0]:
            try:
                os.posix_fadvise(
                    fd, cursor[0], want_end - cursor[0], os.POSIX_FADV_WILLNEED
                )
            except OSError:
                cursor[0] = size  # fd went away mid-shard: stop hinting
                return
            cursor[0] = want_end

    hint(0)
    return hint


@dataclass(frozen=True)
class IteratorState:
    """Grain-style resumable position. ``shard_cursor`` is the POSITION in
    the epoch's iteration order over this host's shard list (identity order,
    or the (seed, epoch)-derived permutation when shuffling);
    ``record_offset`` counts records already consumed from that shard.

    ``fingerprint`` identifies the dataset the position is valid FOR (global
    shard list + process slot + shuffle seed + record type): resuming
    against a changed dataset raises loudly instead of silently reading
    wrong or duplicate data. None (e.g. states from older checkpoints) skips
    the check. Excluded from equality — two states at the same position are
    the same position.

    With windowed row shuffling (``shuffle_window``), a position inside a
    window points at the WINDOW START and ``window_emitted`` counts batches
    already yielded from it: resume re-decodes the window from the stored
    position, re-derives the same permutation (seeded by the start
    position), and skips the emitted batches — state stays O(1)."""

    epoch: int = 0
    shard_cursor: int = 0
    record_offset: int = 0
    fingerprint: Optional[str] = field(default=None, compare=False)
    window_emitted: int = 0

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "epoch": self.epoch,
            "shard_cursor": self.shard_cursor,
            "record_offset": self.record_offset,
        }
        if self.fingerprint is not None:
            out["fingerprint"] = self.fingerprint
        if self.window_emitted:
            out["window_emitted"] = self.window_emitted
        return out

    @staticmethod
    def from_json(obj: Dict[str, Any]) -> "IteratorState":
        # Tolerate unknown keys: state files are forward-compatible within a
        # format version (e.g. 'fingerprint' was added without a version
        # bump), so a newer writer's extra fields must not crash an older
        # reader with a TypeError from the constructor.
        known = {f.name for f in fields(IteratorState)}
        return IteratorState(**{k: v for k, v in obj.items() if k in known})


class TFRecordDataset:
    """Plan a per-host streaming read of a TFRecord dataset.

    ``process_index/process_count`` select this host's shards from the
    deterministic global order (tpu.mesh.assign_shards semantics inline so
    this module stays importable without jax).
    """

    def __init__(
        self,
        paths,
        batch_size: int,
        options: Optional[TFRecordOptions] = None,
        columns: Optional[List[str]] = None,
        drop_remainder: bool = True,
        num_epochs: Optional[int] = 1,
        process_index: int = 0,
        process_count: int = 1,
        prefetch: int = 2,
        num_workers: int = 1,
        shuffle: bool = False,
        shuffle_window: int = 0,
        seed: int = 0,
        read_retries: int = 0,
        retry_policy: Optional[RetryPolicy] = None,
        hash_buckets: Optional[Dict[str, int]] = None,
        pack: Optional[Dict[str, List[str]]] = None,
        slab_bytes: int = 256 << 20,
        max_record_bytes: int = 1 << 30,
        use_mmap: bool = True,
        readahead_bytes: int = 64 << 20,
        **option_kwargs: Any,
    ):
        self._reader = (
            DatasetReader(paths, options=options)
            if options is not None
            else DatasetReader(paths, **option_kwargs)
        )
        self.options = self._reader.options
        # The ORIGINAL source spec (pre-discovery), kept for the data
        # service's job spec: decode workers re-discover the same shard
        # list from it (and prove agreement via the shard-list digest).
        self.source_paths = [
            os.fspath(p)
            for p in (paths if isinstance(paths, (list, tuple)) else [paths])
        ]
        # Flight recorder opt-in (tpu_tfrecord.telemetry): the recorder is
        # process-global (spans come from prefetch workers, the stall
        # guard, and writer threads on one shared timeline), so any
        # dataset built with trace="on" switches it on; trace="off"
        # deliberately does NOT switch it off — another live dataset may
        # be tracing.
        if self.options.trace == "on":
            telemetry.enable()
        if self.options.telemetry_port is not None:
            telemetry.ensure_exporter(self.options.telemetry_port)
        if self.options.telemetry_role is not None:
            # process identity for pulse lines, spool snapshots, and
            # merged-trace track labels (tpu_tfrecord.fleet); like the
            # recorder, the context is process-global
            telemetry.adopt(
                telemetry.current_context().with_role(
                    self.options.telemetry_role
                )
            )
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder
        self.num_epochs = num_epochs
        self.prefetch = prefetch
        full = self._reader.schema()
        part_cols = set(self._reader.partition_schema.names)
        wanted = full if columns is None else full.select(columns)
        # Columnar decode covers the physical record columns; requested
        # partition columns are materialized per row from shard metadata
        # (batches span shards, so this happens during batch assembly).
        self.schema: StructType = StructType(list(wanted.fields))
        self._data_schema = StructType([f for f in wanted if f.name not in part_cols])
        self._partition_fields = [f for f in wanted if f.name in part_cols]
        all_shards = self._reader.shards
        self.process_index = process_index
        self.process_count = process_count
        self._fingerprint: Optional[str] = None
        self.shards = p.interleave(all_shards, process_index, process_count)
        self._decoder = ColumnarDecoder(self._data_schema, self.options.record_type)
        # hash_buckets fuses categorical hashing into the native decode;
        # pack pushes column-group assembly down too ([B, K] matrices).
        # Validation is shared with NativeDecoder and runs eagerly here even
        # when the native library is unavailable — a config typo must fail
        # loudly, never silently disable the fast path.
        self.hash_buckets = _native.validate_hash_buckets(
            self._data_schema, hash_buckets
        )
        self.pack = _native.validate_pack(
            self._data_schema, pack, self.hash_buckets
        )
        self._native_decoder = _native.make_decoder(
            self._data_schema, self.options.record_type, self.hash_buckets, self.pack
        )
        self.num_workers = max(1, num_workers)
        self._scratch_local = threading.local()
        self.shuffle = shuffle
        # Row-level shuffling: permute rows across windows of
        # ``shuffle_window`` batches (0 = off). Deterministic (seeded by the
        # window's start position) and resumable in O(1) state — see
        # IteratorState.window_emitted. Composes with shard-order
        # ``shuffle`` for cross-shard mixing at two scales; TFRecord has no
        # index (reference: isSplitable=false, DefaultSource.scala:26-29),
        # so a GLOBAL row permutation is impossible without a sidecar —
        # windowed shuffle is the streaming-format-native equivalent of
        # tf.data's shuffle buffer, made deterministic.
        if shuffle_window < 0:
            raise ValueError(f"shuffle_window must be >= 0, got {shuffle_window}")
        self.shuffle_window = shuffle_window
        self.seed = seed
        self.read_retries = read_retries
        # One policy object owns retry budget + backoff for every transient
        # read fault (replacing three copy-pasted sleep loops). read_retries
        # stays as the simple spelling; an explicit RetryPolicy wins and
        # brings injectable sleep/clock for tests and deadline support.
        self.retry_policy = (
            retry_policy
            if retry_policy is not None
            else RetryPolicy(max_retries=read_retries)
        )
        self.slab_bytes = max(1, slab_bytes)
        self.max_record_bytes = max_record_bytes
        # mmap fast path for LOCAL uncompressed shards: decode reads the
        # page cache directly (no read() copy pass). Tradeoff: an async
        # disk/NFS error surfaces as SIGBUS instead of a retryable OSError —
        # set use_mmap=False on unreliable mounts to keep stream semantics.
        self.use_mmap = use_mmap
        # Stall defense (tpu_tfrecord.stall): None unless one of the
        # read_deadline_ms / open_deadline_ms / hedge_after_ms options is
        # set, so the default hot path pays nothing. The watchdog
        # (watchdog_timeout_ms) is wired separately in _parallel_chunks.
        self._stall_guard = guard_from_options(self.options)
        if self._stall_guard is None and self.options.autotune == "on":
            # autotune derives hedge/deadline thresholds from observed
            # p99s; an empty guard (no thresholds yet — opens run direct,
            # reads unwrapped) gives the controller a place to install
            # them; streams opened after an install are guarded
            self._stall_guard = StallGuard()
        if self._stall_guard is not None:
            # remote block fetches (PrefetchReader) self-heal under the
            # SAME budget as the shard-level retries
            self._stall_guard.retry_policy = self.retry_policy
        # Sliding posix_fadvise(WILLNEED) window for local shards (0 = off):
        # the kernel fetches ahead ASYNCHRONOUSLY while the C++ decoder
        # chews the current chunk, so cold (non-page-cache-resident) reads
        # run at the store's streaming bandwidth instead of
        # fault-per-page latency. Measured on the bench box: 152 MB/s
        # serial-faulting vs 1068 MB/s with WILLNEED issued ahead — the
        # difference between IO-bound and decode-bound cold ingest
        # (BASELINE.md configs[4], "read at line rate").
        self.readahead_bytes = max(0, readahead_bytes)
        # Columnar epoch cache (tpu_tfrecord.cache): the first pass over a
        # shard appends its decoded chunks to a per-shard entry; later
        # epochs — and later runs with the same decode fingerprint — serve
        # zero-copy mmap views instead of re-decoding, turning warm epochs
        # from CPU-bound into page-cache-bound. Engaged only under the
        # strict corruption policy: tolerant policies can legally emit
        # fewer rows than the shard holds, and caching a salvaged subset
        # would freeze one corruption outcome into later epochs.
        self._cache = None
        if self.options.cache == "auto":
            if self.options.on_corrupt != "raise":
                from tpu_tfrecord.metrics import logger as _logger

                _logger.warning(
                    "tfrecord.cache disabled: cache='auto' requires "
                    "on_corrupt='raise' (got %r)", self.options.on_corrupt,
                )
            else:
                from tpu_tfrecord import cache as _cache_mod

                # the exact column set a decoded chunk carries: data
                # columns (minus pack members when the native fused decode
                # folds them into group matrices) + group names +
                # requested partition fields
                fused = self._native_decoder is not None
                members = (
                    {m for ms in self.pack.values() for m in ms} if fused else set()
                )
                expect = (
                    {f.name for f in self._data_schema if f.name not in members}
                    | (set(self.pack) if fused else set())
                    | {f.name for f in self._partition_fields}
                )
                self._cache = _cache_mod.ShardCache(
                    self.options.cache_dir or _cache_mod.default_cache_dir(),
                    ident=self._cache_ident(),
                    max_bytes=self.options.cache_max_bytes,
                    expect_columns=expect,
                )
                self._cache_dtypes = self.chunk_dtypes()

    # -- chunked decode stream with positional accounting --------------------
    #
    # Each shard streams as slabs of complete frames (bounded memory, tail
    # carried between reads), each slab is decoded in large chunks (one C++
    # call per chunk, GIL released). Chunks carry (epoch, cursor,
    # start_offset) so any row boundary maps back to an exact resume
    # position.

    def _decode_chunk(self, buf, offsets, lengths) -> ColumnarBatch:
        if self._native_decoder is not None:
            return self._native_decoder.decode_spans(buf, offsets, lengths)
        records = [
            bytes(buf[o : o + l]) for o, l in zip(offsets.tolist(), lengths.tolist())
        ]
        return self._decoder.decode_batch(records)

    def _truncated_error(self, path: str) -> "wire.TFRecordCorruptionError":
        return wire.TFRecordCorruptionError(f"truncated TFRecord at end of {path}")

    def _check_declared_length(self, declared: int, path: str) -> None:
        """One owner for the corrupt-length contract (possible with
        verify_crc=False): an absurd declared length must raise promptly,
        never buffer or swallow the rest of a shard."""
        if declared > self.max_record_bytes:
            raise wire.TFRecordCorruptionError(
                f"record length {declared} exceeds max_record_bytes "
                f"({self.max_record_bytes}) in {path} — corrupt length field?"
            )

    def _shard_slabs(self, shard) -> Iterator[tuple]:
        """Stream one shard as (buf, offsets, lengths) slabs of complete
        frames — shards larger than memory never materialize whole (the tail
        of each read carries into the next slab). Compressed shards stream
        through the codec the same way. The framing loop itself (bounded
        tail-carry, declared-length guard) has ONE owner:
        io.reader.scan_spans_stream; this wires in the dataset's slab size,
        record-size cap, and sliding readahead window."""
        from tpu_tfrecord import fs as _fs
        from tpu_tfrecord.io.reader import scan_spans_stream

        def make_hint(fh):
            if _fs.has_scheme(shard.path):
                return None
            try:
                return _make_readahead(
                    fh, os.path.getsize(shard.path), self.readahead_bytes
                )
            except OSError:
                return None

        yield from scan_spans_stream(
            shard.path,
            self.options.verify_crc,
            slab_bytes=self.slab_bytes,
            max_record_bytes=self.max_record_bytes,
            make_hint=make_hint,
            open_fn=self._guarded_open_fn(),
        )

    def _guarded_open_fn(self):
        """The (path, codec) opener the span streams use: the stall guard's
        deadline/hedge open when configured, otherwise a plain
        wire.open_compressed that carries this dataset's retry policy to
        the remote block prefetcher (so PrefetchReader fetches self-heal
        from the exact byte offset under the same budget the shard-level
        retries use)."""
        if self._stall_guard is not None:
            return self._stall_guard.open_compressed
        pol = self.retry_policy

        def open_fn(path, codec):
            from tpu_tfrecord import fs as _fs

            # local paths keep the exact legacy call shape (tests stub
            # wire.open_compressed with 3-arg fakes; the policy only
            # matters for the remote block prefetcher anyway)
            if _fs.has_scheme(path):
                return wire.open_compressed(path, "rb", codec,
                                            retry_policy=pol)
            return wire.open_compressed(path, "rb", codec)

        return open_fn

    def epoch_order(self, epoch: int) -> List[int]:
        """Iteration order over this host's shard list for one epoch.

        With ``shuffle`` the order is a permutation derived purely from
        (seed, epoch): every host and every resume reconstructs it without
        coordination or stored state.
        """
        if not self.shuffle:
            return list(range(len(self.shards)))
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(len(self.shards)).tolist()

    def _shard_tasks(self, state: IteratorState) -> Iterator[tuple]:
        """Enumerate (epoch, position, shard_index, skip) from the resume
        point, in the deterministic per-epoch iteration order."""
        epoch = state.epoch
        while self.num_epochs is None or epoch < self.num_epochs:
            order = self.epoch_order(epoch)
            start_pos = state.shard_cursor if epoch == state.epoch else 0
            for pos in range(start_pos, len(order)):
                skip = (
                    state.record_offset
                    if (epoch == state.epoch and pos == state.shard_cursor)
                    else 0
                )
                yield epoch, pos, order[pos], skip
            epoch += 1

    def _retrying(self, make_attempt: Callable[[], Iterator[tuple]]) -> Iterator[tuple]:
        """Shard-level transient-fault retry (SURVEY.md §5 failure-handling
        plan; the reference leans on Spark task retry), shared by every
        decode path: on an IO/corruption error the attempt restarts under
        ``self.retry_policy`` — each attempt body keeps its own
        emitted-record accounting, so re-entry skips what was already
        yielded (no duplicates, no holes)."""
        pol = self.retry_policy
        attempt = 0
        start = pol.clock()
        while True:
            try:
                yield from make_attempt()
                return
            except (OSError, wire.TFRecordCorruptionError):
                attempt += 1
                if not pol.pause(attempt, start):
                    raise
                METRICS.count("read.retries")
                telemetry.instant("read.retry", attempt=attempt)

    def _decode_shard(self, epoch: int, pos: int, shard_idx: int, skip: int) -> Iterator[tuple]:
        """Decode one shard into chunk tuples, applying the epoch cache
        (serve-on-hit / populate-on-miss), ``on_corrupt`` (via
        ``_decode_shard_inner``) and then ``on_stall``: a stall that
        escaped the transient retries (a DeadlineError from the stall
        guard) either propagates (``"raise"``, the default) or drops the
        rest of this shard with the same deterministic skipped-shard
        accounting corruption uses (``"skip_shard"``)."""
        try:
            if self._cache is not None:
                yield from self._decode_shard_caching(epoch, pos, shard_idx, skip)
            else:
                yield from self._decode_shard_inner(epoch, pos, shard_idx, skip)
        except StallError as e:
            if self.options.on_stall != "skip_shard":
                raise
            self._note_skipped_shard(shard_idx, str(e), kind="shard_stalled")

    def _decode_shard_caching(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """The cache layer around one shard's decode: a validated entry
        serves mmap-backed chunks; a miss decodes from the TFRecord source
        and (on a fresh, full pass) appends each chunk to a staging entry
        committed atomically at shard end. Any mid-decode exception —
        including GeneratorExit from an abandoned iterator — aborts the
        staging entry, so only complete shards are ever cached."""
        shard = self.shards[shard_idx]
        entry = self._cache.open_entry(shard)
        if entry is not None:
            yield from self._serve_cached(entry, epoch, pos, shard_idx, skip)
            return
        # resume mid-shard (skip > 0) decodes a suffix only: populating
        # would cache a partial entry, so it stays a plain decode
        pop = self._cache.populator(shard) if skip == 0 else None
        if pop is None:
            yield from self._decode_shard_inner(epoch, pos, shard_idx, skip)
            return
        try:
            for item in self._decode_shard_inner(epoch, pos, shard_idx, 0):
                pop.append(item[0], item[3])
                yield item
        except BaseException:
            pop.abort()
            raise
        pop.commit()

    def _serve_cached(
        self, entry, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """Yield a cached shard's chunk tuples from the resume point. Chunk
        boundaries are the ones recorded at populate time (the fresh-pass
        decode boundaries), and record indices are absolute within the
        shard — so IteratorState checkpoints resume interchangeably between
        cached and uncached reads; a mid-chunk resume slices the straddling
        chunk exactly like the decode paths start mid-slab."""
        from tpu_tfrecord.tracing import trace

        dtype_of = self._cache_dtypes.__getitem__
        shard_path = self.shards[shard_idx].path
        for i in range(entry.num_chunks):
            start, n = entry.chunk_span(i)
            if n == 0 or start + n <= skip:
                continue
            with timed("cache.serve", METRICS) as t, trace("tfr:cache"), \
                    telemetry.span("cache.serve", shard=shard_path) as sp:
                chunk = entry.chunk_batch(i, dtype_of)
                if skip > start:
                    chunk = slice_batch(chunk, skip - start, chunk.num_rows)
                    start = skip
                t.records += chunk.num_rows
                sp.set(rows=chunk.num_rows)
            yield chunk, epoch, pos, start

    def _decode_shard_inner(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """Decode one shard into chunk tuples (chunk, epoch, pos, start),
        applying the configured ``on_corrupt`` policy:

        - ``raise`` (default): the strict paths, byte-exact legacy behavior.
        - ``skip_record``: the salvage scanner resyncs past corrupt frames;
          quota exhaustion escalates to ``corrupt_fallback``.
        - ``skip_shard``: first corruption (after transient retries) drops
          the rest of the shard and the epoch continues.

        Record indices in emitted tuples always count EMITTED records, so a
        checkpoint/resume over a corrupt shard skips the same frames the
        original pass skipped (the salvage scan is deterministic)."""
        mode = self.options.on_corrupt
        if mode == "skip_record":
            try:
                yield from self._decode_shard_salvage(epoch, pos, shard_idx, skip)
            except CorruptQuotaError as e:
                if self.options.corrupt_fallback == "skip_shard":
                    self._note_skipped_shard(shard_idx, str(e))
                    return
                raise wire.TFRecordCorruptionError(str(e)) from e
            return
        if mode == "skip_shard":
            try:
                yield from self._decode_shard_strict(epoch, pos, shard_idx, skip)
            except wire.TFRecordCorruptionError as e:
                METRICS.count("read.corrupt_records")
                self._note_skipped_shard(shard_idx, str(e))
            return
        yield from self._decode_shard_strict(epoch, pos, shard_idx, skip)

    def _note_skipped_shard(
        self, shard_idx: int, reason: str, kind: str = "shard_skipped"
    ) -> None:
        path = self.shards[shard_idx].path
        log_salvage_event(path=path, kind=kind, error=reason)
        METRICS.count("read.skipped_shards")

    def _emit_chunks(
        self, slabs: Iterator[tuple], epoch: int, pos: int, shard_idx: int,
        next_index: List[int],
    ) -> Iterator[tuple]:
        """Chunk-decode a (buf, offsets, lengths) slab stream from the
        resume point: skip the ``next_index[0]`` records already emitted,
        yield (chunk, epoch, pos, start) tuples, and advance the shared
        emitted-record cell — ONE owner for the skip/chunk/index accounting
        used by both the strict two-pass path and the salvage path."""
        from tpu_tfrecord.tracing import trace

        chunk_records = max(self.batch_size, 2048)
        shard_path = self.shards[shard_idx].path
        base = 0
        for buf, offsets, lengths in slabs:
            n = len(offsets)
            if base + n <= next_index[0]:
                base += n
                continue
            for start in range(max(0, next_index[0] - base), n, chunk_records):
                stop = min(start + chunk_records, n)
                with timed("decode", METRICS) as t, trace("tfr:decode"), \
                        telemetry.span("decode", shard=shard_path) as sp:
                    chunk = self._decode_chunk(
                        buf, offsets[start:stop], lengths[start:stop]
                    )
                    t.records += chunk.num_rows
                    t.bytes += int(lengths[start:stop].sum())
                    sp.set(rows=chunk.num_rows)
                if self._partition_fields:
                    self._attach_partition_chunk(chunk, shard_idx)
                yield chunk, epoch, pos, base + start
                next_index[0] = base + stop
            base += n

    def _decode_shard_salvage(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """skip_record decode: frames stream through the salvage scanner
        (valid spans only; corrupt regions resync'd past and reported), and
        chunks decode exactly like the buffered strict path. Indices count
        emitted (valid) records — deterministic across resumes."""
        shard = self.shards[shard_idx]
        tracker = SalvageTracker(shard.path, self.options)
        next_index = [skip]  # record index within the shard to emit next

        def attempt() -> Iterator[tuple]:
            tracker.reset()  # a transient-IO retry re-scans the same regions
            return self._emit_chunks(
                salvage_spans_stream(
                    shard.path,
                    on_event=tracker,
                    slab_bytes=self.slab_bytes,
                    max_record_bytes=self.max_record_bytes,
                    open_fn=self._guarded_open_fn(),
                ),
                epoch, pos, shard_idx, next_index,
            )

        yield from self._retrying(attempt)

    def _decode_shard_strict(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """Strict decode (on_corrupt='raise' semantics): dispatches to the
        fused/mmap native paths when available, the two-pass Python path
        otherwise."""
        if self._native_decoder is not None:
            yield from self._decode_shard_fused(epoch, pos, shard_idx, skip)
            return
        next_index = [skip]  # record index within the shard to emit next

        def attempt() -> Iterator[tuple]:
            return self._emit_chunks(
                self._shard_slabs(self.shards[shard_idx]),
                epoch, pos, shard_idx, next_index,
            )

        yield from self._retrying(attempt)

    # IO scratch sizing for the fused path: big enough that a typical shard
    # (or a full decode chunk) fits in one readinto, small enough to keep
    # resident memory modest; grows geometrically for huge records.
    _SCRATCH_INIT = 32 << 20

    def _io_scratch(self) -> Dict[str, Any]:
        """Per-thread reusable read buffer — readinto a persistent buffer
        instead of fh.read()'s fresh allocation halves raw-IO cost (no
        per-slab page faults)."""
        loc = self._scratch_local
        if not hasattr(loc, "scratch"):
            loc.scratch = {
                "buf": np.empty(min(self.slab_bytes, self._SCRATCH_INIT), np.uint8)
            }
        return loc.scratch

    def _refill_scratch(self, fh, scratch, tail_len: int, path: str) -> int:
        """Fill scratch['buf'] after the carried tail; same bounded-carry
        contract as ``scan_spans_stream``. Returns the new valid length, or -1 at
        clean EOF; raises on truncation / absurd declared length."""
        buf = scratch["buf"]
        if tail_len >= 8:
            declared = int(buf[:8].view(np.uint64)[0])
            self._check_declared_length(declared, path)
            needed = 16 + declared
            if needed > buf.nbytes:
                grown = np.empty(int(needed), np.uint8)
                grown[:tail_len] = buf[:tail_len]
                scratch["buf"] = buf = grown
        reader = getattr(fh, "readinto", None)
        t0 = time.perf_counter()
        with telemetry.span("read", shard=path) as sp:
            if reader is not None:
                n = reader(memoryview(buf)[tail_len:])
            else:
                # file-like without readinto (wrappers, remote FS objects):
                # one extra copy, same contract
                data = fh.read(buf.nbytes - tail_len)
                n = len(data)
                buf[tail_len : tail_len + n] = np.frombuffer(data, np.uint8)
            sp.set(bytes=int(n or 0))
        dt = time.perf_counter() - t0
        METRICS.add("read.io", nbytes=int(n or 0), seconds=dt, latency=dt)
        if not n:
            if tail_len:
                raise self._truncated_error(path)
            return -1
        return tail_len + n

    def _decode_shard_mmap(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """Local uncompressed shards: mmap the file and scan+decode straight
        out of the page cache — no read() copy pass at all. Slab bounds are
        irrelevant (nothing is materialized; the kernel evicts clean pages
        freely); chunk positions and retry semantics match the buffered
        path."""
        import mmap

        from tpu_tfrecord.tracing import trace

        chunk_records = max(self.batch_size, 2048)
        next_index = [skip]
        dec = self._native_decoder
        verify = self.options.verify_crc
        shard = self.shards[shard_idx]

        def raw_open(path: str, _codec) -> Any:
            # the open runs under the open deadline when configured (mmap
            # READS are page-cache memory — the open is the only stallable
            # filesystem op on this path); _open_local resolves at call
            # time so the chaos injector's patch is honored
            if self._stall_guard is not None:
                return self._stall_guard.call_open(
                    lambda: _open_local(path, "rb"), path
                )
            return _open_local(path, "rb")

        def attempt() -> Iterator[tuple]:
            opened = _timed_open(raw_open, shard.path, None)
            with opened as fh:
                size = os.fstat(fh.fileno()).st_size
                if size == 0:
                    return
                hint = _make_readahead(fh, size, self.readahead_bytes)
                mm = mmap.mmap(fh.fileno(), 0, prot=mmap.PROT_READ)
                try:
                    buf = np.frombuffer(mm, np.uint8)
                    to_skip = next_index[0]
                    abs_idx = 0
                    bpos = 0
                    while True:
                        hint(bpos)
                        with timed("decode", METRICS) as t, trace("tfr:decode"), \
                                telemetry.span("decode", shard=shard.path) as sp:
                            cb, n_sk, n_done, consumed = dec.scan_decode(
                                buf, bpos, verify, to_skip, chunk_records,
                                length=size,
                                max_record_bytes=self.max_record_bytes,
                            )
                            t.records += n_done
                            t.bytes += consumed - bpos
                            sp.set(rows=n_done)
                        to_skip -= n_sk
                        abs_idx += n_sk
                        bpos = consumed
                        if n_done == 0:
                            if bpos != size:
                                # an oversized declared length raised
                                # inside scan_decode; what remains here
                                # is a genuine partial tail frame
                                raise self._truncated_error(shard.path)
                            return
                        if self._partition_fields:
                            self._attach_partition_chunk(cb, shard_idx)
                        yield cb, epoch, pos, abs_idx
                        abs_idx += n_done
                        next_index[0] = abs_idx
                finally:
                    # the numpy view exports mm's buffer: drop it before
                    # closing, else BufferError; if anything else still
                    # holds the view, GC closes the map later
                    try:
                        del buf
                        mm.close()
                    except (BufferError, UnboundLocalError):
                        pass

        yield from self._retrying(attempt)

    def _decode_shard_fused(
        self, epoch: int, pos: int, shard_idx: int, skip: int
    ) -> Iterator[tuple]:
        """Fused scan+decode shard stream: ONE native pass per chunk — each
        record is parsed immediately after its CRC while its bytes are still
        cache-hot, and no offsets/lengths arrays materialize. IO goes through
        a reused per-thread buffer (readinto, no per-slab allocations). Same
        chunk positions, retry semantics, and bounded tail-carry contract as
        the two-pass path."""
        from tpu_tfrecord import fs as _fs
        from tpu_tfrecord.tracing import trace

        shard = self.shards[shard_idx]
        codec = wire.codec_from_path(shard.path)
        if self.use_mmap and codec is None and not _fs.has_scheme(shard.path):
            yield from self._decode_shard_mmap(epoch, pos, shard_idx, skip)
            return
        chunk_records = max(self.batch_size, 2048)
        next_index = [skip]  # record index within the shard to emit next
        dec = self._native_decoder
        verify = self.options.verify_crc
        scratch = self._io_scratch()

        open_fn = self._guarded_open_fn()

        def attempt() -> Iterator[tuple]:
            with _timed_open(open_fn, shard.path, codec) as fh:
                # Readahead for local shards: hint by the wrapper's
                # tell() each refill. For codecs tell() is the DECODED
                # offset, which overshoots the raw offset — that only
                # makes the window more eager (clamped at file size).
                hint = _noop_hint
                if not _fs.has_scheme(shard.path):
                    try:
                        hint = _make_readahead(
                            fh, os.path.getsize(shard.path), self.readahead_bytes
                        )
                    except OSError:
                        pass
                to_skip = next_index[0]
                abs_idx = 0  # shard record index at buffer position bpos
                data_len = 0
                bpos = 0
                while True:
                    buf = scratch["buf"]
                    tail_len = data_len - bpos
                    if tail_len and bpos:
                        # compact the (sub-frame) tail to the front
                        buf[:tail_len] = buf[bpos:data_len].copy()
                    try:
                        hint(fh.tell())
                    except (AttributeError, OSError, ValueError):
                        hint = _noop_hint
                    data_len = self._refill_scratch(fh, scratch, tail_len, shard.path)
                    if data_len < 0:
                        return
                    buf = scratch["buf"]
                    bpos = 0
                    while True:
                        with timed("decode", METRICS) as t, trace("tfr:decode"), \
                                telemetry.span("decode", shard=shard.path) as sp:
                            cb, n_sk, n_done, consumed = dec.scan_decode(
                                buf, bpos, verify, to_skip, chunk_records,
                                length=data_len,
                                max_record_bytes=self.max_record_bytes,
                            )
                            t.records += n_done
                            t.bytes += consumed - bpos
                            sp.set(rows=n_done)
                        to_skip -= n_sk
                        abs_idx += n_sk
                        bpos = consumed
                        if n_done == 0:
                            break  # only a tail remains: refill
                        if self._partition_fields:
                            self._attach_partition_chunk(cb, shard_idx)
                        yield cb, epoch, pos, abs_idx
                        abs_idx += n_done
                        next_index[0] = abs_idx

        yield from self._retrying(attempt)

    def _chunk_stream(
        self, state: IteratorState, stop_event=None, control=None
    ) -> Iterator[tuple]:
        """Yield (chunk, epoch, position, start_offset) from the resume point
        onward. With ``num_workers > 1`` shards decode in a thread pool (the
        native decoder releases the GIL) and chunks are re-emitted in exact
        stream order; memory is bounded by num_workers in-flight shards.
        With a ``control`` (autotune.PipelineControl) the pool path is
        taken even at num_workers=1 so the pool can grow mid-epoch.
        With ``options.service`` set, chunks are FETCHED from the
        disaggregated data service instead of decoded here (same tuples,
        same positions — decode parallelism lives in the worker fleet, so
        ``num_workers`` and the pool control do not apply)."""
        if self.options.service is not None:
            yield from self._service_chunks(
                state, stop_event or threading.Event()
            )
            return
        if self.num_workers <= 1 and control is None:
            for epoch, pos, shard_idx, skip in self._shard_tasks(state):
                yield from self._decode_shard(epoch, pos, shard_idx, skip)
            return
        yield from _parallel_chunks(
            self, state, stop_event or threading.Event(), control
        )

    def _service_chunks(self, state: IteratorState, stop) -> Iterator[tuple]:
        """Service-backed chunk source (tpu_tfrecord.service): each shard's
        chunks stream from a leased decode worker, with exactly-once
        dedupe, reconnect-with-backoff across worker/dispatcher death, and
        graceful degradation to ``_decode_shard`` when the service stays
        unreachable — so resume states are interchangeable between
        service-backed and local iterators by construction."""
        from tpu_tfrecord import service as _service

        client = _service.ServiceClient(self)
        try:
            for epoch, pos, shard_idx, skip in self._shard_tasks(state):
                if stop.is_set():
                    return
                yield from client.shard_chunks(epoch, pos, shard_idx, skip, stop)
        finally:
            client.close()

    def chunk_dtypes(self) -> Dict[str, Any]:
        """name -> schema DataType for every column a decoded chunk can
        carry (requested fields + pack group matrices): the reconstruction
        map shared by the epoch cache (``CachedShard.chunk_batch``) and
        the data service's chunk deserializer."""
        dtypes: Dict[str, Any] = {f.name: f.data_type for f in self.schema}
        for gname, members in self.pack.items():
            dtypes[gname] = self._data_schema[members[0]].data_type
        return dtypes

    def _attach_partition_chunk(self, chunk: ColumnarBatch, cursor: int) -> None:
        """Partition values are constant within a shard: materialize them as
        constant columns over the chunk."""
        from tpu_tfrecord.io.paths import cast_partition_value
        from tpu_tfrecord.schema import numpy_dtype

        n = chunk.num_rows
        for f in self._partition_fields:
            raw = self.shards[cursor].partitions.get(f.name)
            val = cast_partition_value(raw, f.data_type)
            col = Column(
                f.name,
                f.data_type,
                mask=np.full(n, val is not None, dtype=bool),
            )
            np_dt = numpy_dtype(f.data_type)
            if np_dt is None:
                item = val.encode("utf-8") if val is not None else b""
                col.blob = item * n
                col.blob_offsets = np.arange(n + 1, dtype=np.int64) * len(item)
            else:
                col.values = np.full(n, val if val is not None else 0, dtype=np_dt)
            chunk.columns[f.name] = col

    # -- identity ------------------------------------------------------------

    def _cache_ident(self) -> Dict[str, Any]:
        """Everything that changes decoded chunk CONTENT, for the epoch
        cache's decode fingerprint (tpu_tfrecord.cache.decode_fingerprint):
        the physical data schema, requested partition fields, record type,
        the hash/pack decode fusions, CRC verification, and the
        record-size cap. Options that only change how chunks are produced
        (batch_size, workers, prefetch, mmap, readahead, retries,
        deadlines) are excluded so changing them still hits."""
        ident: Dict[str, Any] = {
            "schema": self._data_schema.to_json(),
            "partition_fields": [f.name for f in self._partition_fields],
            "record_type": self.options.record_type.value,
            "hash_buckets": self.hash_buckets,
            "pack": self.pack,
            "verify_crc": self.options.verify_crc,
            "max_record_bytes": self.max_record_bytes,
        }
        if self.hash_buckets or self.pack:
            # hash/pack fusion only happens in the native decoder: chunks
            # produced with vs without it carry different columns, so the
            # environments must not share entries
            ident["fused"] = self._native_decoder is not None
        return ident

    def fingerprint(self) -> str:
        """Digest of everything a resume position depends on: the GLOBAL
        shard list (paths + sizes), this host's process slot, the shuffle
        configuration, and the record type. A saved IteratorState carries
        this; resuming against a dataset with a different fingerprint raises
        instead of silently skewing."""
        if self._fingerprint is None:
            ident = {
                "shards": [(sh.path, sh.size) for sh in self._reader.shards],
                "process_index": self.process_index,
                "process_count": self.process_count,
                "shuffle": self.shuffle,
                "seed": self.seed,
                "record_type": self.options.record_type.value,
            }
            if self.shuffle_window:
                # only stamped when in use: states from row-shuffled
                # iterators must not resume under a different window size
                # (or none), and vice versa; absent for shuffle_window=0 so
                # existing unshuffled states stay valid. batch_size joins
                # because window_emitted counts BATCHES — a different batch
                # size makes the same count a different number of rows.
                ident["shuffle_window"] = self.shuffle_window
                ident["batch_size"] = self.batch_size
            blob = json.dumps(ident, sort_keys=True).encode()
            self._fingerprint = hashlib.sha256(blob).hexdigest()[:32]
        return self._fingerprint

    # -- batched iteration ---------------------------------------------------

    def batches(
        self, state: Optional[IteratorState] = None
    ) -> "CheckpointableIterator":
        if state is not None and state.fingerprint is not None:
            mine = self.fingerprint()
            if state.fingerprint != mine:
                raise ValueError(
                    "iterator state does not match this dataset (fingerprint "
                    f"{state.fingerprint} != {mine}): the shard list, "
                    "process slot, shuffle seed, or record type changed "
                    "since the state was saved — resuming would read wrong "
                    "or duplicate data"
                )
        return CheckpointableIterator(self, state or IteratorState())


def _producer_loop(
    ds: TFRecordDataset,
    start: IteratorState,
    out_queue: queue.Queue,
    stop: threading.Event,
    control=None,
) -> None:
    """Background batch producer (module-level so the thread never pins the
    consumer-side iterator object)."""
    B = ds.batch_size

    def emit_from(pending: List[list], n: int) -> bool:
        """Assemble a batch of n rows from the front of the pending chunks;
        the resume state is the position after the batch's last row."""
        entry = pending[0]
        chunk, consumed, epoch, cursor, chunk_start = entry
        if consumed == 0 and chunk.num_rows == n:
            # Aligned fast path: one decode chunk IS the batch (the common
            # case — _decode_shard chunks at batch_size granularity), so the
            # chunk's columnar buffers pass through without the
            # slice_batch/concat_batches memcpy.
            pending.pop(0)
            batch = chunk
            end_pos = IteratorState(epoch, cursor, chunk_start + n)
        else:
            slices = []
            need = n
            end_pos = start
            while need:
                entry = pending[0]
                chunk, consumed, epoch, cursor, chunk_start = entry
                take = min(need, chunk.num_rows - consumed)
                slices.append(slice_batch(chunk, consumed, consumed + take))
                entry[1] = consumed + take
                need -= take
                end_pos = IteratorState(epoch, cursor, chunk_start + entry[1])
                if entry[1] >= chunk.num_rows:
                    pending.pop(0)
            batch = concat_batches(slices)
        blocked = False
        while not stop.is_set():
            try:
                out_queue.put((batch, end_pos), timeout=0.1)
                return True
            except queue.Full:
                if not blocked:
                    # the consumer is behind (queue full): one count per
                    # blocked put, not per 100ms poll
                    blocked = True
                    METRICS.count("read.backpressure_waits")
                continue
        return False

    if ds.shuffle_window:
        _shuffled_producer_loop(ds, start, out_queue, stop, control)
        return
    try:
        # pending: [chunk, consumed_rows, epoch, cursor, chunk_start]
        pending: List[list] = []
        avail = 0
        for chunk, epoch, cursor, chunk_start in ds._chunk_stream(start, stop, control):
            if stop.is_set():
                return
            if chunk.num_rows == 0:
                continue
            pending.append([chunk, 0, epoch, cursor, chunk_start])
            avail += chunk.num_rows
            while avail >= B:
                if not emit_from(pending, B):
                    return
                avail -= B
        if avail and not ds.drop_remainder:
            emit_from(pending, avail)
        _put_until_stopped(out_queue, None, stop)
    except BaseException as e:  # propagate to consumer  # graftlint: swallow(exception forwarded to the consumer queue and re-raised there)
        _put_until_stopped(out_queue, e, stop)


def _window_permutation(seed: int, pos: IteratorState, n: int) -> np.ndarray:
    """The deterministic row permutation for the window starting at ``pos``:
    derived purely from (seed, start position), so a resume re-creates it
    without any stored buffer state."""
    ss = np.random.SeedSequence(
        [seed & 0xFFFFFFFF, pos.epoch, pos.shard_cursor, pos.record_offset]
    )
    return np.random.default_rng(ss).permutation(n)


def _shuffled_producer_loop(
    ds: TFRecordDataset,
    start: IteratorState,
    out_queue: queue.Queue,
    stop: threading.Event,
    control=None,
) -> None:
    """Windowed row shuffle: accumulate ``shuffle_window`` batches worth of
    rows, permute them (seeded by the window's start position), emit
    batch-size slices. Windows may span shards and epochs, exactly like
    batches do in the unshuffled path.

    Positions: every batch except a window's last carries the WINDOW START
    plus ``window_emitted``; the last batch carries the position after the
    window's end (so a checkpoint between windows needs no window replay).
    """
    B = ds.batch_size
    target = ds.shuffle_window * B

    def put(batch, pos) -> bool:
        blocked = False
        while not stop.is_set():
            try:
                out_queue.put((batch, pos), timeout=0.1)
                return True
            except queue.Full:
                if not blocked:
                    blocked = True
                    METRICS.count("read.backpressure_waits")
                continue
        return False

    try:
        # Resume mid-window: rebuild from the stored window START; skip the
        # batches the consumer already saw.
        emit_skip = start.window_emitted
        win_start = IteratorState(start.epoch, start.shard_cursor, start.record_offset)
        win: List[ColumnarBatch] = []
        rows = 0

        def flush(end_pos: IteratorState, tail: bool) -> bool:
            """Permute + emit the accumulated window; True to continue."""
            nonlocal emit_skip, win, rows, win_start
            if rows:
                window = concat_batches(win) if len(win) > 1 else win[0]
                perm = _window_permutation(ds.seed, win_start, rows)
                n_batches = rows // B
                if tail and rows % B and not ds.drop_remainder:
                    n_batches += 1
                for k in range(n_batches):
                    if k < emit_skip:
                        continue  # resume: skipped batches are never gathered
                    # gather each emitted slice of the permutation directly:
                    # one copy per batch instead of a whole-window gather
                    # followed by per-batch slices
                    piece = take_rows(window, perm[k * B : min((k + 1) * B, rows)])
                    last = k == n_batches - 1
                    pos = (
                        end_pos
                        if last
                        else IteratorState(
                            win_start.epoch,
                            win_start.shard_cursor,
                            win_start.record_offset,
                            window_emitted=k + 1,
                        )
                    )
                    if not put(piece, pos):
                        return False
            emit_skip = 0
            win = []
            rows = 0
            win_start = end_pos
            return True

        stream_end = win_start  # position after the last consumed row
        for chunk, epoch, cursor, chunk_start in ds._chunk_stream(
            win_start, stop, control
        ):
            if stop.is_set():
                return
            consumed = 0
            while consumed < chunk.num_rows:
                take = min(target - rows, chunk.num_rows - consumed)
                if consumed == 0 and take == chunk.num_rows:
                    win.append(chunk)  # aligned: no slice copy
                else:
                    win.append(slice_batch(chunk, consumed, consumed + take))
                rows += take
                consumed += take
                stream_end = IteratorState(epoch, cursor, chunk_start + consumed)
                if rows >= target:
                    if not flush(stream_end, tail=False):
                        return
        # stream end: the final (short) window
        if rows and not flush(stream_end, tail=True):
            return
        _put_until_stopped(out_queue, None, stop)
    except BaseException as e:  # propagate to consumer  # graftlint: swallow(exception forwarded to the consumer queue and re-raised there)
        _put_until_stopped(out_queue, e, stop)


def _put_until_stopped(q: queue.Queue, item, stop: threading.Event) -> None:
    """Enqueue without blocking forever on an abandoned consumer."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return
        except queue.Full:
            continue


class _ShardJob:
    """One shard's decode job in the parallel pipeline: a bounded output
    queue written by a worker, drained in stream order by the emitter.

    ``beat`` is the worker's progress heartbeat (monotonic seconds) — it is
    stamped on every chunk handed over AND on every blocked-put poll
    iteration, so backpressure (a full queue while the emitter drains
    earlier shards) never looks like a stall. The watchdog declares the job
    wedged (``wedged``/``failed``) only when the heartbeat goes silent,
    which on a daemon worker means it is blocked inside a read that will
    never return."""

    __slots__ = ("task", "out", "beat", "failed", "wedged")

    def __init__(self, task: tuple, depth: int, now: float = 0.0):
        self.task = task
        self.out: queue.Queue = queue.Queue(maxsize=depth)
        self.beat = now
        self.failed: Optional[BaseException] = None
        self.wedged = False


def _parallel_chunks(
    ds: TFRecordDataset, state: IteratorState, stop: threading.Event,
    control=None,
) -> Iterator[tuple]:
    """Ordered parallel shard decode, with an optional watchdog and an
    optionally LIVE-RESIZABLE pool.

    A dispatcher enumerates shard tasks lazily (epochs may be infinite) and
    hands each to the worker pool; every task owns a small bounded queue, so
    backpressure is per shard and total buffering is bounded by the
    in-flight shard cap. The emitter drains task queues in the exact task
    order, so output is identical to the sequential stream — checkpoint
    state and batch contents do not depend on the worker count, which is
    exactly what makes the pool safely resizable mid-epoch: with a
    ``control`` (autotune.PipelineControl), growth spawns extra worker
    threads that pull from the same task queue, and shrink lets surplus
    workers retire between shards (``should_exit``) — ordering, chunk
    boundaries, and resume positions never change.

    With ``watchdog_timeout_ms`` set, a watchdog thread scans the in-flight
    jobs' progress heartbeats: a worker that goes silent past the timeout
    (wedged in a read that raises nothing — the failure mode deadlines
    cannot see when unconfigured) has its job failed with a WatchdogError
    and a REPLACEMENT worker spawned, so the remaining shards keep decoding
    instead of the consumer blocking on the dead worker's queue forever.
    The emitter applies ``on_stall`` to the failed job after draining the
    chunks it produced before wedging."""
    n_workers = ds.num_workers if control is None else control.workers
    # queue capacities are fixed at construction: under a control they are
    # sized to the pool CEILING so later growth is not strangled by a
    # queue sized for the starting worker count
    cap = n_workers if control is None else max(control.max_workers, n_workers)
    task_q: queue.Queue = queue.Queue(maxsize=cap)
    order_q: queue.Queue = queue.Queue(maxsize=cap + 1)
    END = object()
    clock = time.monotonic
    wd_ms = ds.options.watchdog_timeout_ms
    wd_timeout = wd_ms / 1000.0 if wd_ms else None
    inflight: Dict[int, _ShardJob] = {}
    inflight_lock = threading.Lock()

    def put_checked(q: queue.Queue, item, job: Optional[_ShardJob] = None) -> bool:
        while not stop.is_set():
            if job is not None:
                job.beat = clock()  # blocked-on-full-queue is not a stall
                if job.wedged:
                    return False
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def dispatcher() -> None:
        try:
            for task in ds._shard_tasks(state):
                job = _ShardJob(task, depth=2, now=clock())
                if not put_checked(order_q, job):
                    return
                if not put_checked(task_q, job):
                    return
            put_checked(order_q, END)
        finally:
            if control is not None:
                # dynamic pool: ONE sentinel, re-put by each worker that
                # sees it — terminates any number of workers
                put_checked(task_q, END)
            else:
                for _ in range(n_workers):
                    if not put_checked(task_q, END):
                        break

    def worker() -> None:
        permitted = False
        replaced = False  # declared wedged: the watchdog's replacement
        # already took over this slot, so this thread's (possibly very
        # late) exit must NOT debit the pool books a second time
        try:
            while not stop.is_set():
                if control is not None and control.should_exit():
                    permitted = True  # pool over target: retire between shards
                    return
                try:
                    job = task_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if job is END:
                    if control is not None:
                        put_checked(task_q, END)  # pass the sentinel on
                    return
                job.beat = clock()
                with inflight_lock:
                    inflight[id(job)] = job
                    METRICS.gauge("read.inflight_workers", len(inflight))
                try:
                    try:
                        for item in ds._decode_shard(*job.task):
                            if not put_checked(job.out, ("chunk", item), job=job):
                                replaced = job.wedged
                                return
                            job.beat = clock()
                        if job.wedged:
                            replaced = True
                            return  # declared dead: a replacement already runs
                        # job= keeps the heartbeat fresh while blocked on a
                        # full queue — a DONE shard backpressured behind the
                        # emitter must never look wedged
                        put_checked(job.out, ("end", None), job=job)
                    except BaseException as e:  # graftlint: swallow(failure encoded into the job result for the emitter)
                        if job.wedged:
                            replaced = True
                            return
                        put_checked(job.out, ("error", e), job=job)
                        return
                finally:
                    with inflight_lock:
                        inflight.pop(id(job), None)
                        METRICS.gauge("read.inflight_workers", len(inflight))
                        if job.wedged:
                            # the watchdog declared THIS job wedged (under
                            # this lock) before we removed it: a
                            # replacement is (being) spawned for our slot,
                            # so this thread must retire even though it
                            # may have just finished the job normally —
                            # two unbooked threads working one slot would
                            # skew the pool books
                            replaced = True
                if replaced:
                    return
        finally:
            if control is not None and not replaced:
                control.note_exit(permitted)

    def watchdog() -> None:
        interval = max(0.01, wd_timeout / 4.0)
        while not stop.is_set():
            stop.wait(interval)
            if stop.is_set():
                return
            now = clock()
            with inflight_lock:
                # wedged is DECIDED under the lock, against jobs still in
                # flight: a worker finishing a job pops it (and observes
                # wedged) in its own locked finally, so exactly one side
                # wins — a job can complete normally or be declared
                # wedged+replaced, never both (racing the mark after the
                # pop let a just-finished worker keep running unaware it
                # had been replaced, skewing the autotune pool books)
                stale = [
                    j
                    for j in inflight.values()
                    if not j.wedged and now - j.beat > wd_timeout
                ]
                for j in stale:
                    j.wedged = True
                    inflight.pop(id(j), None)
            for job in stale:
                path = ds.shards[job.task[2]].path
                job.failed = WatchdogError(
                    f"shard worker made no progress for "
                    f"{wd_timeout * 1000:.0f} ms on {path}"
                )
                METRICS.count("read.stalls")
                METRICS.count("read.watchdog_restarts")
                telemetry.instant("watchdog_restart", path=path)
                log_salvage_event(
                    path=path, kind="watchdog_restart", error=str(job.failed)
                )
                # the wedged thread can never be cancelled (blocked in a
                # C-level read); a fresh worker takes over the task queue
                # so the epoch keeps decoding. Pool books under a control:
                # the replacement inherits the wedged thread's slot — it
                # is NOT booked as a spawn, and the wedged thread's own
                # eventual exit is suppressed (`replaced` in worker()) —
                # so the accounted pool always equals the PRODUCTIVE
                # worker count and should_exit never retires a healthy
                # worker to pay for a zombie
                threading.Thread(target=worker, daemon=True).start()

    threads = [threading.Thread(target=dispatcher, daemon=True)]
    if control is None:
        threads += [
            threading.Thread(target=worker, daemon=True) for _ in range(n_workers)
        ]
    if wd_timeout is not None:
        threads.append(threading.Thread(target=watchdog, daemon=True))
    for t in threads:
        t.start()
    if control is not None:
        # the control owns worker lifecycle: this brings the pool up to
        # its current target and lets set_workers() grow it later
        control.bind_spawn(
            lambda: threading.Thread(target=worker, daemon=True).start()
        )

    while not stop.is_set():
        try:
            job = order_q.get(timeout=0.1)
        except queue.Empty:
            continue
        if job is END:
            return
        while not stop.is_set():
            try:
                kind, payload = job.out.get(timeout=0.1)
            except queue.Empty:
                if job.failed is not None:
                    # drained everything the worker produced before it
                    # wedged; now apply the stall policy
                    if ds.options.on_stall == "skip_shard":
                        ds._note_skipped_shard(
                            job.task[2], str(job.failed), kind="shard_stalled"
                        )
                        break
                    raise job.failed
                continue
            if kind == "end":
                break
            if kind == "error":
                raise payload
            yield payload


class CheckpointableIterator:
    """Iterator of ColumnarBatch with a live, resumable ``state``.

    ``state()`` reflects the last batch YIELDED (not prefetched): restoring
    from it replays nothing and skips nothing, even though a background
    thread runs ahead of the consumer.
    """

    def __init__(self, dataset: TFRecordDataset, state: IteratorState):
        self._ds = dataset
        self._start = state
        self._consumed_state = state
        self._finished = None  # None=running, True=exhausted, Exception=failed
        self._queue: queue.Queue = _ResizableQueue(maxsize=max(1, dataset.prefetch))
        self._stop = threading.Event()
        # Bound-ness telemetry: EMA of the prefetch queue's fill fraction,
        # sampled by the consumer at each batch get (telemetry.Pulse reads
        # the gauge; boundness_verdict interprets it).
        self._occupancy = telemetry.OccupancyEma(telemetry.OCCUPANCY_GAUGE)
        # Closed-loop autotuning (tpu_tfrecord.autotune): a PipelineControl
        # exposes THIS iterator's live knobs (decode pool, prefetch queue,
        # readahead window, stall-guard thresholds); the controller runs
        # as a pulse observer, so autotune="on" implies a pulse (at
        # pulse_interval_s if configured, else autotune_interval_s).
        self._control = None
        self.autotune = None
        pulse_interval = dataset.options.pulse_interval_s
        if dataset.options.autotune == "on" and dataset.options.service is not None:
            from tpu_tfrecord.metrics import logger as _logger

            _logger.warning(
                "autotune disabled: this iterator is service-backed "
                "(options.service=%r) — decode parallelism lives in the "
                "worker fleet, not in a local pool the controller could "
                "resize", dataset.options.service,
            )
        elif dataset.options.autotune == "on":
            from tpu_tfrecord import autotune as _autotune

            self._control = _autotune.PipelineControl(
                workers=dataset.num_workers,
                queue=self._queue,
                dataset=dataset,
                guard=dataset._stall_guard,
            )
            if pulse_interval is None:
                pulse_interval = (
                    dataset.options.autotune_interval_s
                    or _autotune.DEFAULT_INTERVAL_S
                )
            self.autotune = _autotune.AutotuneController(
                self._control, interval_s=pulse_interval
            )
        self._pulse = None
        if pulse_interval is not None:
            from tpu_tfrecord.telemetry import Pulse

            self._pulse = Pulse(pulse_interval)
            if self.autotune is not None:
                self._pulse.add_observer(self.autotune.on_pulse)
            self._pulse.start()
            # like the stop-event finalizer below: an abandoned iterator
            # must not leave its pulse thread ticking forever (the
            # finalizer holds the Pulse, never this object)
            self._pulse_finalizer = weakref.finalize(
                self, Pulse.stop, self._pulse, False
            )
        # Cluster telemetry spool (tpu_tfrecord.fleet): periodic atomic
        # snapshots of this process's registry + heartbeat into one file
        # per process under spool_dir, for the fleet aggregator/doctor.
        # Refcounted process singleton (snapshots are process-global);
        # spool_dir unset = this branch is the only new work.
        # abspath ONCE: acquire and the (possibly much later) release must
        # agree on the registry key even if the process chdirs in between.
        # Scheme'd dirs ("gs://...") pass through untouched — abspath would
        # mangle them into a local path BEFORE TelemetrySpool's loud
        # rejection could see the scheme, silently spooling into a private
        # local dir on every host.
        spool_dir = dataset.options.telemetry_spool_dir
        if spool_dir is not None:
            from tpu_tfrecord import fs as _fs

            if not _fs.has_scheme(spool_dir):
                spool_dir = os.path.abspath(spool_dir)
        self._spool_dir = spool_dir
        if self._spool_dir is not None:
            from tpu_tfrecord import fleet

            fleet.acquire_spool(
                self._spool_dir,
                # None keeps the process's adopted trace-context role (the
                # documented telemetry_role default) instead of clobbering
                # it back to a fixed label
                role=dataset.options.telemetry_role,
                interval_s=dataset.options.spool_interval_s,
            )
            # the finalizer releases the refcount for abandoned iterators;
            # _stop_pulse fires it explicitly on clean shutdown (finalize
            # callables are once-only, so the pair can't double-release)
            self._spool_finalizer = weakref.finalize(
                self, fleet.release_spool, self._spool_dir
            )
        # If the iterator is abandoned without close() (no with-block, early
        # break, GC after an error), the finalizer trips the stop event so
        # producer/dispatcher/worker threads exit and shard buffers free.
        # The producer is a module-level function, not a bound method: the
        # thread must hold no reference to this object, or GC could never
        # collect an abandoned iterator and the finalizer would never fire.
        self._finalizer = weakref.finalize(self, self._stop.set)
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(dataset, state, self._queue, self._stop, self._control),
            daemon=True,
        )
        self._thread.start()

    def __iter__(self) -> "CheckpointableIterator":
        return self

    def __next__(self) -> ColumnarBatch:
        if self._finished is not None:
            raise self._finished if not isinstance(self._finished, bool) else StopIteration
        # Bound-ness sample BEFORE blocking: the queue's fill fraction as
        # the consumer arrives is the signal — full = producer keeps ahead
        # (consumer-bound), empty = the consumer is waiting on decode
        # (producer-bound).
        q = self._queue
        depth = q.qsize()
        self._occupancy.update(depth / q.maxsize)
        METRICS.gauge("prefetch.queue_depth", depth)
        t0_ns = time.perf_counter_ns()
        while True:
            if self._stop.is_set():
                # close()d: iteration is over — the producer exits without
                # enqueuing its None sentinel, so never block forever (and a
                # batch racing into the queue during close() is not yielded).
                self._finished = True
                self._stop_pulse()
                raise StopIteration
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                continue
        if item is None:
            self._finished = True
            self._stop.set()  # let any lingering pipeline threads exit
            self._stop_pulse()
            raise StopIteration
        if isinstance(item, BaseException):
            self._finished = item
            self._stop.set()
            self._stop_pulse()
            raise item
        batch, end_pos = item
        wait_ns = time.perf_counter_ns() - t0_ns
        wait_s = wait_ns / 1e9
        METRICS.add(
            "batch.wait", records=batch.num_rows, seconds=wait_s, latency=wait_s
        )
        telemetry.record_span("batch", t0_ns, wait_ns, rows=batch.num_rows)
        self._consumed_state = end_pos
        return batch

    def _stop_pulse(self) -> None:
        """Stop the telemetry pulse and release the fleet spool at end of
        iteration (exhausted, failed, or closed); the final tick/snapshot
        covers the tail interval."""
        pulse, self._pulse = self._pulse, None
        if pulse is not None:
            try:
                pulse.stop()
            except Exception:  # graftlint: swallow(telemetry teardown must not fail iterator close)
                pass
        if self._spool_dir is not None:
            try:
                self._spool_finalizer()  # once-only: safe vs the GC path
            except Exception:  # graftlint: swallow(telemetry teardown must not fail iterator close)
                pass

    def state(self) -> IteratorState:
        """Resume position of the last batch YIELDED, stamped with the
        dataset fingerprint so a later resume validates identity."""
        return replace(self._consumed_state, fingerprint=self._ds.fingerprint())

    def close(self, _empty=queue.Empty) -> None:
        # queue.Empty is bound as a default arg: close() can run during
        # interpreter shutdown (an abandoned iterator collected late), when
        # module globals — including our `queue` import — are already None.
        self._stop.set()
        self._stop_pulse()
        # Drain so the producer unblocks and exits.
        try:
            while True:
                self._queue.get_nowait()
        except _empty:
            pass

    def __enter__(self) -> "CheckpointableIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
