"""Throughput counters and structured logging.

The reference has no observability of its own (SURVEY.md §5: tracing ABSENT,
metrics ride on Spark's UI). Here per-stage counters are first-class because
records/sec and bytes/sec into the device ARE the north-star metric
(BASELINE.md). Counters are cheap (updated at batch granularity, never per
record) and thread-safe.

Three value kinds live in one registry (distinct storage, one lock):

- **stages/counters** (``add``/``count``): monotonic per-stage totals —
  records, bytes, batches, seconds. ``count()`` is the pure-event spelling
  (the count rides the ``records`` field).
- **gauges** (``gauge``): last-written instantaneous values — prefetch
  queue depth, in-flight workers, backpressure occupancy. First-class
  since PR 5 (previously anything instantaneous had to abuse ``count``).
- **latency histograms** (``observe`` / ``timed``): log-bucketed
  per-op latency distributions (tpu_tfrecord.telemetry.Histogram) so
  p50/p90/p99 sit next to the totals and stragglers stop hiding inside
  means. ``timed`` feeds them automatically — one observation per timed
  block, same lock acquisition as the totals update. ``add``/``observe``
  take an optional ``exemplar=(trace_id, span_id)`` that tags the
  observation's bucket (the pointer from a fleet p99 back to the request
  trace that produced it — see telemetry.Histogram.exemplar_at).

Cumulative registries compose upward: the fleet spool ships
``raw_totals()`` + ``hist_states()`` per interval, and the SLO engine
(tpu_tfrecord.slo.SloEngine) folds those cumulative snapshots into its
bounded ring of windowed samples for multi-window burn-rate alerts —
this registry stays cheap and monotonic, windowing lives downstream.

Every name passed to these calls must be registered in
``tpu_tfrecord.vocabulary`` (the single owner of the metric/span name
vocabulary) and documented in the README's generated vocabulary block —
``tools/graftlint`` enforces both directions, so a dashboard keyed on a
documented name can never silently read zero because the code spells it
differently.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from tpu_tfrecord.telemetry import Histogram

logger = logging.getLogger("tpu_tfrecord")


@dataclass
class StageStats:
    records: int = 0
    bytes: int = 0
    batches: int = 0
    seconds: float = 0.0

    def throughput(self) -> Dict[str, float]:
        dt = self.seconds or 1e-9
        return {
            "records_per_sec": self.records / dt,
            "bytes_per_sec": self.bytes / dt,
            "records": self.records,
            "bytes": self.bytes,
            "batches": self.batches,
            "seconds": self.seconds,
        }


class Metrics:
    """Registry of per-stage counters (read, decode, h2d, write, ...),
    gauges, and latency histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, StageStats] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    def add(
        self,
        stage: str,
        records: int = 0,
        nbytes: int = 0,
        seconds: float = 0.0,
        latency: Optional[float] = None,
        exemplar: Optional[Tuple[str, str]] = None,
    ) -> None:
        """Accumulate into a stage's totals. ``latency`` additionally folds
        one observation into the stage's latency histogram under the SAME
        lock acquisition (``timed`` passes its elapsed time here, so every
        timed stage grows a p50/p90/p99 for free). ``exemplar`` is an
        optional (trace_id, span_id) attached to the latency observation's
        bucket (telemetry.Histogram exemplars): the pointer from a fleet
        p99 back to the request trace that produced it."""
        with self._lock:
            st = self._stages.setdefault(stage, StageStats())
            st.records += records
            st.bytes += nbytes
            st.batches += 1
            st.seconds += seconds
            if latency is not None:
                hist = self._hists.get(stage)
                if hist is None:
                    hist = self._hists[stage] = Histogram()
                hist.observe(latency, exemplar=exemplar)

    def count(self, stage: str, n: int = 1) -> None:
        """Increment a pure event counter (the ``records`` field carries the
        count). Used by the robustness counters: ``read.corrupt_records``,
        ``read.resyncs``, ``read.retries``, ``read.skipped_shards``,
        ``write.commit_retries``, the stall counters (``read.stalls``,
        ``read.deadline_misses``, ``read.hedges``, ``read.hedge_wins``,
        ``read.watchdog_restarts``), the epoch-cache counters
        (``cache.hits``, ``cache.misses``, ``cache.bytes_written``,
        ``cache.evictions``, ``cache.corrupt_fallbacks`` — mmap-served
        chunk throughput lands in the ``cache.serve`` stage), the
        per-stage error counters ``<stage>.errors`` (bumped by ``timed``
        when an exception propagates through it), the backpressure
        counters ``read.backpressure_waits``/``write.backpressure_waits``,
        the autotune decision counter ``autotune.adjustments`` (each
        controller knob move — the current knob VALUES live in the
        ``autotune.<knob>`` gauges), the cluster-spool counters
        (``fleet.spool_writes`` = snapshots landed in the telemetry spool,
        ``fleet.spool_errors`` = snapshot attempts that failed — spooling
        is telemetry, it never raises into the pipeline), and the
        training flight recorder's ``train.steps`` (one per completed
        harness step — the step-phase decomposition itself rides the
        ``train.data_wait``/``train.h2d``/``train.compute``/``train.ckpt``
        /``train.step`` STAGES with latency histograms, the windowed
        phase shares ride ``train.share.<phase>`` gauges, and the in-jit
        model diagnostics ride the ``moe.dropped_fraction``/
        ``moe.gate_entropy``/``moe.expert_imbalance``/
        ``pipeline.bubble_fraction`` gauges + histograms).

        INSTANTANEOUS values (queue depths, occupancies, in-flight worker
        counts) belong in ``gauge()``, not here — a counter only goes up.

        Thread-safety audit (counters are bumped from prefetch workers,
        stall-guard workers, the watchdog, and writer pipeline threads):
        every mutation — add/count/gauge/observe — and every read —
        counter/stage/gauge_value/snapshot/raw_totals/gauges/quantiles —
        takes ``self._lock``, so concurrent updates never lose increments
        (pinned by tests/test_chaos.py::TestMetricsThreadSafety and
        tests/test_telemetry.py::TestGauges). The one contract callers
        must keep: a StageStats object returned by ``stage()`` is a live
        reference — read its fields, never mutate them outside this class
        (all in-tree callers only read)."""
        self.add(stage, records=n)

    def counter(self, stage: str) -> int:
        """Current value of a ``count()``-style counter (0 if never hit)."""
        with self._lock:
            st = self._stages.get(stage)
            return st.records if st is not None else 0

    # -- gauges (instantaneous values, last write wins) ----------------------

    def gauge(self, name: str, value: float) -> None:
        """Set an instantaneous gauge (prefetch queue depth, in-flight
        workers, backpressure occupancy). Last write wins — gauges answer
        "what is it NOW", counters answer "how much so far"."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_value(self, name: str, default: Optional[float] = None) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    # -- latency histograms --------------------------------------------------

    def observe(
        self,
        stage: str,
        seconds: float,
        exemplar: Optional[Tuple[str, str]] = None,
    ) -> None:
        """Fold one latency observation into ``stage``'s histogram without
        touching its throughput totals (for ops timed inline rather than
        through ``timed``). ``exemplar`` optionally tags the observation's
        bucket with a (trace_id, span_id) — see ``add``."""
        with self._lock:
            hist = self._hists.get(stage)
            if hist is None:
                hist = self._hists[stage] = Histogram()
            hist.observe(seconds, exemplar=exemplar)

    def quantiles(self, prefix: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        """Per-stage latency quantile snapshot (p50/p90/p99 seconds +
        count/mean); ``prefix`` filters like ``snapshot``."""
        with self._lock:
            return {
                name: hist.quantiles()
                for name, hist in self._hists.items()
                if prefix is None
                or name == prefix
                or name.startswith(prefix + ".")
            }

    def hist_states(self) -> Dict[str, dict]:
        """One-lock copy of every stage histogram's mergeable state
        (telemetry.Histogram.state — sparse bucket counts). The spool
        writer (tpu_tfrecord.fleet) ships these across processes; fixed
        shared bucket layout means the aggregator's merge is EXACT, so
        cluster p99s are real quantiles, not averages of quantiles."""
        with self._lock:
            return {name: hist.state() for name, hist in self._hists.items()}

    def stage(self, stage: str) -> StageStats:
        with self._lock:
            return self._stages.setdefault(stage, StageStats())

    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        """Per-stage throughput map; ``prefix`` filters to one stage family
        (e.g. ``'write'`` -> write, write.encode, write.compress, write.io
        — the breakdown the write bench reports).

        Key stability contract (bench/test consumers): stage entries keep
        the exact keys they always had (records_per_sec, bytes_per_sec,
        records, bytes, batches, seconds). Stages with a latency histogram
        additionally carry ``p50_s``/``p90_s``/``p99_s``/``hist_count``;
        gauges appear under their own names as ``{"gauge": value}`` —
        distinct shapes, so consumers that iterate stages should select on
        the keys they need (``"seconds" in entry``)."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for name, st in self._stages.items():
                if (
                    prefix is not None
                    and name != prefix
                    and not name.startswith(prefix + ".")
                ):
                    continue
                entry = st.throughput()
                hist = self._hists.get(name)
                if hist is not None and hist.count:
                    q = hist.quantiles()
                    entry["p50_s"] = q["p50_s"]
                    entry["p90_s"] = q["p90_s"]
                    entry["p99_s"] = q["p99_s"]
                    entry["hist_count"] = q["count"]
                out[name] = entry
            for name, value in self._gauges.items():
                if (
                    prefix is not None
                    and name != prefix
                    and not name.startswith(prefix + ".")
                ):
                    continue
                out[name] = {"gauge": value}
            return out

    def raw_totals(self) -> Dict[str, Tuple[int, int, int, float]]:
        """One-lock copy of every stage's raw totals as (records, bytes,
        batches, seconds) — the delta source for telemetry.Pulse and the
        Prometheus exporter."""
        with self._lock:
            return {
                name: (st.records, st.bytes, st.batches, st.seconds)
                for name, st in self._stages.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()
            self._gauges.clear()
            self._hists.clear()
            # the fleet spool's wall-window epoch (tpu_tfrecord.fleet
            # stamps it on this registry) describes the totals just
            # cleared — a restarted registry restarts the window
            self.__dict__.pop("_spool_epoch", None)


# Process-global default registry.
METRICS = Metrics()


def log_salvage_event(**fields) -> None:
    """One structured warning per salvage/skip event (corrupt frame found,
    resync landed, shard dropped): a single machine-parseable JSON line on
    the package logger, keyed by path/offset/kind. Fleet log pipelines can
    alert on these without scraping free-form text."""
    logger.warning(
        "tfrecord.salvage %s", json.dumps(fields, sort_keys=True, default=str)
    )


class timed:
    """Context manager adding elapsed wall time (and counts) to a stage,
    plus one latency-histogram observation per block.

    An exception propagating through the block still records the elapsed
    time AND bumps ``<stage>.errors`` — per-stage error rates are visible
    in the pulse/doctor output instead of failed work silently vanishing
    from the timings (the pre-PR-5 ``__exit__(*exc)`` swallowed the
    exception info)."""

    def __init__(self, stage: str, metrics: Optional[Metrics] = None):
        self.stage = stage
        self.metrics = metrics or METRICS
        self.records = 0
        self.bytes = 0

    def __enter__(self) -> "timed":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dt = time.perf_counter() - self._t0
        self.metrics.add(
            self.stage,
            records=self.records,
            nbytes=self.bytes,
            seconds=dt,
            latency=dt,
        )
        if exc_type is not None:
            self.metrics.count(self.stage + ".errors")
