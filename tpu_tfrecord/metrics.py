"""Throughput counters and structured logging.

The reference has no observability of its own (SURVEY.md §5: tracing ABSENT,
metrics ride on Spark's UI). Here per-stage counters are first-class because
records/sec and bytes/sec into the device ARE the north-star metric
(BASELINE.md). Counters are cheap (updated at batch granularity, never per
record) and thread-safe.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

logger = logging.getLogger("tpu_tfrecord")


@dataclass
class StageStats:
    records: int = 0
    bytes: int = 0
    batches: int = 0
    seconds: float = 0.0

    def throughput(self) -> Dict[str, float]:
        dt = self.seconds or 1e-9
        return {
            "records_per_sec": self.records / dt,
            "bytes_per_sec": self.bytes / dt,
            "records": self.records,
            "bytes": self.bytes,
            "batches": self.batches,
            "seconds": self.seconds,
        }


class Metrics:
    """Registry of per-stage counters (read, decode, h2d, write, ...)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, StageStats] = {}

    def add(self, stage: str, records: int = 0, nbytes: int = 0, seconds: float = 0.0) -> None:
        with self._lock:
            st = self._stages.setdefault(stage, StageStats())
            st.records += records
            st.bytes += nbytes
            st.batches += 1
            st.seconds += seconds

    def count(self, stage: str, n: int = 1) -> None:
        """Increment a pure event counter (the ``records`` field carries the
        count). Used by the robustness counters: ``read.corrupt_records``,
        ``read.resyncs``, ``read.retries``, ``read.skipped_shards``,
        ``write.commit_retries``, the stall counters (``read.stalls``,
        ``read.deadline_misses``, ``read.hedges``, ``read.hedge_wins``,
        ``read.watchdog_restarts``), and the epoch-cache counters
        (``cache.hits``, ``cache.misses``, ``cache.bytes_written``,
        ``cache.evictions``, ``cache.corrupt_fallbacks`` — mmap-served
        chunk throughput lands in the ``cache.serve`` stage).

        Thread-safety audit (counters are bumped from prefetch workers,
        stall-guard workers, the watchdog, and writer pipeline threads):
        every mutation — add/count — and every read — counter/stage/
        snapshot — takes ``self._lock``, so concurrent increments never
        lose updates (pinned by tests/test_chaos.py::TestMetricsThreadSafety).
        The one contract callers must keep: a StageStats object returned by
        ``stage()`` is a live reference — read its fields, never mutate
        them outside this class (all in-tree callers only read)."""
        self.add(stage, records=n)

    def counter(self, stage: str) -> int:
        """Current value of a ``count()``-style counter (0 if never hit)."""
        with self._lock:
            st = self._stages.get(stage)
            return st.records if st is not None else 0

    def stage(self, stage: str) -> StageStats:
        with self._lock:
            return self._stages.setdefault(stage, StageStats())

    def snapshot(self, prefix: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        """Per-stage throughput map; ``prefix`` filters to one stage family
        (e.g. ``'write'`` -> write, write.encode, write.compress, write.io
        — the breakdown the write bench reports)."""
        with self._lock:
            return {
                name: st.throughput()
                for name, st in self._stages.items()
                if prefix is None
                or name == prefix
                or name.startswith(prefix + ".")
            }

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()


# Process-global default registry.
METRICS = Metrics()


def log_salvage_event(**fields) -> None:
    """One structured warning per salvage/skip event (corrupt frame found,
    resync landed, shard dropped): a single machine-parseable JSON line on
    the package logger, keyed by path/offset/kind. Fleet log pipelines can
    alert on these without scraping free-form text."""
    logger.warning(
        "tfrecord.salvage %s", json.dumps(fields, sort_keys=True, default=str)
    )


class timed:
    """Context manager adding elapsed wall time (and counts) to a stage."""

    def __init__(self, stage: str, metrics: Optional[Metrics] = None):
        self.stage = stage
        self.metrics = metrics or METRICS
        self.records = 0
        self.bytes = 0

    def __enter__(self) -> "timed":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.metrics.add(
            self.stage,
            records=self.records,
            nbytes=self.bytes,
            seconds=time.perf_counter() - self._t0,
        )
