"""Stall defense: per-op deadlines, hedged reads, and the stall exceptions.

PR 2's ``RetryPolicy``/``on_corrupt`` machinery only fires on exceptions —
a hung object-store ``read()`` or a wedged prefetch worker hangs the epoch
forever without ever raising. This module converts stalls INTO raising
faults so all the existing policy machinery applies:

- ``StallError`` (an OSError) is the common type every stall detection
  raises, so it flows through the transient-retry nets
  (io/dataset._retrying catches OSError) and then, if retries are
  exhausted, through the new ``on_stall`` policy ("raise" | "skip_shard").
- ``StallGuard`` is the per-dataset configuration + enforcement object:
  shard opens run under ``open_deadline_ms``, every underlying read under
  ``read_deadline_ms``, and ``hedge_after_ms`` launches a backup
  open+read of the same byte range when the primary goes quiet — first
  result wins, the loser is abandoned and its handle closed when its
  blocked call finally returns. Results are byte-identical whichever side
  wins (both sides read the same [offset, offset+n) of the same object).
- The guarded stream sits UNDER the codec wrapper (raw object bytes), so
  deadlines/hedging work identically for plain, gzip, zstd, ... shards,
  and hedge reopens can seek (codec streams cannot).

Enforcement model: each guarded stream owns one persistent daemon worker
thread that executes its (strictly sequential) reads; the consumer waits on
a Future with a timeout. A deadline miss ABANDONS the worker — Python
cannot cancel a thread blocked in a C-level read — marks the stream
wedged, bumps ``read.stalls``/``read.deadline_misses``, and raises
``DeadlineError``; the abandoned worker closes the handle when (if) its
blocked call returns. Retry machinery reopens a fresh stream, so abandoned
threads accumulate only one per detected stall, never one per read.

Fault-free overhead is one queue hand-off per underlying read; small
(per-record) reads are amortized through an internal >= ``io_chunk``
buffer, so the guarded row reader does not pay a hand-off per 8-byte
header. bench.py's ``stall_guard_overhead_pct`` field tracks this.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import FIRST_COMPLETED, Future
from concurrent.futures import TimeoutError as _FutureTimeout
from concurrent.futures import wait as _wait_futures
from time import monotonic as _monotonic
from typing import BinaryIO, Callable, Optional

from tpu_tfrecord import telemetry
from tpu_tfrecord.metrics import METRICS, Metrics


class StallError(OSError):
    """A stall converted into a raising fault. OSError so PR 2's transient
    retry nets and commit retry paths treat it like any other IO fault."""


class DeadlineError(StallError):
    """An op exceeded its configured deadline (read_deadline_ms /
    open_deadline_ms)."""


class WatchdogError(StallError):
    """The pipeline watchdog declared a shard worker wedged (no progress
    heartbeat within the watchdog timeout)."""


class _OpWorker:
    """One daemon thread running submitted thunks strictly in order.

    ``abandon()`` tells it to exit after the op it is (possibly forever)
    blocked in; the pending future still completes/errors when that op
    returns, so an ``add_done_callback`` can close the abandoned handle.
    """

    def __init__(self, name: str = "tfr-stall"):
        self._q: "queue.Queue" = queue.Queue()
        self.abandoned = False
        self._thread = threading.Thread(target=self._run, daemon=True, name=name)
        self._thread.start()

    def submit(self, fn: Callable) -> Future:
        fut: Future = Future()
        self._q.put((fn, fut))
        return fut

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, fut = item
            try:
                result = fn()
            except BaseException as e:  # delivered through the future  # graftlint: swallow(delivered through the op future (set_exception))
                fut.set_exception(e)
            else:
                fut.set_result(result)
            if self.abandoned:
                return

    def abandon(self) -> None:
        self.abandoned = True
        self._q.put(None)  # wake it if idle so the thread exits

    def close(self) -> None:
        self._q.put(None)


class _WorkerPool:
    """Free-list of _OpWorkers. Shard opens happen ~continuously on small
    shards; paying a thread CREATE per open/stream measurably taxes a
    fully-loaded host (the bench's stall_guard_overhead_pct field), while a
    reused idle worker costs only the queue hand-off. Abandoned (wedged)
    workers are never checked back in; the idle list is bounded.

    There is ONE pool per process (``_SHARED_POOL``): a checked-out worker
    is exclusively owned until checkin, so sharing is safe, idle threads
    are bounded process-wide, and short-lived guards (the row API builds
    one per ShardReader) cannot strand their own pool's idle threads."""

    _MAX_IDLE = 8

    def __init__(self):
        self._idle: list = []
        self._lock = threading.Lock()

    def checkout(self) -> _OpWorker:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return _OpWorker()

    def checkin(self, worker: _OpWorker) -> None:
        if worker.abandoned:
            return
        with self._lock:
            if len(self._idle) < self._MAX_IDLE:
                self._idle.append(worker)
                return
        worker.close()


_SHARED_POOL = _WorkerPool()


def _close_quietly(fh) -> None:
    try:
        fh.close()
    except Exception:  # graftlint: swallow(closing an abandoned/stalled handle; nothing to deliver to)
        pass


def _close_result_when_done(fut: Future, pick=lambda r: r) -> None:
    """When an ABANDONED op finally returns, close the handle it yields
    (``pick`` extracts it from the result); errors are swallowed — the op
    was already given up on."""

    def _cb(f: Future) -> None:
        if f.cancelled() or f.exception() is not None:
            return
        _close_quietly(pick(f.result()))

    fut.add_done_callback(_cb)


def _close_fh_when_done(fut: Future, fh) -> None:
    """Close ``fh`` once the abandoned op blocked on it completes (the
    result — bytes of a stream we no longer trust — is discarded)."""

    def _cb(f: Future) -> None:
        f.exception()  # consume, never let it propagate
        _close_quietly(fh)

    fut.add_done_callback(_cb)


class GuardedReadStream:
    """Sequential read stream with per-op deadline + optional hedging.

    Plain duck-typed file object (read/tell/close only — deliberately NO
    readinto: an abandoned worker must never be left writing into
    caller-owned scratch memory, so every guarded read returns fresh
    bytes). ``reopen(pos)`` returns a fresh raw handle positioned at byte
    ``pos`` — the hedge's backup side; hedging is off when it is None.
    """

    def __init__(
        self,
        fh: BinaryIO,
        path: str,
        read_deadline: Optional[float],
        hedge_after: Optional[float],
        reopen: Optional[Callable[[int], BinaryIO]] = None,
        metrics: Metrics = METRICS,
        io_chunk: int = 4 << 20,
        pool: Optional[_WorkerPool] = None,
        guard: "Optional[StallGuard]" = None,
    ):
        self._fh = fh
        self._path = path
        self._fixed_deadline = read_deadline
        self._fixed_hedge_after = hedge_after
        # threshold source (autotune): when a guard is given, every fetch
        # reads ITS current read_deadline/hedge_after — so a controller
        # update (StallGuard.update_thresholds) takes effect on live
        # streams, not just the next shard open
        self._guard = guard
        self._reopen = reopen
        self._metrics = metrics
        self._io_chunk = max(1, int(io_chunk))
        self._pool = pool
        self._worker = pool.checkout() if pool is not None else _OpWorker()
        self._fetched = 0  # raw bytes consumed from the underlying object
        self._buf = b""
        self._buf_pos = 0
        self._wedged = False
        self._closed = False

    # -- live thresholds -----------------------------------------------------

    @property
    def _deadline(self) -> Optional[float]:
        g = self._guard
        return g.read_deadline if g is not None else self._fixed_deadline

    @property
    def _hedge_after(self) -> Optional[float]:
        if self._reopen is None:
            return None  # no backup opener: hedging impossible
        g = self._guard
        return g.hedge_after if g is not None else self._fixed_hedge_after

    # -- the guarded fetch ---------------------------------------------------

    def _fetch(self, n: int) -> bytes:
        """One underlying read of up to ``n`` bytes under deadline+hedge."""
        if self._wedged:
            raise DeadlineError(f"read stream wedged after stall: {self._path}")
        fh = self._fh
        t0 = _monotonic()
        fut = self._worker.submit(lambda: fh.read(n))
        hedge_first = self._hedge_after is not None and (
            self._deadline is None or self._hedge_after < self._deadline
        )
        try:
            data = fut.result(self._hedge_after if hedge_first else self._deadline)
        except _FutureTimeout:
            if hedge_first:
                return self._fetch_hedged(fut, n, t0)
            self._declare_stall(fut)
        self._fetched += len(data)
        return data

    def _remaining(self, t0: float) -> Optional[float]:
        """Seconds left of this fetch's read deadline (None = unbounded)."""
        if self._deadline is None:
            return None
        return max(0.001, self._deadline - (_monotonic() - t0))

    def _fetch_hedged(self, primary_fut: Future, n: int, t0: float) -> bytes:
        """The primary went quiet: launch a backup open+read of the SAME
        byte range; first result wins, the loser is abandoned (bytes
        discarded, handle closed when its blocked call returns)."""
        self._metrics.count("read.hedges")
        telemetry.instant("read.hedge", path=self._path)
        pos = self._fetched
        reopen = self._reopen
        backup_worker = _OpWorker(name="tfr-stall-hedge")

        def backup_read():
            bfh = reopen(pos)
            try:
                return bfh, bfh.read(n)
            except BaseException:
                _close_quietly(bfh)
                raise

        backup_fut = backup_worker.submit(backup_read)
        done, _ = _wait_futures(
            [primary_fut, backup_fut],
            timeout=self._remaining(t0),
            return_when=FIRST_COMPLETED,
        )
        if primary_fut in done:
            backup_worker.abandon()
            _close_result_when_done(backup_fut, pick=lambda r: r[0])
            data = primary_fut.result()  # re-raises a real (non-stall) error
            self._fetched += len(data)
            return data
        if backup_fut in done:
            try:
                bfh, data = backup_fut.result()
            except BaseException:  # graftlint: swallow(losing hedge leg abandoned; winner already returned)
                # The BACKUP failed (its open/read erred) while the primary
                # is merely slow: a failed hedge must not shorten the
                # primary's deadline — keep waiting on the primary for the
                # rest of the read budget (forever when no deadline is
                # configured; only its true expiry declares the stall).
                backup_worker.close()
                try:
                    data = primary_fut.result(self._remaining(t0))
                except _FutureTimeout:
                    self._declare_stall(primary_fut)
                self._fetched += len(data)
                return data
            backup_worker.close()
            self._metrics.count("read.hedge_wins")
            telemetry.instant("read.hedge_win", path=self._path)
            old_worker = self._worker
            old_worker.abandon()
            _close_fh_when_done(primary_fut, self._fh)
            self._fh = bfh
            self._worker = (
                self._pool.checkout() if self._pool is not None else _OpWorker()
            )
            self._fetched += len(data)
            return data
        # neither side produced within the deadline
        backup_worker.abandon()
        _close_result_when_done(backup_fut, pick=lambda r: r[0])
        self._declare_stall(primary_fut)

    def _declare_stall(self, fut: Future):
        self._wedged = True
        self._metrics.count("read.stalls")
        self._metrics.count("read.deadline_misses")
        telemetry.instant("read.stall", path=self._path, kind="read_deadline")
        self._worker.abandon()
        _close_fh_when_done(fut, self._fh)
        raise DeadlineError(
            f"read exceeded deadline "
            f"({(self._deadline or 0) * 1000:.0f} ms) on {self._path}"
        ) from None

    # -- file-object surface -------------------------------------------------

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            parts = []
            while True:
                chunk = self.read(self._io_chunk)
                if not chunk:
                    return b"".join(parts)
                parts.append(chunk)
        if size == 0:
            return b""
        avail = len(self._buf) - self._buf_pos
        if avail:
            take = min(avail, size)
            out = self._buf[self._buf_pos : self._buf_pos + take]
            self._buf_pos += take
            if self._buf_pos >= len(self._buf):
                self._buf = b""
                self._buf_pos = 0
            return out
        if size >= self._io_chunk:
            return self._fetch(size)
        data = self._fetch(self._io_chunk)
        if len(data) <= size:
            return data
        self._buf = data
        self._buf_pos = size
        return data[:size]

    def tell(self) -> int:
        return self._fetched - (len(self._buf) - self._buf_pos)

    def readable(self) -> bool:
        return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        worker, fh = self._worker, self._fh
        if self._wedged:
            worker.close()  # handle closes via the abandoned-op callback
            return
        fut = worker.submit(fh.close)
        try:
            fut.result(1.0)
        except _FutureTimeout:
            worker.abandon()
            return
        except Exception:  # graftlint: swallow(pool checkin of an abandoned worker at guard close)
            pass
        if self._pool is not None:
            self._pool.checkin(worker)
        else:
            worker.close()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "GuardedReadStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StallGuard:
    """Per-dataset stall policy: deadlines + hedging wired into the shard
    open path. Built from TFRecordOptions (``guard_from_options``); None
    when no stall knob is set, so the unguarded hot path stays untouched."""

    def __init__(
        self,
        read_deadline: Optional[float] = None,
        open_deadline: Optional[float] = None,
        hedge_after: Optional[float] = None,
        metrics: Metrics = METRICS,
        io_chunk: int = 4 << 20,
        retry_policy=None,
    ):
        self.read_deadline = read_deadline
        self.open_deadline = open_deadline
        self.hedge_after = hedge_after
        self.metrics = metrics
        self.io_chunk = io_chunk
        # handed to the remote block prefetcher so its fetches self-heal
        # under the SAME budget the dataset's shard-level retries use
        # (io/dataset sets this from its retry_policy)
        self.retry_policy = retry_policy
        # the process-wide pool: shard churn reuses worker threads instead
        # of creating one per open, and discarding this guard strands no
        # idle threads (ShardReader builds a guard per shard)
        self._pool = _SHARED_POOL

    # -- controller-updated thresholds (autotune) ----------------------------

    def update_thresholds(
        self,
        read_deadline_ms: Optional[float] = None,
        open_deadline_ms: Optional[float] = None,
        hedge_after_ms: Optional[float] = None,
    ) -> None:
        """Retarget the guard's thresholds (milliseconds; None leaves a
        knob untouched). Live streams pick up read_deadline/hedge_after on
        their next fetch (GuardedReadStream reads them through the guard);
        open_deadline applies to the next open. Plain float attribute
        writes — atomic under the GIL, so no lock is needed for readers."""
        if read_deadline_ms is not None:
            self.read_deadline = read_deadline_ms / 1000.0
        if open_deadline_ms is not None:
            self.open_deadline = open_deadline_ms / 1000.0
        if hedge_after_ms is not None:
            self.hedge_after = hedge_after_ms / 1000.0

    # -- open-side deadline --------------------------------------------------

    def call_open(self, fn: Callable, path: str):
        """Run an open-type call under ``open_deadline_ms``. A miss bumps
        the stall counters and raises DeadlineError (retryable OSError);
        the late-arriving handle of an abandoned open is closed when the
        blocked call finally returns."""
        if self.open_deadline is None:
            return fn()
        worker = self._pool.checkout()
        fut = worker.submit(fn)
        try:
            result = fut.result(self.open_deadline)
        except _FutureTimeout:
            worker.abandon()
            _close_result_when_done(fut)
            self.metrics.count("read.stalls")
            self.metrics.count("read.deadline_misses")
            telemetry.instant("read.stall", path=path, kind="open_deadline")
            raise DeadlineError(
                f"open exceeded deadline "
                f"({self.open_deadline * 1000:.0f} ms) on {path}"
            ) from None
        except BaseException:
            # a REAL open error (missing file, transient fault): the op
            # completed, so the worker is healthy — return it to the pool
            # instead of leaking its thread, and let the error propagate
            self._pool.checkin(worker)
            raise
        self._pool.checkin(worker)
        return result

    # -- guarded compressed open ---------------------------------------------

    def open_compressed(self, path: str, codec: Optional[str]) -> BinaryIO:
        """The guarded twin of ``wire.open_compressed(path, 'rb', codec)``:
        raw open under the open deadline, raw reads under the read deadline
        (+hedge), codec wrapper on top (so the deadline model covers every
        codec identically — the guard sees raw object bytes)."""
        from tpu_tfrecord import fs as _fs, wire

        if _fs.has_scheme(path):
            fsys = _fs.filesystem_for(path)
            raw = self.call_open(
                lambda: _fs.open_for_read(
                    fsys, path, retry_policy=self.retry_policy
                ),
                path,
            )

            def reopen(pos: int) -> BinaryIO:
                fh = fsys.open(path, "rb")
                _seek_to(fh, pos)
                return fh

        else:
            raw = self.call_open(lambda: _fs.local_open(path, "rb"), path)

            def reopen(pos: int) -> BinaryIO:
                fh = _fs.local_open(path, "rb")
                _seek_to(fh, pos)
                return fh

        if self.read_deadline is None and self.hedge_after is None:
            guarded: BinaryIO = raw  # open-deadline only: no read wrapper
        else:
            guarded = GuardedReadStream(
                raw,
                path,
                read_deadline=self.read_deadline,
                hedge_after=self.hedge_after,
                reopen=reopen,
                metrics=self.metrics,
                io_chunk=self.io_chunk,
                pool=self._pool,
                guard=self,  # live thresholds: autotune updates apply mid-stream
            )
        return wire.wrap_codec(path, "rb", codec, guarded)


def _seek_to(fh, pos: int) -> None:
    """Position a fresh hedge handle at ``pos`` — the shared
    seek-or-discard idiom lives in fs.seek_to (one owner with the
    self-healing stream's resume)."""
    from tpu_tfrecord.fs import seek_to

    seek_to(fh, pos)


def guard_from_options(options) -> Optional[StallGuard]:
    """A StallGuard for these options, or None when every stall knob is
    unset (the zero-overhead default)."""
    rd = getattr(options, "read_deadline_ms", None)
    od = getattr(options, "open_deadline_ms", None)
    hg = getattr(options, "hedge_after_ms", None)
    if rd is None and od is None and hg is None:
        return None
    return StallGuard(
        read_deadline=rd / 1000.0 if rd is not None else None,
        open_deadline=od / 1000.0 if od is not None else None,
        hedge_after=hg / 1000.0 if hg is not None else None,
    )
