"""Hadoop ecosystem compression codecs with zero hard dependencies.

The reference forwards ANY codec class name into the Hadoop conf
(DefaultSource.scala:95-102): a cluster with SnappyCodec / Lz4Codec /
BZip2Codec on the classpath reads and writes those files for free. This
module supplies the same breadth natively:

- **raw snappy** (`snappy_decompress` / `snappy_compress`): the full
  element format (literals + all three copy tags, incl. overlapping
  RLE-style copies). Both directions are REAL in-repo implementations:
  decode and greedy-matching ENCODE live in the native library (round 4 —
  writes actually compress with zero optional dependencies); pure-Python
  references remain as oracles and fallbacks (`python-snappy` is used for
  encode when importable and the native build is unavailable; the final
  fallback emits valid literal-only snappy at ratio 1.0).
- **lz4 block** (`lz4_decompress` / `lz4_compress`): full sequence decode
  (literal runs + matches with extended lengths); native greedy-matching
  encode (round 4), literal-only pure-Python fallback.
- **Hadoop block stream framing** (`HadoopBlockFile`): the
  BlockCompressorStream / BlockDecompressorStream wire layout both
  SnappyCodec and Lz4Codec use — per block a 4-byte big-endian
  uncompressed length, then chunks of 4-byte big-endian compressed length
  + compressed bytes until the block is complete.
- **bzip2** (`Bz2File`): stdlib `bz2`; Hadoop's BZip2Codec writes standard
  (possibly concatenated) .bz2 streams.

Truncated or corrupt streams raise TFRecordCorruptionError (imported
lazily to avoid an import cycle with wire.py).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Optional


def _corruption(msg: str) -> Exception:
    from tpu_tfrecord.wire import TFRecordCorruptionError

    return TFRecordCorruptionError(msg)


from tpu_tfrecord.wire import read_exact as _read_exact  # noqa: E402


# ---------------------------------------------------------------------------
# Raw snappy
# ---------------------------------------------------------------------------


def _snappy_lib():
    """Optional python-snappy accel; None -> pure-Python paths below."""
    try:
        import snappy  # type: ignore

        return snappy
    except ImportError:
        return None


def _read_varint(buf, pos: int):
    shift = 0
    out = 0
    while True:
        if pos >= len(buf):
            raise _corruption("snappy: truncated length varint")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 35:
            raise _corruption("snappy: length varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Decode one raw-snappy buffer (the format inside Hadoop's block
    framing). Full spec: literal elements and 1/2/4-byte-offset copies,
    including overlapping copies (offset < length, byte-at-a-time RLE
    semantics). Dispatch: in-repo native decoder (memory-speed) ->
    python-snappy if installed -> the pure-Python reference below."""
    try:
        from tpu_tfrecord import _native

        if _native.available():
            out = _native.snappy_decompress(data)
            if out is not None:
                return out
    except ValueError as e:
        raise _corruption(f"snappy: {e}") from e
    except ImportError:
        pass
    lib = _snappy_lib()
    if lib is not None:
        try:
            return lib.uncompress(data)
        except Exception as e:
            raise _corruption(f"snappy: {e}") from e
    return _snappy_decompress_py(data)


def _snappy_decompress_py(data: bytes) -> bytes:
    """Pure-Python reference decoder (also the oracle for the native one)."""
    buf = memoryview(data)
    expected, pos = _read_varint(buf, 0)
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:  # 60..63 -> that many extra length bytes
                extra = length - 59
                if pos + extra > n:
                    raise _corruption("snappy: truncated literal length")
                length = int.from_bytes(buf[pos : pos + extra], "little")
                pos += extra
            length += 1
            if pos + length > n:
                raise _corruption("snappy: truncated literal")
            out += buf[pos : pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            if pos >= n:
                raise _corruption("snappy: truncated copy offset")
            offset = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise _corruption("snappy: truncated copy offset")
            offset = int.from_bytes(buf[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise _corruption("snappy: truncated copy offset")
            offset = int.from_bytes(buf[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise _corruption("snappy: copy offset out of range")
        start = len(out) - offset
        if offset >= length:
            out += out[start : start + length]
        else:  # overlapping copy: RLE semantics, byte at a time
            for i in range(length):
                out.append(out[start + i])
    if len(out) != expected:
        raise _corruption(
            f"snappy: decoded {len(out)} bytes, header promised {expected}"
        )
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Encode raw snappy. Dispatch: in-repo native greedy-matching encoder
    (REAL compression, zero dependencies — round 4) -> python-snappy if
    installed -> the literal-only pure-Python fallback (valid snappy,
    readable everywhere, ratio 1.0 — reached only when the native build is
    unavailable AND python-snappy is absent)."""
    try:
        from tpu_tfrecord import _native

        out = _native.snappy_compress(data)
        if out is not None:
            return out
    except ImportError:
        pass
    lib = _snappy_lib()
    if lib is not None:
        return lib.compress(data)
    out = bytearray(_write_varint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = min(n - pos, 1 << 16)
        length = chunk - 1
        if length < 60:
            out.append(length << 2)
        else:
            extra = (length.bit_length() + 7) // 8
            out.append((59 + extra) << 2)
            out += length.to_bytes(extra, "little")
        out += data[pos : pos + chunk]
        pos += chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# LZ4 block format
# ---------------------------------------------------------------------------


def lz4_decompress(
    data: bytes,
    expected: Optional[int] = None,
    max_out: Optional[int] = None,
) -> bytes:
    """Decode one lz4 BLOCK (the format inside Hadoop's Lz4Codec framing):
    sequences of [token][literal-len ext][literals][offset LE16][match-len
    ext]; the final sequence is literals-only. Dispatch: in-repo native
    decoder -> the pure-Python reference below. ``expected`` is enforced
    exactly; ``max_out`` only sizes the native output buffer (the block
    header's remaining bytes — avoids a decode-retry on high-ratio
    chunks)."""
    try:
        from tpu_tfrecord import _native

        if _native.available():
            out = _native.lz4_decompress(data, expected, max_out)
            if out is not None:
                return out
    except ValueError as e:
        raise _corruption(f"lz4: {e}") from e
    except ImportError:
        pass
    return _lz4_decompress_py(data, expected)


def _lz4_decompress_py(data: bytes, expected: Optional[int] = None) -> bytes:
    """Pure-Python reference decoder (also the oracle for the native one)."""
    buf = memoryview(data)
    out = bytearray()
    pos = 0
    n = len(buf)
    while pos < n:
        token = buf[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise _corruption("lz4: truncated literal length")
                b = buf[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise _corruption("lz4: truncated literals")
        out += buf[pos : pos + lit_len]
        pos += lit_len
        if pos >= n:
            break  # final literals-only sequence
        if pos + 2 > n:
            raise _corruption("lz4: truncated match offset")
        offset = int.from_bytes(buf[pos : pos + 2], "little")
        pos += 2
        if offset == 0 or offset > len(out):
            raise _corruption("lz4: match offset out of range")
        match_len = (token & 0x0F) + 4
        if (token & 0x0F) == 15:
            while True:
                if pos >= n:
                    raise _corruption("lz4: truncated match length")
                b = buf[pos]
                pos += 1
                match_len += b
                if b != 255:
                    break
        start = len(out) - offset
        if offset >= match_len:
            out += out[start : start + match_len]
        else:
            for i in range(match_len):
                out.append(out[start + i])
    if expected is not None and len(out) != expected:
        raise _corruption(
            f"lz4: decoded {len(out)} bytes, framing promised {expected}"
        )
    return bytes(out)


# The native lz4 encoder's match table stores int32 positions: a single
# call beyond this is out of contract (positions would alias past 2 GiB —
# matches are byte-verified so output stays VALID, but the ratio collapses
# silently). Guarded here as well as in _native so the dispatch can never
# silently degrade; module-level so tests can shrink it and pin the
# fallback without allocating 2 GiB.
LZ4_NATIVE_MAX_BYTES = 2**31 - 1


def lz4_compress(data: bytes) -> bytes:
    """Encode one lz4 block. Dispatch: in-repo native greedy-matching
    encoder (real compression — round 4) -> pure-Python literals-only
    fallback (legal per the block spec — the last sequence carries only
    literals). Inputs past ``LZ4_NATIVE_MAX_BYTES`` (the native match
    table's int32 position contract) skip the native path entirely;
    Hadoop block framing (``compress_hadoop_blocks``/``HadoopBlockFile``)
    never gets here — it frames in 256 KiB blocks."""
    try:
        from tpu_tfrecord import _native

        if len(data) <= LZ4_NATIVE_MAX_BYTES:
            out = _native.lz4_compress(data)
            if out is not None:
                return out
    except ImportError:
        pass
    n = len(data)
    out = bytearray()
    if n < 15:
        out.append(n << 4)
    else:
        out.append(0xF0)
        rest = n - 15
        while rest >= 255:
            out.append(255)
            rest -= 255
        out.append(rest)
    out += data
    return bytes(out)


# ---------------------------------------------------------------------------
# Hadoop block stream framing (BlockCompressorStream layout)
# ---------------------------------------------------------------------------

_RAW_CODECS = {
    "snappy": (snappy_compress, snappy_decompress),
    "lz4": (lz4_compress, lz4_decompress),
}

# Hadoop io.compression.codec.snappy.buffersize default (SnappyCodec) —
# also a safe block size for Lz4Codec interop.
_BLOCK_SIZE = 256 * 1024


def compress_hadoop_blocks(codec: str, data) -> bytes:
    """Compress one slab into whole BlockCompressorStream blocks. Blocks are
    self-delimiting (uncompressed-length + chunk-length headers), so the
    concatenation of slabs compressed independently is exactly the stream
    HadoopBlockFile would have produced for the concatenated plaintext with
    aligned block boundaries — this is what lets the parallel writer
    compress snappy/lz4 slabs on worker threads."""
    compress, _ = _RAW_CODECS[codec]
    # one bytes copy per 256KB block (the native compressors take bytes);
    # the memoryview avoids copying the whole multi-MB slab up front
    view = memoryview(data).cast("B")
    out = bytearray()
    for pos in range(0, len(view), _BLOCK_SIZE):
        block = bytes(view[pos : pos + _BLOCK_SIZE])
        comp = compress(block)
        out += len(block).to_bytes(4, "big")
        out += len(comp).to_bytes(4, "big")
        out += comp
    return bytes(out)


class HadoopBlockFile(io.RawIOBase):
    """BlockCompressorStream/BlockDecompressorStream wire layout shared by
    Hadoop's SnappyCodec and Lz4Codec: per block a 4-byte big-endian
    uncompressed length, then one or more chunks of 4-byte big-endian
    compressed length + compressed payload until the block is complete.
    Writes flush whole blocks; close() closes the underlying stream
    (remote writers upload on close)."""

    def __init__(self, path: str, mode: str, codec: str,
                 fileobj: Optional[BinaryIO] = None):
        super().__init__()
        self._path = path
        self._codec = codec
        self._compress, self._decompress = _RAW_CODECS[codec]
        # lz4 chunks carry no own output-size header; the block header's
        # remaining byte count sizes the native decode buffer exactly
        self._pass_bound = codec == "lz4"
        if "w" in mode:
            self._raw = fileobj if fileobj is not None else open(path, "wb")
            self._writing = True
            self._wbuf = bytearray()
        else:
            self._raw = fileobj if fileobj is not None else open(path, "rb")
            self._writing = False
            self._pending = bytearray()
            self._eof = False

    def readable(self) -> bool:
        return not self._writing

    def writable(self) -> bool:
        return self._writing

    # -- read side ---------------------------------------------------------

    def _read_be4(self, what: str) -> Optional[int]:
        hdr = _read_exact(self._raw, 4)
        if not hdr:
            return None  # clean EOF only at a block boundary
        if len(hdr) < 4:
            raise _corruption(
                f"truncated {self._codec} stream in {self._path}: partial {what}"
            )
        return int.from_bytes(hdr, "big")

    def _fill(self) -> None:
        uncomp_len = self._read_be4("block header")
        if uncomp_len is None:
            self._eof = True
            return
        got = 0
        while got < uncomp_len:
            chunk_len = self._read_be4("chunk header")
            if chunk_len is None:
                raise _corruption(
                    f"truncated {self._codec} stream in {self._path}: "
                    "EOF inside a block"
                )
            chunk = _read_exact(self._raw, chunk_len)
            if len(chunk) < chunk_len:
                raise _corruption(
                    f"truncated {self._codec} stream in {self._path}: "
                    "EOF inside a chunk"
                )
            if self._pass_bound:
                plain = self._decompress(chunk, max_out=uncomp_len - got)
            else:
                plain = self._decompress(chunk)
            got += len(plain)
            self._pending += plain
        if got != uncomp_len:
            raise _corruption(
                f"corrupt {self._codec} stream in {self._path}: block "
                f"decoded to {got} bytes, header promised {uncomp_len}"
            )

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            while not self._eof:
                self._fill()
            out = bytes(self._pending)
            self._pending = bytearray()
            return out
        while len(self._pending) < size and not self._eof:
            self._fill()
        out = bytes(self._pending[:size])
        del self._pending[:size]
        return out

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    # -- write side --------------------------------------------------------

    def _emit_block(self, block: bytes) -> None:
        comp = self._compress(block)
        self._raw.write(len(block).to_bytes(4, "big"))
        self._raw.write(len(comp).to_bytes(4, "big"))
        self._raw.write(comp)

    def _flush_block(self) -> None:
        if self._wbuf:
            block = bytes(self._wbuf)
            self._wbuf = bytearray()
            self._emit_block(block)

    def write(self, data) -> int:
        self._wbuf += data
        while len(self._wbuf) >= _BLOCK_SIZE:
            block = bytes(self._wbuf[:_BLOCK_SIZE])
            del self._wbuf[:_BLOCK_SIZE]
            self._emit_block(block)
        return len(data)

    def close(self) -> None:
        if not self.closed:
            try:
                if self._writing:
                    self._flush_block()
            finally:
                if not self._raw.closed:
                    self._raw.close()
                super().close()


# ---------------------------------------------------------------------------
# bzip2 (stdlib)
# ---------------------------------------------------------------------------


class Bz2File(io.RawIOBase):
    """Hadoop BZip2Codec streams are standard (possibly concatenated) .bz2.
    stdlib bz2 handles multi-stream; EOFError on a truncated stream maps to
    TFRecordCorruptionError like every other codec here."""

    def __init__(self, path: str, mode: str, fileobj: Optional[BinaryIO] = None):
        super().__init__()
        import bz2

        self._path = path
        raw = fileobj if fileobj is not None else open(
            path, "wb" if "w" in mode else "rb"
        )
        self._raw = raw
        self._inner = bz2.BZ2File(raw, "wb" if "w" in mode else "rb")
        self._writing = "w" in mode

    def readable(self) -> bool:
        return not self._writing

    def writable(self) -> bool:
        return self._writing

    def read(self, size: int = -1) -> bytes:
        try:
            return self._inner.read(size if size is not None and size >= 0 else -1)
        except (EOFError, OSError) as e:
            raise _corruption(
                f"truncated or corrupt bzip2 stream in {self._path}: {e}"
            ) from e

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def write(self, data) -> int:
        return self._inner.write(data)

    def close(self) -> None:
        if not self.closed:
            try:
                self._inner.close()
            finally:
                if not self._raw.closed:
                    self._raw.close()
                super().close()
