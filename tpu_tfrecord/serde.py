"""Schema-driven row <-> tf.Example/SequenceExample codec.

TPU-native re-implementation of the reference's TFRecordSerializer.scala and
TFRecordDeserializer.scala, with the exact same type semantics:

- Integer/Long -> Int64List; Float -> FloatList; Double and Decimal are
  DOWNCAST to float32 on the wire (TFRecordSerializer.scala:86-90) and come
  back widened (Double) / re-decimalized (Decimal) on read
  (TFRecordDeserializer.scala:86-91).
- String -> utf-8 BytesList; Binary -> BytesList.
- Array of a scalar type -> the corresponding list feature.
- Array-of-Array -> a SequenceExample FeatureList (one inner Feature per
  sub-array; TFRecordSerializer.scala:137-147). Only valid for
  SequenceExample rows.
- Null handling: a None value for a nullable field is OMITTED on write
  (TFRecordSerializer.scala:24-33) and a missing feature reads back as None
  for nullable fields; for non-nullable fields both directions raise
  (TFRecordSerializer.scala:29-31, TFRecordDeserializer.scala:31).
- On read, the feature kind must match the schema type family
  ("Feature must be of type ..." requires, TFRecordDeserializer.scala:177-221).

Rows are plain Python sequences aligned to the schema's field order, with
None for null — the analog of Spark's InternalRow. Converters/writers are
precomputed PER SCHEMA at construction for both directions; the reference only
did this on the serialize side and rebuilt writers per field per row on
deserialize (TFRecordDeserializer.scala:29 vs TFRecordSerializer.scala:14) —
an inefficiency SURVEY.md §3.1 calls out, fixed here.

Decoders are stateless: every call builds a fresh row, so values can never
leak between records (pinned by the reference's state-leak regression test,
TFRecordDeserializerTest.scala:313-346, mirrored in tests/test_serde.py).
"""

from __future__ import annotations

import decimal
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from tpu_tfrecord import proto
from tpu_tfrecord.proto import (
    BYTES_LIST,
    FLOAT_LIST,
    INT64_LIST,
    Example,
    Feature,
    FeatureList,
    SequenceExample,
)
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    StructType,
)

Row = List[Any]


class NullValueError(ValueError):
    """A null value where the schema forbids it (the reference throws
    NullPointerException, e.g. TFRecordSerializer.scala:30)."""


class UnsupportedDataTypeError(ValueError):
    """A schema type outside the supported vocabulary (the reference throws
    RuntimeException at converter construction, TFRecordSerializer.scala:151)."""


def _f32(value: Any) -> float:
    return float(np.float32(value))


def _to_i32(value: int) -> int:
    """Scala Long.toInt semantics: two's-complement truncation to 32 bits."""
    value &= 0xFFFFFFFF
    return value - (1 << 32) if value >= (1 << 31) else value


# ---------------------------------------------------------------------------
# Serializer (row -> proto)
# ---------------------------------------------------------------------------


class TFRecordSerializer:
    """Serialize rows to Example / SequenceExample / raw bytes.

    Mirrors reference TFRecordSerializer.scala:12-208. Unsupported top-level
    types raise at construction (pinned by TFRecordSerializerTest.scala:290-299).
    """

    def __init__(self, schema: StructType):
        self.schema = schema
        self._converters = [self._new_feature_converter(f.data_type) for f in schema]
        self._is_feature_list = [
            isinstance(f.data_type, ArrayType)
            and isinstance(f.data_type.element_type, ArrayType)
            for f in schema
        ]

    # -- entry points -------------------------------------------------------

    def serialize_byte_array(self, row: Sequence[Any]) -> bytes:
        value = row[0]
        if not isinstance(value, (bytes, bytearray, memoryview)):
            raise TypeError("ByteArray record type requires a single binary column")
        return bytes(value)

    def serialize_example(self, row: Sequence[Any]) -> Example:
        example = Example()
        for idx, field in enumerate(self.schema):
            value = row[idx]
            if value is not None:
                if self._is_feature_list[idx]:
                    raise UnsupportedDataTypeError(
                        f"Field {field.name}: array-of-array maps to a "
                        "FeatureList and requires recordType=SequenceExample"
                    )
                example.features[field.name] = self._converters[idx](value)
            elif not field.nullable:
                raise NullValueError(f"{field.name} does not allow null values")
        return example

    def serialize_sequence_example(self, row: Sequence[Any]) -> SequenceExample:
        se = SequenceExample()
        for idx, field in enumerate(self.schema):
            value = row[idx]
            if value is not None:
                if self._is_feature_list[idx]:
                    se.feature_lists[field.name] = self._converters[idx](value)
                else:
                    se.context[field.name] = self._converters[idx](value)
            elif not field.nullable:
                raise NullValueError(f"{field.name} does not allow null values")
        return se

    # -- converters ---------------------------------------------------------

    def _new_feature_converter(self, dtype: DataType) -> Callable[[Any], Any]:
        if isinstance(dtype, NullType):
            return lambda value: None
        if isinstance(dtype, (IntegerType, LongType)):
            return lambda value: Feature(INT64_LIST, [int(value)])
        if isinstance(dtype, FloatType):
            return lambda value: Feature(FLOAT_LIST, [_f32(value)])
        if isinstance(dtype, (DoubleType, DecimalType)):
            # Explicit precision loss: double/decimal -> float32 on the wire.
            return lambda value: Feature(FLOAT_LIST, [_f32(value)])
        if isinstance(dtype, StringType):
            return lambda value: Feature(BYTES_LIST, [str(value).encode("utf-8")])
        if isinstance(dtype, BinaryType):
            return lambda value: Feature(BYTES_LIST, [bytes(value)])
        if isinstance(dtype, ArrayType):
            return self._new_array_converter(dtype)
        raise UnsupportedDataTypeError(
            f"Cannot convert field to unsupported data type {dtype}"
        )

    def _new_array_converter(self, dtype: ArrayType) -> Callable[[Any], Any]:
        elem = dtype.element_type
        if isinstance(elem, (IntegerType, LongType)):
            def conv(values):
                return Feature(INT64_LIST, [int(_not_null(v)) for v in values])
        elif isinstance(elem, (FloatType, DoubleType, DecimalType)):
            def conv(values):
                return Feature(FLOAT_LIST, [_f32(_not_null(v)) for v in values])
        elif isinstance(elem, StringType):
            def conv(values):
                return Feature(
                    BYTES_LIST, [str(_not_null(v)).encode("utf-8") for v in values]
                )
        elif isinstance(elem, BinaryType):
            def conv(values):
                return Feature(BYTES_LIST, [bytes(_not_null(v)) for v in values])
        elif isinstance(elem, ArrayType):
            # 2-D array -> FeatureList (TFRecordSerializer.scala:137-147).
            inner = self._new_feature_converter(elem)
            def conv(values):
                return FeatureList([inner(_not_null(v)) for v in values])
        else:
            raise UnsupportedDataTypeError(
                f"Array element data type {elem} is unsupported"
            )
        return conv


def _not_null(value: Any) -> Any:
    if value is None:
        # The reference NPEs on null array elements when building the proto
        # (bytesListFeature -> ByteString.copyFrom(null)).
        raise NullValueError("null array element cannot be written to a TFRecord feature")
    return value


# ---------------------------------------------------------------------------
# Deserializer (proto -> row)
# ---------------------------------------------------------------------------


def _require_kind(feature: Feature, kind: int, label: str) -> None:
    if feature is None or feature.kind != kind:
        raise ValueError(f"Feature must be of type {label}")


def _int64_values(feature: Feature) -> Sequence[int]:
    _require_kind(feature, INT64_LIST, "Int64List")
    return feature.values


def _float_values(feature: Feature) -> Sequence[float]:
    _require_kind(feature, FLOAT_LIST, "FloatList")
    return feature.values


def _bytes_values(feature: Feature) -> Sequence[bytes]:
    _require_kind(feature, BYTES_LIST, "ByteList")
    return feature.values


def _head(values: Sequence, label: str):
    if len(values) == 0:
        raise ValueError(f"empty {label} feature has no head value")
    return values[0]


class TFRecordDeserializer:
    """Deserialize Example / SequenceExample / raw bytes into rows.

    Mirrors reference TFRecordDeserializer.scala:15-277. Feature writers are
    precomputed per schema (the reference rebuilt them per field per row).
    """

    def __init__(self, schema: StructType):
        self.schema = schema
        self._writers = [self._new_feature_writer(f.data_type) for f in schema]
        self._list_writers = [self._new_feature_list_writer(f.data_type) for f in schema]

    # -- entry points -------------------------------------------------------

    def deserialize_byte_array(self, data: bytes) -> Row:
        return [bytes(data)]

    def deserialize_example(self, example: Example) -> Row:
        row: Row = [None] * len(self.schema)
        for idx, field in enumerate(self.schema):
            feature = example.features.get(field.name)
            if feature is not None:
                row[idx] = self._writers[idx](feature)
            elif not field.nullable:
                raise NullValueError(f"Field {field.name} does not allow null values")
        return row

    def deserialize_sequence_example(self, se: SequenceExample) -> Row:
        row: Row = [None] * len(self.schema)
        for idx, field in enumerate(self.schema):
            feature = se.context.get(field.name)
            if feature is not None:
                row[idx] = self._writers[idx](feature)
                continue
            flist = se.feature_lists.get(field.name)
            if flist is not None:
                writer = self._list_writers[idx]
                if writer is None:
                    raise UnsupportedDataTypeError(
                        f"Cannot convert FeatureList to data type "
                        f"{field.data_type} for field {field.name}"
                    )
                row[idx] = writer(flist)
            elif not field.nullable:
                raise NullValueError(f"Field {field.name} does not allow null values")
        return row

    # -- feature writers ----------------------------------------------------

    def _new_feature_writer(self, dtype: DataType) -> Callable[[Feature], Any]:
        if isinstance(dtype, NullType):
            return lambda feature: None
        if isinstance(dtype, IntegerType):
            return lambda feature: _to_i32(_head(_int64_values(feature), "Int64List"))
        if isinstance(dtype, LongType):
            return lambda feature: int(_head(_int64_values(feature), "Int64List"))
        if isinstance(dtype, FloatType):
            return lambda feature: float(_head(_float_values(feature), "FloatList"))
        if isinstance(dtype, DoubleType):
            return lambda feature: float(_head(_float_values(feature), "FloatList"))
        if isinstance(dtype, DecimalType):
            return lambda feature: decimal.Decimal(
                str(_head(_float_values(feature), "FloatList"))
            )
        if isinstance(dtype, StringType):
            return lambda feature: _head(_bytes_values(feature), "ByteList").decode("utf-8")
        if isinstance(dtype, BinaryType):
            return lambda feature: bytes(_head(_bytes_values(feature), "ByteList"))
        if isinstance(dtype, ArrayType):
            return self._new_array_writer(dtype)
        raise UnsupportedDataTypeError(f"{dtype} is not supported yet.")

    def _new_array_writer(self, dtype: ArrayType) -> Callable[[Feature], List[Any]]:
        elem = dtype.element_type
        if isinstance(elem, IntegerType):
            return lambda feature: [_to_i32(v) for v in _int64_values(feature)]
        if isinstance(elem, LongType):
            return lambda feature: [int(v) for v in _int64_values(feature)]
        if isinstance(elem, FloatType):
            return lambda feature: [float(v) for v in _float_values(feature)]
        if isinstance(elem, DoubleType):
            return lambda feature: [float(v) for v in _float_values(feature)]
        if isinstance(elem, DecimalType):
            return lambda feature: [
                decimal.Decimal(str(v)) for v in _float_values(feature)
            ]
        if isinstance(elem, StringType):
            return lambda feature: [v.decode("utf-8") for v in _bytes_values(feature)]
        if isinstance(elem, BinaryType):
            return lambda feature: [bytes(v) for v in _bytes_values(feature)]
        if isinstance(elem, ArrayType):
            # A nested array can never come from a single Feature — only from
            # a FeatureList. Defer the error to call time, like the reference
            # (writers there are built lazily per row, so a SequenceExample
            # field served by a FeatureList never hits this path).
            def bad_writer(feature):
                raise UnsupportedDataTypeError(
                    f"Cannot convert Array type to unsupported data type {elem}"
                )

            return bad_writer
        raise UnsupportedDataTypeError(
            f"Cannot convert Array type to unsupported data type {elem}"
        )

    def _new_feature_list_writer(
        self, dtype: DataType
    ) -> Optional[Callable[[FeatureList], List[Any]]]:
        """Writer for FeatureList -> ArrayType(element); each inner Feature is
        decoded with the element type's feature writer
        (TFRecordDeserializer.scala:129-143). None for non-array types."""
        if not isinstance(dtype, ArrayType):
            return None
        try:
            elem_writer = self._new_feature_writer(dtype.element_type)
        except UnsupportedDataTypeError:
            return None
        return lambda flist: [elem_writer(f) for f in flist.feature]


# ---------------------------------------------------------------------------
# Record-level convenience: serialized bytes <-> rows
# ---------------------------------------------------------------------------


def encode_row(serializer: TFRecordSerializer, record_type, row: Sequence[Any]) -> bytes:
    """Row -> serialized record bytes, dispatching on record type (the write
    hot loop body, ref TFRecordOutputWriter.scala:26-38)."""
    from tpu_tfrecord.options import RecordType

    if record_type == RecordType.EXAMPLE:
        return proto.encode_example(serializer.serialize_example(row))
    if record_type == RecordType.SEQUENCE_EXAMPLE:
        return proto.encode_sequence_example(serializer.serialize_sequence_example(row))
    if record_type == RecordType.BYTE_ARRAY:
        return serializer.serialize_byte_array(row)
    raise ValueError(f"Unsupported recordType {record_type}")


def decode_record(deserializer: TFRecordDeserializer, record_type, data: bytes) -> Row:
    """Serialized record bytes -> row (the read hot loop body, ref
    TFRecordFileReader.scala:46-82)."""
    from tpu_tfrecord.options import RecordType

    if record_type == RecordType.EXAMPLE:
        return deserializer.deserialize_example(proto.parse_example(data))
    if record_type == RecordType.SEQUENCE_EXAMPLE:
        return deserializer.deserialize_sequence_example(proto.parse_sequence_example(data))
    if record_type == RecordType.BYTE_ARRAY:
        return deserializer.deserialize_byte_array(data)
    raise ValueError(f"Unsupported recordType {record_type}")
