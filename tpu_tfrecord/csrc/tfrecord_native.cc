// TFRecord native fast path: CRC32C, frame scan, batch Example decode.
//
// Re-implements natively the two components the reference ships as shaded
// JVM libraries (SURVEY.md §2.8 tensorflow-hadoop wire codec, §2.9 protobuf
// runtime), fused: one pass over an in-memory shard buffer produces columnar
// output buffers ready to wrap as numpy arrays. Exposed as a plain C ABI and
// driven from Python via ctypes (no pybind11 in the image); ctypes releases
// the GIL for the duration of each call, so decode overlaps Python-side work
// and device transfers.
//
// Layouts match tpu_tfrecord.columnar.Column exactly:
//   scalar : values[N]                        + mask[N]
//   ragged : values[total] + row_offsets[N+1] + mask[N]
//   ragged2: values[total] + inner_offsets[M+1] + row_offsets[N+1] + mask[N]
//   bytes-like columns use blob + blob_offsets (value boundaries) instead of
//   a typed values buffer.
//
// Build: g++ -std=c++20 -O3 -fPIC -shared [-msse4.2] tfrecord_native.cc

#include <array>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif
#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// CRC32C
// ---------------------------------------------------------------------------

uint32_t crc32c_table[8][256];
bool crc32c_table_init_done = false;

void init_crc32c_table() {
  if (crc32c_table_init_done) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc32c_table[0][i] = crc;
  }
  for (int k = 1; k < 8; k++)
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = crc32c_table[k - 1][i];
      crc32c_table[k][i] = (c >> 8) ^ crc32c_table[0][c & 0xFF];
    }
  crc32c_table_init_done = true;
}

uint32_t crc32c_sw(const uint8_t* p, uint64_t n, uint32_t crc) {
  crc ^= 0xFFFFFFFFu;
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    w ^= crc;  // little-endian
    crc = crc32c_table[7][w & 0xFF] ^ crc32c_table[6][(w >> 8) & 0xFF] ^
          crc32c_table[5][(w >> 16) & 0xFF] ^ crc32c_table[4][(w >> 24) & 0xFF] ^
          crc32c_table[3][(w >> 32) & 0xFF] ^ crc32c_table[2][(w >> 40) & 0xFF] ^
          crc32c_table[1][(w >> 48) & 0xFF] ^ crc32c_table[0][(w >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ crc32c_table[0][(crc ^ *p++) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

#if defined(__SSE4_2__)
// Advance-by-256-zero-bytes tables: shift256(c) == the CRC state after
// feeding 256 zero bytes starting from state c. The state update is linear
// over GF(2), so the transform decomposes into 4 byte-indexed tables. This
// lets three independent _mm_crc32_u64 chains run in parallel over 3x256B
// blocks (the serial 3-cycle latency chain is the bottleneck of the naive
// loop) and be combined afterwards — ~2x on the ~1KB payloads TFRecord
// shards typically carry.
uint32_t crc_shift256_tbl[4][256];
std::once_flag crc_shift256_once;

void init_crc_shift256_impl() {
  uint32_t basis[32];
  for (int b = 0; b < 32; b++) {
    uint32_t c = 1u << b;
    for (int i = 0; i < 32; i++) c = (uint32_t)_mm_crc32_u64(c, 0);  // 8 zero bytes x32
    basis[b] = c;
  }
  for (int k = 0; k < 4; k++) {
    for (int v = 0; v < 256; v++) {
      uint32_t acc = 0;
      for (int j = 0; j < 8; j++)
        if (v & (1 << j)) acc ^= basis[8 * k + j];
      crc_shift256_tbl[k][v] = acc;
    }
  }
}

// Decode worker threads (num_workers>1) may race the lazy init; call_once
// gives the table stores release/acquire ordering a plain bool guard lacks.
void init_crc_shift256() { std::call_once(crc_shift256_once, init_crc_shift256_impl); }

inline uint32_t crc_shift256(uint32_t c) {
  return crc_shift256_tbl[0][c & 0xFF] ^ crc_shift256_tbl[1][(c >> 8) & 0xFF] ^
         crc_shift256_tbl[2][(c >> 16) & 0xFF] ^ crc_shift256_tbl[3][c >> 24];
}
#endif

uint32_t crc32c_impl(const uint8_t* p, uint64_t n, uint32_t crc) {
#if defined(__SSE4_2__)
  crc ^= 0xFFFFFFFFu;
  if (n >= 768) {
    init_crc_shift256();
    do {
      uint32_t c0 = crc, c1 = 0, c2 = 0;
      const uint8_t* p1 = p + 256;
      const uint8_t* p2 = p + 512;
      for (int i = 0; i < 256; i += 8) {
        uint64_t w0, w1, w2;
        std::memcpy(&w0, p + i, 8);
        std::memcpy(&w1, p1 + i, 8);
        std::memcpy(&w2, p2 + i, 8);
        c0 = (uint32_t)_mm_crc32_u64(c0, w0);
        c1 = (uint32_t)_mm_crc32_u64(c1, w1);
        c2 = (uint32_t)_mm_crc32_u64(c2, w2);
      }
      crc = crc_shift256(crc_shift256(c0) ^ c1) ^ c2;
      p += 768;
      n -= 768;
    } while (n >= 768);
  }
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    crc = (uint32_t)_mm_crc32_u64(crc, w);
    p += 8;
    n -= 8;
  }
  while (n--) crc = _mm_crc32_u8(crc, *p++);
  return crc ^ 0xFFFFFFFFu;
#else
  return crc32c_sw(p, n, crc);
#endif
}

// CRC32C of a short blob (categorical keys are a few bytes): straight-line
// hardware steps, no loop setup or 3-way machinery.
inline uint32_t crc32c_short(const uint8_t* p, uint64_t n) {
#if defined(__SSE4_2__)
  uint32_t crc = 0xFFFFFFFFu;
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    crc = (uint32_t)_mm_crc32_u64(crc, w);
    p += 8;
    n -= 8;
  }
  if (n >= 4) {
    uint32_t w;
    std::memcpy(&w, p, 4);
    crc = _mm_crc32_u32(crc, w);
    p += 4;
    n -= 4;
  }
  if (n >= 2) {
    uint16_t w;
    std::memcpy(&w, p, 2);
    crc = _mm_crc32_u16(crc, w);
    p += 2;
    n -= 2;
  }
  if (n) crc = _mm_crc32_u8(crc, *p);
  return crc ^ 0xFFFFFFFFu;
#else
  return crc32c_impl(p, n, 0);
#endif
}

// One owner for the short/long split: below crc32c_impl's 3-way block size
// (768B) the straight-line path wins; at or above it the interleaved
// streams do. Hashing call sites use this, never the threshold directly.
inline uint32_t crc32c_hash(const uint8_t* p, uint64_t n) {
  return n < 768 ? crc32c_short(p, n) : crc32c_impl(p, n, 0);
}

inline uint32_t masked_crc(const uint8_t* p, uint64_t n) {
  uint32_t c = crc32c_impl(p, n, 0);
  return ((c >> 15) | (c << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Protobuf wire primitives
// ---------------------------------------------------------------------------

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
};

inline bool read_varint(Cursor& c, uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  while (c.p < c.end) {
    uint8_t b = *c.p++;
    result |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

inline bool turbo_read_varint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  if (p < end && !(*p & 0x80)) { *out = *p++; return true; }  // 1-byte fast case
  uint64_t result = 0;
  int shift = 0;
  while (p < end) {
    uint8_t b = *p++;
    result |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) { *out = result; return true; }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

// Branch-light varint decode: load 8 bytes, locate the terminator byte with
// ctz over the inverted continuation bits, extract the payload bits with
// PEXT. Covers varints up to 8 bytes (56 bits — every int32-range feature);
// longer ones and buffer tails fall back to the byte loop. Compiled with a
// per-function target attribute and dispatched at runtime so the library
// never executes PEXT on a CPU without BMI2 (and the binary itself is not
// built -mbmi2). Note: PEXT is microcoded (slow) on AMD Zen1/Zen2; the
// expected deployment (TPU host VMs) is Intel, where it is 3 cycles.
#if defined(__x86_64__)
__attribute__((target("bmi2"), noinline))
bool turbo_varint_pext(const uint8_t*& p, uint64_t* out) {
  uint64_t w;
  std::memcpy(&w, p, 8);
  uint64_t term = ~w & 0x8080808080808080ULL;  // terminator high bits
  if (!term) return false;  // >8-byte varint: caller falls back
  int nbytes = (__builtin_ctzll(term) >> 3) + 1;
  uint64_t mask = (nbytes == 8) ? ~0ULL : ((1ULL << (8 * nbytes)) - 1);
  *out = _pext_u64(w & mask, 0x7F7F7F7F7F7F7F7FULL);
  p += nbytes;
  return true;
}
const bool g_has_bmi2 = __builtin_cpu_supports("bmi2");
#endif

inline bool turbo_varint_fast(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
#if defined(__x86_64__)
  if (g_has_bmi2 && end - p >= 8 && turbo_varint_pext(p, out)) return true;
#endif
  return turbo_read_varint(p, end, out);
}

inline bool skip_field(Cursor& c, uint32_t wire_type) {
  uint64_t tmp;
  switch (wire_type) {
    case 0: return read_varint(c, &tmp);
    case 1: if (c.end - c.p < 8) return false; c.p += 8; return true;
    case 2:
      if (!read_varint(c, &tmp) || (uint64_t)(c.end - c.p) < tmp) return false;
      c.p += tmp;
      return true;
    case 5: if (c.end - c.p < 4) return false; c.p += 4; return true;
    default: return false;
  }
}

// ---------------------------------------------------------------------------
// Column builders
// ---------------------------------------------------------------------------

constexpr int32_t KIND_BYTES = 1, KIND_FLOAT = 2, KIND_INT64 = 3;
constexpr int32_t LAYOUT_SCALAR = 0, LAYOUT_RAGGED = 1, LAYOUT_RAGGED2 = 2;
constexpr int32_t DT_I64 = 0, DT_I32 = 1, DT_F32 = 2, DT_F64 = 3, DT_BYTES = -1;

struct ColBuilder {
  int32_t layout = LAYOUT_SCALAR;
  int32_t kind = KIND_INT64;
  int32_t dtype = DT_I64;
  bool nullable = true;
  int64_t hash_buckets = 0;  // >0: bytes values hash to i32 during decode
  // Column-group packing: scalar fields assigned to a group write straight
  // into a shared [n_records, width] matrix at (cur_row, group_pos) instead
  // of their own vector — the batch layout MXU consumers want, with no
  // per-column extraction or Python-side stacking.
  uint8_t* group_buf = nullptr;
  int64_t group_stride = 0;  // bytes per row
  int64_t group_off = 0;     // byte offset of this field within a row
  int64_t cur_row = 0;
  std::string name;

  std::vector<int64_t> i64;
  std::vector<int32_t> i32;
  std::vector<float> f32;
  std::vector<double> f64;
  std::vector<uint8_t> blob;
  std::vector<int64_t> blob_offsets;  // value boundaries in blob
  std::vector<int64_t> row_offsets;   // per-row value (or inner-list) counts
  std::vector<int64_t> inner_offsets; // ragged2 only
  std::vector<uint8_t> mask;

  int64_t value_count = 0;   // running for row_offsets
  int64_t inner_count = 0;   // running for ragged2 inner lists

  void init_offsets() {
    row_offsets.push_back(0);
    if (layout == LAYOUT_RAGGED2) inner_offsets.push_back(0);
    if (dtype == DT_BYTES && hash_buckets == 0) blob_offsets.push_back(0);
  }

  inline void push_i64(int64_t v) {
    if (group_buf) {
      uint8_t* p = group_buf + cur_row * group_stride + group_off;
      if (dtype == DT_I64) std::memcpy(p, &v, 8);
      else { int32_t t = (int32_t)v; std::memcpy(p, &t, 4); }
      return;
    }
    if (dtype == DT_I64) i64.push_back(v);
    else i32.push_back((int32_t)v);  // Scala Long.toInt truncation semantics
  }
  inline void push_f32(float v) {
    if (group_buf) {
      uint8_t* p = group_buf + cur_row * group_stride + group_off;
      if (dtype == DT_F32) std::memcpy(p, &v, 4);
      else { double t = (double)v; std::memcpy(p, &t, 8); }
      return;
    }
    if (dtype == DT_F32) f32.push_back(v);
    else f64.push_back((double)v);
  }
  inline void push_hashed(int32_t v) {
    if (group_buf) {
      std::memcpy(group_buf + cur_row * group_stride + group_off, &v, 4);
      return;
    }
    i32.push_back(v);
  }
  inline void push_bytes(const uint8_t* p, uint64_t n) {
    blob.insert(blob.end(), p, p + n);
    blob_offsets.push_back((int64_t)blob.size());
  }

  // Undo record ``r``'s (single) contribution to this column — clear its
  // mask slot plus whatever values/offsets it appended. Everything is
  // derivable from the buffer tails, so duplicate-key last-wins semantics
  // cost nothing on the happy path. Only called after this record wrote to
  // the column (dedup via seen_epoch, or the turbo slot walk), so the value
  // tails are this record's; masks are positional (pre-filled 1), so the
  // clear is an idempotent store.
  void rollback(int64_t r) {
    if ((size_t)r < mask.size()) mask[(size_t)r] = 0;
    if (group_buf) {
      // Zero the slot: if the duplicate's last occurrence turns out to be
      // missing (unset oneof), the documented missing->0 must hold — the
      // first occurrence's value may not survive.
      int itemsize = (dtype == DT_I64 || dtype == DT_F64) ? 8 : 4;
      std::memset(group_buf + r * group_stride + group_off, 0, itemsize);
      return;
    }
    if (layout == LAYOUT_SCALAR) {
      if (dtype == DT_BYTES) {
        if (blob_offsets.size() < 2) return;
        blob_offsets.pop_back();
        blob.resize((size_t)blob_offsets.back());
      } else {
        switch (dtype) {
          case DT_I64: if (!i64.empty()) i64.pop_back(); break;
          case DT_I32: if (!i32.empty()) i32.pop_back(); break;
          case DT_F32: if (!f32.empty()) f32.pop_back(); break;
          case DT_F64: if (!f64.empty()) f64.pop_back(); break;
        }
      }
      return;
    }
    if (row_offsets.size() < 2) return;
    row_offsets.pop_back();
    int64_t prev = row_offsets.back();
    if (layout == LAYOUT_RAGGED) {
      value_count = prev;
      if (dtype == DT_BYTES) {
        blob_offsets.resize((size_t)prev + 1);
        blob.resize((size_t)blob_offsets.back());
      } else {
        switch (dtype) {
          case DT_I64: i64.resize((size_t)prev); break;
          case DT_I32: i32.resize((size_t)prev); break;
          case DT_F32: f32.resize((size_t)prev); break;
          case DT_F64: f64.resize((size_t)prev); break;
        }
      }
    } else {  // RAGGED2: row_offsets index inner lists
      value_count = prev;
      inner_offsets.resize((size_t)prev + 1);
      inner_count = inner_offsets.back();
      if (dtype == DT_BYTES) {
        blob_offsets.resize((size_t)inner_count + 1);
        blob.resize((size_t)blob_offsets.back());
      } else {
        switch (dtype) {
          case DT_I64: i64.resize((size_t)inner_count); break;
          case DT_I32: i32.resize((size_t)inner_count); break;
          case DT_F32: f32.resize((size_t)inner_count); break;
          case DT_F64: f64.resize((size_t)inner_count); break;
        }
      }
    }
  }
};

struct BatchResult {
  std::vector<ColBuilder> cols;
  std::vector<std::vector<uint8_t>> group_bufs;
  std::string error;
};

struct string_hash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const { return std::hash<std::string_view>{}(sv); }
  size_t operator()(const std::string& s) const { return std::hash<std::string_view>{}(s); }
};

using FieldMap = std::unordered_map<std::string, int, string_hash, std::equal_to<>>;

// Heterogeneous unordered lookup (P0919) landed in libstdc++ 11; on older
// toolchains (GCC 10 ships with this image's Debian) fall back to a
// temporary std::string. The StickyOrder fast path keeps the hash lookup
// rare, so the fallback allocation is off the hot path.
inline FieldMap::const_iterator field_find(const FieldMap& m, std::string_view key) {
#if defined(__cpp_lib_generic_unordered_lookup)
  return m.find(key);
#else
  return m.find(std::string(key));
#endif
}

// Records from one writer almost always carry their feature-map entries in
// the same key order. Remember the order seen in the first record and match
// subsequent records' keys by position with a single memcmp — a hit skips
// the hash lookup entirely (including for keys NOT in the schema).
struct StickyOrder {
  std::vector<std::pair<std::string, int>> order;  // key -> field idx (-1: skip)
  size_t cursor = 0;
  bool building = true;

  inline int lookup(std::string_view key, const FieldMap& fields) {
    if (cursor < order.size()) {
      const auto& e = order[cursor];
      if (e.first.size() == key.size() &&
          std::memcmp(e.first.data(), key.data(), key.size()) == 0) {
        cursor++;
        return e.second;
      }
    }
    auto it = field_find(fields, key);
    int idx = it == fields.end() ? -1 : it->second;
    if (building) {
      order.emplace_back(std::string(key), idx);
      cursor = order.size();
    } else {
      cursor = order.size();  // out of sync for the rest of this record
    }
    return idx;
  }

  inline void next_record() {
    building = false;
    cursor = 0;
  }
};

// Parse one Feature submessage's values into col. element_cap: for scalar
// columns only the first value is kept but extra values are legal (head
// semantics of the reference deserializer). Returns value count, or -1 on
// kind mismatch / parse error (err set).
int64_t parse_feature_values(const uint8_t* fp, const uint8_t* fend,
                             ColBuilder& col, bool scalar, std::string& err) {
  Cursor c{fp, fend};
  int64_t count = 0;
  bool kind_seen = false;
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated feature tag"; return -1; }
    uint32_t fnum = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if ((int32_t)fnum != col.kind || wt != 2) {
      if (fnum >= 1 && fnum <= 3 && wt == 2) {
        err = "column " + col.name + ": feature kind does not match schema type";
        return -1;
      }
      if (!skip_field(c, wt)) { err = "bad field in feature"; return -1; }
      continue;
    }
    kind_seen = true;
    uint64_t len;
    if (!read_varint(c, &len) || (uint64_t)(c.end - c.p) < len) {
      err = "truncated list"; return -1;
    }
    Cursor lc{c.p, c.p + len};
    c.p += len;
    // Inside BytesList/FloatList/Int64List: field 1 values.
    while (lc.p < lc.end) {
      uint64_t ltag;
      if (!read_varint(lc, &ltag)) { err = "truncated list tag"; return -1; }
      uint32_t lnum = (uint32_t)(ltag >> 3), lwt = (uint32_t)(ltag & 7);
      if (lnum != 1) { if (!skip_field(lc, lwt)) { err = "bad list field"; return -1; } continue; }
      if (col.kind == KIND_INT64) {
        if (lwt == 2) {  // packed varints
          uint64_t plen;
          if (!read_varint(lc, &plen) || (uint64_t)(lc.end - lc.p) < plen) { err = "truncated packed"; return -1; }
          Cursor pc{lc.p, lc.p + plen};
          lc.p += plen;
          while (pc.p < pc.end) {
            uint64_t v;
            // PEXT fast decode when available (token-id lists are the
            // SequenceExample int hot case); falls back byte-wise
            if (!turbo_varint_fast(pc.p, pc.end, &v)) { err = "truncated varint"; return -1; }
            if (!scalar || count == 0) col.push_i64((int64_t)v);
            count++;
          }
        } else if (lwt == 0) {
          uint64_t v;
          if (!read_varint(lc, &v)) { err = "truncated varint"; return -1; }
          if (!scalar || count == 0) col.push_i64((int64_t)v);
          count++;
        } else { if (!skip_field(lc, lwt)) { err = "bad int64 enc"; return -1; } }
      } else if (col.kind == KIND_FLOAT) {
        if (lwt == 2) {  // packed floats
          uint64_t plen;
          if (!read_varint(lc, &plen) || (uint64_t)(lc.end - lc.p) < plen || plen % 4) { err = "bad packed floats"; return -1; }
          uint64_t n = plen / 4;
          if (!scalar && col.dtype == DT_F32 && !col.group_buf) {
            // bulk path for ragged float columns (the SequenceExample
            // frames hot case): one memcpy for the whole packed run
            // instead of a per-value push loop — the wire bytes ARE the
            // little-endian f32 layout the column stores
            if (n) {  // memcpy with a null dest (empty vector) is UB
              size_t old = col.f32.size();
              col.f32.resize(old + n);
              std::memcpy(col.f32.data() + old, lc.p, (size_t)plen);
              count += (int64_t)n;
            }
          } else {
            for (uint64_t i = 0; i < n; i++) {
              float v;
              std::memcpy(&v, lc.p + 4 * i, 4);
              if (!scalar || count == 0) col.push_f32(v);
              count++;
            }
          }
          lc.p += plen;
        } else if (lwt == 5) {
          float v;
          if (lc.end - lc.p < 4) { err = "truncated float"; return -1; }
          std::memcpy(&v, lc.p, 4);
          lc.p += 4;
          if (!scalar || count == 0) col.push_f32(v);
          count++;
        } else { if (!skip_field(lc, lwt)) { err = "bad float enc"; return -1; } }
      } else {  // KIND_BYTES
        if (lwt != 2) { if (!skip_field(lc, lwt)) { err = "bad bytes enc"; return -1; } continue; }
        uint64_t blen;
        if (!read_varint(lc, &blen) || (uint64_t)(lc.end - lc.p) < blen) { err = "truncated bytes"; return -1; }
        if (!scalar || count == 0) {
          if (col.hash_buckets > 0) {
            // fused categorical hashing: bytes -> embedding-row index,
            // no blob ever materialized
            uint32_t h = crc32c_hash(lc.p, blen);
            col.push_hashed((int32_t)(h % (uint64_t)col.hash_buckets));
          } else {
            col.push_bytes(lc.p, blen);
          }
        }
        lc.p += blen;
        count++;
      }
    }
  }
  if (!kind_seen) return -2;  // kind oneof unset -> treated as missing
  return count;
}

// Decode one Features map region (Example.features or SequenceExample.context)
// seen_epoch: record index for which a column holds a value (any source).
// seen_fl_epoch: record index for which that value came from feature_lists —
// needed to arbitrate precedence: context beats feature_lists regardless of
// wire order (the oracle parses into dicts first, columnar.py:340-346), while
// duplicate keys WITHIN one map are protobuf-map last-wins.
bool parse_features_map(const uint8_t* p, const uint8_t* end, const FieldMap& fields,
                        StickyOrder& sticky,
                        std::vector<ColBuilder>& cols, std::vector<int32_t>& seen_epoch,
                        std::vector<int32_t>& seen_fl_epoch,
                        int32_t epoch, std::string& err) {
  Cursor c{p, end};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated features tag"; return false; }
    if ((tag >> 3) != 1 || (tag & 7) != 2) { if (!skip_field(c, (uint32_t)(tag & 7))) { err = "bad features field"; return false; } continue; }
    uint64_t elen;
    if (!read_varint(c, &elen) || (uint64_t)(c.end - c.p) < elen) { err = "truncated map entry"; return false; }
    Cursor ec{c.p, c.p + elen};
    c.p += elen;
    std::string_view key;
    const uint8_t* fstart = nullptr;
    const uint8_t* fend = nullptr;
    while (ec.p < ec.end) {
      uint64_t etag;
      if (!read_varint(ec, &etag)) { err = "truncated entry tag"; return false; }
      uint32_t enum_ = (uint32_t)(etag >> 3), ewt = (uint32_t)(etag & 7);
      if (enum_ == 1 && ewt == 2) {
        uint64_t klen;
        if (!read_varint(ec, &klen) || (uint64_t)(ec.end - ec.p) < klen) { err = "truncated key"; return false; }
        key = std::string_view((const char*)ec.p, klen);
        ec.p += klen;
      } else if (enum_ == 2 && ewt == 2) {
        uint64_t flen;
        if (!read_varint(ec, &flen) || (uint64_t)(ec.end - ec.p) < flen) { err = "truncated feature"; return false; }
        fstart = ec.p;
        fend = ec.p + flen;
        ec.p += flen;
      } else {
        if (!skip_field(ec, ewt)) { err = "bad entry field"; return false; }
      }
    }
    if (key.empty() && fstart == nullptr) continue;
    int idx = sticky.lookup(key, fields);
    if (idx < 0) continue;  // column pruning: skip cheap
    ColBuilder& col = cols[idx];
    if (col.layout == LAYOUT_RAGGED2) {
      err = "column " + col.name + ": flat feature for array-of-array type";
      return false;
    }
    if (seen_epoch[idx] == epoch) {
      // Already set this record: either a duplicate context key (protobuf
      // map last-wins) or a feature_lists entry that appeared earlier in
      // the wire (context has priority either way) — roll back the previous
      // contribution, then re-append.
      col.rollback(epoch);
      seen_epoch[idx] = -1;  // unseen again until the re-append succeeds
      seen_fl_epoch[idx] = -1;  // any feature_lists claim is gone
    }
    col.cur_row = epoch;  // record index, for group-matrix writes
    bool scalar = col.layout == LAYOUT_SCALAR;
    int64_t n = fstart ? parse_feature_values(fstart, fend, col, scalar, err)
                       : -2;
    if (n == -1) return false;
    if (n == -2) continue;  // unset oneof -> missing
    seen_epoch[idx] = epoch;
    if (scalar) {
      if (n == 0) {
        if (col.kind == KIND_BYTES) {
          if (col.hash_buckets > 0) {
            // hash of b"" — crc32c("") == 0 (Python oracle parity)
            col.push_hashed(0);
          } else {
            // Empty BytesList scalar decodes as b"" (Python oracle parity).
            col.blob_offsets.push_back((int64_t)col.blob.size());
          }
        } else {
          err = "column " + col.name + ": empty feature for scalar";
          return false;
        }
      }
      col.mask[(size_t)epoch] = 1;  // positional: rollback may have cleared it
    } else {
      col.value_count += n;
      col.row_offsets.push_back(col.value_count);
      col.mask[(size_t)epoch] = 1;
    }
  }
  return true;
}

bool parse_feature_lists(const uint8_t* p, const uint8_t* end, const FieldMap& fields,
                         StickyOrder& sticky,
                         std::vector<ColBuilder>& cols, std::vector<int32_t>& seen_epoch,
                         std::vector<int32_t>& seen_fl_epoch,
                         int32_t epoch, std::string& err) {
  Cursor c{p, end};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated featurelists tag"; return false; }
    if ((tag >> 3) != 1 || (tag & 7) != 2) { if (!skip_field(c, (uint32_t)(tag & 7))) { err = "bad featurelists field"; return false; } continue; }
    uint64_t elen;
    if (!read_varint(c, &elen) || (uint64_t)(c.end - c.p) < elen) { err = "truncated fl entry"; return false; }
    Cursor ec{c.p, c.p + elen};
    c.p += elen;
    std::string_view key;
    const uint8_t* lstart = nullptr;
    const uint8_t* lend = nullptr;
    while (ec.p < ec.end) {
      uint64_t etag;
      if (!read_varint(ec, &etag)) { err = "truncated fl entry tag"; return false; }
      uint32_t enum_ = (uint32_t)(etag >> 3), ewt = (uint32_t)(etag & 7);
      if (enum_ == 1 && ewt == 2) {
        uint64_t klen;
        if (!read_varint(ec, &klen) || (uint64_t)(ec.end - ec.p) < klen) { err = "truncated fl key"; return false; }
        key = std::string_view((const char*)ec.p, klen);
        ec.p += klen;
      } else if (enum_ == 2 && ewt == 2) {
        uint64_t flen;
        if (!read_varint(ec, &flen) || (uint64_t)(ec.end - ec.p) < flen) { err = "truncated featurelist"; return false; }
        lstart = ec.p;
        lend = ec.p + flen;
        ec.p += flen;
      } else {
        if (!skip_field(ec, ewt)) { err = "bad fl entry field"; return false; }
      }
    }
    int idx = sticky.lookup(key, fields);
    if (idx < 0) continue;
    ColBuilder& col = cols[idx];
    if (seen_epoch[idx] == epoch && seen_fl_epoch[idx] != epoch) {
      // Set by the context map: context wins over feature_lists
      // (oracle parity, columnar.py:340-346) — skip this entry entirely.
      continue;
    }
    if (seen_fl_epoch[idx] == epoch) {
      // Duplicate FeatureList map key in one record: protobuf map semantics
      // are last-wins (matching the Python oracle's dict overwrite) — roll
      // back the previous occurrence's contribution, then re-append, the
      // same contract as the context/features path above.
      col.rollback(epoch);
      seen_epoch[idx] = -1;  // unseen again until the re-append succeeds
      seen_fl_epoch[idx] = -1;
    }
    // iterate FeatureList { repeated Feature feature = 1; }
    int64_t n_inner = 0;
    Cursor lc{lstart ? lstart : end, lend ? lend : end};
    while (lc.p < lc.end) {
      uint64_t ltag;
      if (!read_varint(lc, &ltag)) { err = "truncated fl tag"; return false; }
      if ((ltag >> 3) != 1 || (ltag & 7) != 2) { if (!skip_field(lc, (uint32_t)(ltag & 7))) { err = "bad fl field"; return false; } continue; }
      uint64_t flen;
      if (!read_varint(lc, &flen) || (uint64_t)(lc.end - lc.p) < flen) { err = "truncated inner feature"; return false; }
      const uint8_t* fs = lc.p;
      const uint8_t* fe = lc.p + flen;
      lc.p += flen;
      if (col.layout == LAYOUT_RAGGED2) {
        // fast frame: the common float-frames shape is exactly
        // [0x12 llen 0x0A plen <f32 run>] — bulk-append without the
        // generic per-frame call; any deviation (empty, multi-segment,
        // other kinds) takes the generic path below
        if (col.kind == KIND_FLOAT && col.dtype == DT_F32 && fe - fs >= 4 &&
            fs[0] == 0x12) {
          const uint8_t* q = fs + 1;
          uint64_t llen;
          if (turbo_read_varint(q, fe, &llen) && (uint64_t)(fe - q) == llen &&
              q < fe && *q == 0x0A) {
            const uint8_t* q2 = q + 1;
            uint64_t plen;
            if (turbo_read_varint(q2, fe, &plen) &&
                (uint64_t)(fe - q2) == plen && plen % 4 == 0 && plen > 0) {
              size_t nf = (size_t)(plen / 4);
              size_t old = col.f32.size();
              col.f32.resize(old + nf);
              std::memcpy(col.f32.data() + old, q2, (size_t)plen);
              col.inner_count += (int64_t)nf;
              col.inner_offsets.push_back(col.inner_count);
              n_inner++;
              continue;
            }
          }
        }
        int64_t n = parse_feature_values(fs, fe, col, false, err);
        if (n == -1) return false;
        if (n == -2) n = 0;
        col.inner_count += n;
        col.inner_offsets.push_back(col.inner_count);
        n_inner++;
      } else if (col.layout == LAYOUT_RAGGED) {
        // FeatureList of scalar features: one value per inner feature
        int64_t n = parse_feature_values(fs, fe, col, true, err);
        if (n == -1) return false;
        if (n == 0 || n == -2) { err = "column " + col.name + ": empty inner feature"; return false; }
        n_inner++;
      } else {
        err = "column " + col.name + ": FeatureList for scalar type";
        return false;
      }
    }
    seen_epoch[idx] = epoch;
    seen_fl_epoch[idx] = epoch;
    if (col.layout == LAYOUT_RAGGED2) {
      col.value_count += n_inner;       // rows index inner lists
      col.row_offsets.push_back(col.value_count);
    } else {
      col.value_count += n_inner;
      col.row_offsets.push_back(col.value_count);
    }
    col.mask[(size_t)epoch] = 1;  // positional: rollback may have cleared it
  }
  return true;
}

// ---------------------------------------------------------------------------
// Turbo path: sticky-prefix specialized record parse
// ---------------------------------------------------------------------------
//
// Records from one serializer share their byte-level key structure: every
// record's features map has the same entries in the same order, differing
// only in the value payloads. After the first record builds the sticky
// order, each subsequent record is matched entry-by-entry against the
// precomputed prefix bytes [0x0A klen key] with one memcmp, skipping the
// generic tag-dispatch walk entirely (which costs ~half of decode time on
// wide schemas). ANY deviation — missing/extra/duplicate keys, unexpected
// wire layout, empty or multi-segment features — rolls back the partial
// record and re-parses it with the generic (oracle-verified) path, so turbo
// is purely an optimization: byte-identical results by construction.
// Applies to Example records whose schema is all-scalar (the common dense
// tabular case, e.g. Criteo).

// One cached entry byte shape: all tags + lengths up to the value payload.
// When a record's entry matches the cached bytes (ONE memcmp), the value
// sits at a fixed offset — no per-field tag walking at all.
struct SlotShape {
  std::vector<uint8_t> cache;   // entry bytes from entry tag to value start
  uint32_t entry_total = 0;     // full entry byte length (tag..end)
  uint32_t value_len = 0;       // value payload bytes (BYTES/FLOAT: fixed)
};

struct TurboSlot {
  std::vector<uint8_t> prefix;  // 0x0A klen <key bytes>
  int idx;                      // field index, or -1 (pruned: skip entry)
  // Adaptive entry-shape caches: records from one serializer usually repeat
  // the exact entry byte shape, differing only in the value payload. Varint
  // int values drift among a handful of BYTE LENGTHS (uniform 31-bit ints
  // are ~87% 5-byte / ~12% 4-byte varints), and each length implies a
  // distinct but recurring skeleton — so beyond the MRU shape a small set
  // of alternates is kept, keyed by total entry length. The MRU check is
  // one memcmp; an MRU miss probes the alternates by the candidate entry
  // length read from the entry's own length byte before falling back to
  // the field-wise parse (which verifies and remembers the new shape).
  SlotShape mru;
  std::array<SlotShape, 6> alts;
  int n_alts = 0;
  uint32_t alt_rr = 0;          // round-robin eviction cursor

  // The alternate probe decodes the entry's 1- or 2-byte length varint, so
  // only totals <= 3 + 16383 can ever match an alternate; larger shapes
  // must not occupy (or round-robin-evict) slots they can never win from
  // (r3 advisor finding).
  static bool probe_reachable(uint32_t etot) { return etot <= 3u + 16383u; }

  // Record a field-wise-verified shape as the MRU, demoting the outgoing
  // MRU into the alternate set (replacing any alternate with the same
  // total length). The new shape lives ONLY in the MRU — storing it in the
  // alternates too would let the promotion swap breed duplicates that
  // evict distinct live shapes.
  void remember(const uint8_t* start, const uint8_t* vstart, uint32_t etot,
                uint32_t vlen) {
    if (mru.entry_total && mru.entry_total != etot &&
        probe_reachable(mru.entry_total)) {
      int slot = -1;
      for (int i = 0; i < n_alts; i++) {
        if (alts[i].entry_total == mru.entry_total) { slot = i; break; }
      }
      if (slot < 0) {
        slot = n_alts < (int)alts.size() ? n_alts++
                                         : (int)(alt_rr++ % alts.size());
      }
      alts[slot] = std::move(mru);
    }
    mru.cache.assign(start, vstart);
    mru.entry_total = etot;
    mru.value_len = vlen;
  }
};



// Parse one record in turbo mode. Returns true on success (columns written,
// *out_written = number of distinct fields written — when it equals the
// schema width the caller can skip ALL per-record bookkeeping); false = no
// harm done (partial writes rolled back via the slot walk), caller
// re-parses generically. Slots are mutable: their adaptive entry caches
// refresh as value shapes drift.
bool turbo_parse(const uint8_t* rp, const uint8_t* rend,
                 std::vector<TurboSlot>& slots,
                 std::vector<ColBuilder>& cols, int32_t epoch,
                 int* out_written) {
  const uint8_t* p = rp;
  // Record must be exactly one top-level field: features map (tag 0x0A).
  if (p >= rend || *p != 0x0A) return false;
  p++;
  uint64_t mlen;
  if (!turbo_read_varint(p, rend, &mlen)) return false;
  if ((uint64_t)(rend - p) != mlen) return false;
  int n_written = 0;
  const size_t n_slots = slots.size();
  size_t si = 0;
  // Every completed slot with idx >= 0 wrote exactly one contribution (all
  // abort sites precede the slot's value write), so rolling back the
  // prefix of completed slots undoes the record without per-write
  // bookkeeping on the happy path.
  auto abort_record = [&]() {
    for (size_t j = 0; j < si; j++) {
      if (slots[j].idx >= 0) cols[slots[j].idx].rollback(epoch);
    }
    return false;
  };
  for (; si < n_slots; si++) {
    TurboSlot& s = slots[si];
    // --- cache-hit fast lane: one memcmp covers every tag and length ---
    const SlotShape* shape = nullptr;
    if (s.mru.entry_total && (uint64_t)(rend - p) >= s.mru.entry_total &&
        std::memcmp(p, s.mru.cache.data(), s.mru.cache.size()) == 0) {
      shape = &s.mru;
    } else if (s.n_alts && (uint64_t)(rend - p) >= 2 && p[0] == 0x0A) {
      // MRU miss: the entry's own length varint (1 or 2 bytes — entries
      // up to ~16KB, e.g. long bytes values) names the candidate total
      // length; probe the alternates for that shape. The memcmp verifies
      // the full prefix, so the decoded length only preselects.
      uint32_t etot = 0;
      if (p[1] < 0x80) {
        etot = 2u + p[1];
      } else if ((uint64_t)(rend - p) >= 3 && p[2] < 0x80) {
        etot = 3u + (((uint32_t)(p[1] & 0x7F)) | ((uint32_t)p[2] << 7));
      }
      for (int a = 0; etot && a < s.n_alts; a++) {
        SlotShape& v = s.alts[a];
        if (v.entry_total == etot && (uint64_t)(rend - p) >= etot &&
            std::memcmp(p, v.cache.data(), v.cache.size()) == 0) {
          if (TurboSlot::probe_reachable(s.mru.entry_total)) {
            std::swap(s.mru, v);  // promote; old MRU stays as an alternate
          } else {
            // The outgoing MRU can never be probe-matched: dropping it
            // (compact the set) keeps every alternate slot live instead
            // of parking a dead shape the r3 guard exists to prevent.
            s.mru = std::move(v);
            if (a != --s.n_alts) v = std::move(s.alts[s.n_alts]);
          }
          shape = &s.mru;
          break;
        }
      }
    }
    if (shape) {
      const uint8_t* q = p + shape->cache.size();
      p += shape->entry_total;
      if (s.idx < 0) continue;
      ColBuilder& col = cols[s.idx];
      col.cur_row = epoch;
      if (col.kind == KIND_INT64) {
        // value: one-varint-or-more packed run of value_len bytes. The
        // fast varint may load past ve (within the record) — the q > ve
        // check catches a run with no terminator, like the bounded read.
        const uint8_t* ve = q + shape->value_len;
        uint64_t v;
        if (!turbo_varint_fast(q, rend, &v) || q > ve) return abort_record();
        while (q < ve) {  // rest of the run: validate well-formed varints
          int cont = 0;
          while (q < ve && (*q & 0x80)) { q++; cont++; }
          if (q >= ve || cont > 9) return abort_record();
          q++;
        }
        col.push_i64((int64_t)v);
      } else if (col.kind == KIND_BYTES) {
        if (col.hash_buckets > 0) {
          uint32_t h = crc32c_hash(q, shape->value_len);
          col.push_hashed((int32_t)(h % (uint64_t)col.hash_buckets));
        } else {
          col.push_bytes(q, shape->value_len);
        }
      } else {  // KIND_FLOAT
        float v;
        std::memcpy(&v, q, 4);
        col.push_f32(v);
      }
      n_written++;  // mask slot is pre-filled 1
      continue;
    }
    // --- field-wise lane (cache miss): parse tags, refresh the cache ---
    const uint8_t* p0 = p;  // entry tag byte (cache starts here)
    if (p >= rend || *p != 0x0A) return abort_record();
    p++;
    uint64_t elen;
    if (!turbo_read_varint(p, rend, &elen)) return abort_record();
    const uint8_t* ee = p + elen;
    if (ee > rend || elen < s.prefix.size() ||
        std::memcmp(p, s.prefix.data(), s.prefix.size()) != 0)
      return abort_record();
    const uint8_t* q = p + s.prefix.size();
    p = ee;
    if (s.idx < 0) {
      // pruned column: cache the key prefix so future skips are one memcmp
      if (ee - p0 < 0x10000) {
        s.remember(p0, q, (uint32_t)(ee - p0), 0);
      }
      continue;
    }
    ColBuilder& col = cols[s.idx];
    // map-entry value: Feature (field 2) filling the rest of the entry
    if (q >= ee || *q != 0x12) return abort_record();
    q++;
    uint64_t flen;
    if (!turbo_read_varint(q, ee, &flen)) return abort_record();
    if ((uint64_t)(ee - q) != flen || flen == 0) return abort_record();
    col.cur_row = epoch;
    const uint8_t* vstart = nullptr;
    uint32_t vlen = 0;
    if (col.kind == KIND_INT64) {
      // Feature { int64_list = 3 { packed values = 1 } }
      if (*q != 0x1A) return abort_record();
      q++;
      uint64_t llen;
      if (!turbo_read_varint(q, ee, &llen)) return abort_record();
      if ((uint64_t)(ee - q) != llen || llen == 0) return abort_record();
      if (*q != 0x0A) return abort_record();
      q++;
      uint64_t plen;
      if (!turbo_read_varint(q, ee, &plen)) return abort_record();
      if ((uint64_t)(ee - q) != plen || plen == 0) return abort_record();
      vstart = q;
      vlen = (uint32_t)plen;
      uint64_t v;
      if (!turbo_varint_fast(q, ee, &v)) return abort_record();
      // scalar head semantics: first value wins; the rest of the packed
      // run is legal but must still be well-formed varints (the generic
      // path validates them, so turbo must too)
      while (q < ee) {
        int cont = 0;
        while (q < ee && (*q & 0x80)) { q++; cont++; }
        if (q >= ee || cont > 9) return abort_record();
        q++;
      }
      col.push_i64((int64_t)v);
    } else if (col.kind == KIND_BYTES) {
      // Feature { bytes_list = 1 { values = 1 (len-delimited) } }
      if (*q != 0x0A) return abort_record();
      q++;
      uint64_t llen;
      if (!turbo_read_varint(q, ee, &llen)) return abort_record();
      if ((uint64_t)(ee - q) != llen || llen == 0) return abort_record();
      if (*q != 0x0A) return abort_record();
      q++;
      uint64_t blen;
      if (!turbo_read_varint(q, ee, &blen)) return abort_record();
      if ((uint64_t)(ee - q) < blen) return abort_record();
      // single-value scalar only: a second value changes head semantics
      // bookkeeping, so multi-value records take the generic path
      if ((uint64_t)(ee - q) != blen) return abort_record();
      vstart = q;
      vlen = (uint32_t)blen;
      if (col.hash_buckets > 0) {
        uint32_t h = crc32c_hash(q, blen);
        col.push_hashed((int32_t)(h % (uint64_t)col.hash_buckets));
      } else {
        col.push_bytes(q, blen);
      }
    } else {  // KIND_FLOAT
      // Feature { float_list = 2 { packed values = 1 | single = 5 } }
      if (*q != 0x12) return abort_record();
      q++;
      uint64_t llen;
      if (!turbo_read_varint(q, ee, &llen)) return abort_record();
      if ((uint64_t)(ee - q) != llen || llen == 0) return abort_record();
      float v;
      if (*q == 0x0A) {
        q++;
        uint64_t plen;
        if (!turbo_read_varint(q, ee, &plen)) return abort_record();
        if ((uint64_t)(ee - q) != plen || plen < 4 || (plen & 3)) return abort_record();
        std::memcpy(&v, q, 4);  // head semantics: first of the packed run
        if (plen == 4) { vstart = q; vlen = 4; }
      } else if (*q == 0x0D) {
        q++;
        if ((uint64_t)(ee - q) != 4) return abort_record();
        std::memcpy(&v, q, 4);
        vstart = q;
        vlen = 4;
      } else {
        return abort_record();
      }
      col.push_f32(v);
    }
    // refresh the adaptive caches: entry header bytes up to the value
    // payload; value fills the rest of the entry exactly (verified above)
    if (vstart && (uint64_t)(vstart - p0) + vlen == (uint64_t)(ee - p0) &&
        ee - p0 < 0x10000) {
      s.remember(p0, vstart, (uint32_t)(ee - p0), vlen);
    }
    n_written++;  // mask slot is pre-filled 1
  }
  if (p != rend) return abort_record();  // extra entries -> generic
  *out_written = n_written;
  return true;
}

void append_missing(ColBuilder& col, int64_t r) {
  if ((size_t)r < col.mask.size()) col.mask[(size_t)r] = 0;
  if (col.group_buf) return;  // group matrix is zero-initialized
  if (col.layout == LAYOUT_SCALAR) {
    switch (col.dtype) {
      case DT_I64: col.i64.push_back(0); break;
      case DT_I32: col.i32.push_back(0); break;
      case DT_F32: col.f32.push_back(0.f); break;
      case DT_F64: col.f64.push_back(0.0); break;
      case DT_BYTES: col.blob_offsets.push_back((int64_t)col.blob.size()); break;
    }
  } else {
    col.row_offsets.push_back(col.value_count);
  }
}

}  // namespace

extern "C" {

uint32_t tfr_crc32c(const uint8_t* data, uint64_t len) {
  init_crc32c_table();
  return crc32c_impl(data, len, 0);
}

int64_t tfr_scan_partial(const uint8_t* buf, uint64_t len, int32_t verify,
                         uint64_t* offsets, uint64_t* lengths, int64_t cap,
                         uint64_t* consumed);

// Strict scan: the whole buffer must be complete frames. Returns record
// count, or -1 (corrupt length crc), -2 (truncated), -3 (bad data crc),
// -4 (capacity exceeded). Implemented as partial scan + completeness check
// so the framing/CRC contract lives in one place.
int64_t tfr_scan(const uint8_t* buf, uint64_t len, int32_t verify,
                 uint64_t* offsets, uint64_t* lengths, int64_t cap) {
  uint64_t consumed = 0;
  int64_t n = tfr_scan_partial(buf, len, verify, offsets, lengths, cap, &consumed);
  if (n < 0) return n;
  if (consumed != len) return -2;
  return n;
}

// Partial frame scan for slab streaming: like tfr_scan, but a record that
// extends past the end of the buffer is NOT an error — scanning stops and
// *consumed is set to the byte offset of that record's frame start, so the
// caller can carry the tail into the next slab. CRC failures on complete
// records still error. Reaching ``cap`` records is a CLEAN stop (not an
// error): bytes past the cap are neither framed nor CRC-checked, which is
// what lets record-limited consumers (schema-inference sampling) match the
// lazy Python reader on shards whose corruption lies beyond the limit.
// (tfr_scan's full-buffer contract still reports a short scan as -2 via
// its consumed != len check.)
int64_t tfr_scan_partial(const uint8_t* buf, uint64_t len, int32_t verify,
                         uint64_t* offsets, uint64_t* lengths, int64_t cap,
                         uint64_t* consumed) {
  init_crc32c_table();
  uint64_t pos = 0;
  int64_t n = 0;
  *consumed = 0;
  while (pos < len) {
    if (n >= cap) break;  // clean stop: caller resumes from *consumed
    if (pos + 12 > len) break;  // incomplete header -> tail
    uint64_t rec_len;
    std::memcpy(&rec_len, buf + pos, 8);
    uint32_t len_crc;
    std::memcpy(&len_crc, buf + pos + 8, 4);
    if (verify && masked_crc(buf + pos, 8) != len_crc) return -1;
    uint64_t start = pos + 12;
    if (len - start < 4 || rec_len > len - start - 4) break;  // tail
    if (verify) {
      uint32_t data_crc;
      std::memcpy(&data_crc, buf + start + rec_len, 4);
      if (masked_crc(buf + start, rec_len) != data_crc) return -3;
    }
    offsets[n] = start;
    lengths[n] = rec_len;
    n++;
    pos = start + rec_len + 4;
    *consumed = pos;
  }
  return n;
}

}  // extern "C" (temporarily closed: decode state helpers below are C++)

namespace {

// Shared state for batch decoding — used by both the span-driven
// tfr_decode_batch and the fused tfr_scan_decode (frame scan + decode in
// one pass over the buffer, record bytes decoded while still cache-hot).
struct DecodeState {
  BatchResult* res = nullptr;
  FieldMap fields;
  StickyOrder sticky_features, sticky_lists;
  std::vector<int32_t> seen_epoch, seen_fl_epoch;
  std::vector<TurboSlot> turbo_slots;
  bool turbo_eligible = false, turbo_ready = false;
  int32_t record_format = 0;
  int32_t n_fields = 0;
  std::string err;
};

// Allocate the result + columns. n_records_hint sizes the group matrices
// and reservations; the fused path shrinks group buffers afterwards.
void init_decode_state(DecodeState& st, int64_t n_records_hint,
                       int32_t record_format,
                       int32_t n_fields, const char** field_names,
                       const int32_t* layouts, const int32_t* kinds,
                       const int32_t* dtypes, const uint8_t* nullables,
                       const int64_t* hash_buckets,
                       const int32_t* group_ids, const int64_t* group_offs,
                       int32_t n_groups, const int64_t* group_strides) {
  st.record_format = record_format;
  st.n_fields = n_fields;
  auto* res = new BatchResult();
  st.res = res;
  res->cols.resize(n_fields);
  res->group_bufs.resize(n_groups);
  for (int32_t g = 0; g < n_groups; g++) {
    res->group_bufs[g].assign((size_t)n_records_hint * group_strides[g], 0);
  }
  for (int32_t i = 0; i < n_fields; i++) {
    ColBuilder& col = res->cols[i];
    col.name = field_names[i];
    col.layout = layouts[i];
    col.kind = kinds[i];
    col.dtype = dtypes[i];
    col.nullable = nullables[i] != 0;
    col.hash_buckets = hash_buckets ? hash_buckets[i] : 0;
    if (group_ids && group_ids[i] >= 0) {
      int32_t g = group_ids[i];
      col.group_buf = res->group_bufs[g].data();
      col.group_stride = group_strides[g];
      col.group_off = group_offs[i];
    }
    col.init_offsets();
    st.fields.emplace(col.name, i);
    // Positional mask, pre-filled "present": success paths never touch it
    // (the hot case), missing/rollback clear their record's slot. Sized to
    // the hint; the fused path shrinks it to the decoded count afterwards.
    col.mask.assign((size_t)n_records_hint, 1);
    if (col.layout != LAYOUT_SCALAR) col.row_offsets.reserve(n_records_hint + 1);
    if (col.group_buf) continue;  // values live in the group matrix
    if (col.dtype == DT_BYTES) {
      col.blob_offsets.reserve(n_records_hint + 1);
      col.blob.reserve((size_t)n_records_hint * 8);
    } else if (col.layout == LAYOUT_SCALAR) {
      switch (col.dtype) {
        case DT_I64: col.i64.reserve(n_records_hint); break;
        case DT_I32: col.i32.reserve(n_records_hint); break;
        case DT_F32: col.f32.reserve(n_records_hint); break;
        case DT_F64: col.f64.reserve(n_records_hint); break;
      }
    }
  }
  st.seen_epoch.assign(n_fields, -1);
  st.seen_fl_epoch.assign(n_fields, -1);
  // Turbo eligibility: Example records, all-scalar schema, supported kinds
  // (see turbo_parse). Slots are built from the sticky order after the
  // first record parses generically.
  st.turbo_eligible = record_format == 0 && n_fields <= 256;
  for (int32_t i = 0; st.turbo_eligible && i < n_fields; i++) {
    if (res->cols[i].layout != LAYOUT_SCALAR) st.turbo_eligible = false;
  }
}

// Decode one record (r = its index in this batch). On failure fills errbuf;
// the caller owns cleanup of st.res.
bool decode_one(DecodeState& st, const uint8_t* rp, uint64_t rlen, int64_t r,
                char* errbuf, int64_t errbuf_len) {
  BatchResult* res = st.res;
  const int32_t n_fields = st.n_fields;
  if (r) { st.sticky_features.next_record(); st.sticky_lists.next_record(); }
  int turbo_written = 0;
  if (st.turbo_ready &&
      turbo_parse(rp, rp + rlen, st.turbo_slots, res->cols, (int32_t)r,
                  &turbo_written)) {
    // All fields written: nothing can be missing, and seen_epoch updates
    // are unobservable (later records compare against THEIR index, and
    // record indices never repeat) — skip all per-record bookkeeping.
    if (turbo_written == n_fields) return true;
    for (const TurboSlot& s : st.turbo_slots) {
      if (s.idx >= 0) st.seen_epoch[s.idx] = (int32_t)r;
    }
    for (int32_t i = 0; i < n_fields; i++) {
      if (st.seen_epoch[i] != (int32_t)r) {
        if (!res->cols[i].nullable) {
          std::snprintf(errbuf, errbuf_len, "record %lld: %s", (long long)r,
                        ("Field " + res->cols[i].name +
                         " does not allow null values").c_str());
          return false;
        }
        append_missing(res->cols[i], r);
      }
    }
    return true;
  }
  Cursor c{rp, rp + rlen};
  bool ok = true;
  while (c.p < c.end && ok) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { st.err = "truncated record tag"; ok = false; break; }
    uint32_t fnum = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (wt == 2 && ((st.record_format == 0 && fnum == 1) ||
                    (st.record_format == 1 && (fnum == 1 || fnum == 2)))) {
      uint64_t mlen;
      if (!read_varint(c, &mlen) || (uint64_t)(c.end - c.p) < mlen) { st.err = "truncated message"; ok = false; break; }
      const uint8_t* ms = c.p;
      const uint8_t* me = c.p + mlen;
      c.p += mlen;
      if (st.record_format == 1 && fnum == 2) {
        ok = parse_feature_lists(ms, me, st.fields, st.sticky_lists, res->cols, st.seen_epoch, st.seen_fl_epoch, (int32_t)r, st.err);
      } else {
        ok = parse_features_map(ms, me, st.fields, st.sticky_features, res->cols, st.seen_epoch, st.seen_fl_epoch, (int32_t)r, st.err);
      }
    } else {
      if (!skip_field(c, wt)) { st.err = "bad record field"; ok = false; }
    }
  }
  if (ok) {
    for (int32_t i = 0; i < n_fields; i++) {
      if (st.seen_epoch[i] != (int32_t)r) {
        if (!res->cols[i].nullable) {
          st.err = "Field " + res->cols[i].name + " does not allow null values";
          ok = false;
          break;
        }
        append_missing(res->cols[i], r);
      }
    }
  }
  if (!ok) {
    std::snprintf(errbuf, errbuf_len, "record %lld: %s", (long long)r, st.err.c_str());
    return false;
  }
  if (st.turbo_eligible && !st.turbo_ready && r == 0) {
    // Build the turbo slots from record 0's sticky order. Duplicate keys
    // disable turbo (their last-wins bookkeeping needs the generic path).
    st.turbo_ready = true;
    std::vector<bool> used(n_fields, false);
    for (auto& e : st.sticky_features.order) {
      if (e.first.size() >= 128) { st.turbo_ready = false; break; }
      if (e.second >= 0) {
        if (used[e.second]) { st.turbo_ready = false; break; }
        used[e.second] = true;
      }
      TurboSlot s;
      s.prefix.reserve(2 + e.first.size());
      s.prefix.push_back(0x0A);
      s.prefix.push_back((uint8_t)e.first.size());
      s.prefix.insert(s.prefix.end(), e.first.begin(), e.first.end());
      s.idx = e.second;
      st.turbo_slots.push_back(std::move(s));
    }
    if (st.turbo_slots.empty()) st.turbo_ready = false;
  }
  return true;
}

}  // namespace

extern "C" {

// Batch decode. record_format: 0 = Example, 1 = SequenceExample.
// Returns an opaque handle (free with tfr_result_free) or nullptr with
// errbuf filled.
void* tfr_decode_batch(const uint8_t* buf,
                       const uint64_t* rec_offsets, const uint64_t* rec_lengths,
                       int64_t n_records, int32_t record_format,
                       int32_t n_fields, const char** field_names,
                       const int32_t* layouts, const int32_t* kinds,
                       const int32_t* dtypes, const uint8_t* nullables,
                       const int64_t* hash_buckets,
                       const int32_t* group_ids, const int64_t* group_offs,
                       int32_t n_groups, const int64_t* group_strides,
                       char* errbuf, int64_t errbuf_len) {
  // The fused categorical-hash path uses crc32c; without this, a process
  // whose FIRST native call is decode would hash through a zeroed software
  // CRC table on non-SSE4.2 builds (silent wrong bucket indices).
  init_crc32c_table();
  DecodeState st;
  init_decode_state(st, n_records, record_format, n_fields, field_names,
                    layouts, kinds, dtypes, nullables, hash_buckets,
                    group_ids, group_offs, n_groups, group_strides);
  for (int64_t r = 0; r < n_records; r++) {
    if (!decode_one(st, buf + rec_offsets[r], rec_lengths[r], r, errbuf, errbuf_len)) {
      delete st.res;
      return nullptr;
    }
  }
  return st.res;
}

// Fused frame scan + decode: walk TFRecord frames from buf+start, verify
// CRCs (when verify), skip the first skip_records complete frames
// (scanned+verified but not decoded — the resume path), then decode up to
// max_records records in the same pass (each record parsed immediately
// after its CRC while its bytes are cache-hot; no offsets/lengths arrays
// materialize at all). Stops at max_records or at a partial tail frame
// (*consumed = absolute end of the last processed frame; not an error).
// Returns a result handle, or nullptr with errbuf filled (prefix
// "corrupt TFRecord"/"truncated TFRecord" = framing, else decode error).
void* tfr_scan_decode(const uint8_t* buf, uint64_t len, uint64_t start,
                      int32_t verify, int64_t skip_records, int64_t max_records,
                      uint64_t max_record_bytes,
                      int32_t record_format,
                      int32_t n_fields, const char** field_names,
                      const int32_t* layouts, const int32_t* kinds,
                      const int32_t* dtypes, const uint8_t* nullables,
                      const int64_t* hash_buckets,
                      const int32_t* group_ids, const int64_t* group_offs,
                      int32_t n_groups, const int64_t* group_strides,
                      int64_t* n_skipped, int64_t* n_decoded, uint64_t* consumed,
                      char* errbuf, int64_t errbuf_len) {
  init_crc32c_table();
  DecodeState st;
  init_decode_state(st, max_records, record_format, n_fields, field_names,
                    layouts, kinds, dtypes, nullables, hash_buckets,
                    group_ids, group_offs, n_groups, group_strides);
  uint64_t pos = start;
  int64_t skipped = 0, decoded = 0;
  *consumed = start;
  while (decoded < max_records) {
    if (pos + 12 > len) break;  // incomplete header -> tail
    uint64_t rec_len;
    std::memcpy(&rec_len, buf + pos, 8);
    if (max_record_bytes && rec_len > max_record_bytes) {
      // a corrupt length field (possible with verify off) must never
      // swallow the rest of the shard as one giant "record"
      std::snprintf(errbuf, errbuf_len,
                    "corrupt TFRecord: record length %llu exceeds "
                    "max_record_bytes (%llu)",
                    (unsigned long long)rec_len,
                    (unsigned long long)max_record_bytes);
      delete st.res;
      return nullptr;
    }
    uint32_t len_crc;
    std::memcpy(&len_crc, buf + pos + 8, 4);
    if (verify && masked_crc(buf + pos, 8) != len_crc) {
      std::snprintf(errbuf, errbuf_len, "corrupt TFRecord: bad length CRC");
      delete st.res;
      return nullptr;
    }
    uint64_t rstart = pos + 12;
    if (len - rstart < 4 || rec_len > len - rstart - 4) break;  // tail
    if (verify) {
      uint32_t data_crc;
      std::memcpy(&data_crc, buf + rstart + rec_len, 4);
      if (masked_crc(buf + rstart, rec_len) != data_crc) {
        std::snprintf(errbuf, errbuf_len, "corrupt TFRecord: bad data CRC");
        delete st.res;
        return nullptr;
      }
    }
    pos = rstart + rec_len + 4;
    if (skipped < skip_records) {
      skipped++;
      *consumed = pos;
      continue;
    }
    if (!decode_one(st, buf + rstart, rec_len, decoded, errbuf, errbuf_len)) {
      delete st.res;
      return nullptr;
    }
    decoded++;
    *consumed = pos;
  }
  // Group matrices and masks were sized for max_records; shrink to what
  // decoded.
  for (size_t g = 0; g < st.res->group_bufs.size(); g++) {
    st.res->group_bufs[g].resize((size_t)decoded * group_strides[g]);
  }
  for (auto& col : st.res->cols) col.mask.resize((size_t)decoded);
  *n_skipped = skipped;
  *n_decoded = decoded;
  return st.res;
}

static ColBuilder* get_col(void* h, int32_t i) {
  return &static_cast<BatchResult*>(h)->cols[i];
}

// Drop everything a long-lived handle no longer needs: per-column vectors
// (their contents were copied to Python) and group-buffer slack capacity.
// MUST be called BEFORE tfr_result_group hands out group pointers —
// shrink_to_fit may reallocate. Keeps a handle pinned by zero-copy views
// from holding more than the group matrices themselves.
void tfr_result_trim(void* h) {
  auto* res = static_cast<BatchResult*>(h);
  for (auto& c : res->cols) {
    std::vector<int64_t>().swap(c.i64);
    std::vector<int32_t>().swap(c.i32);
    std::vector<float>().swap(c.f32);
    std::vector<double>().swap(c.f64);
    std::vector<uint8_t>().swap(c.blob);
    std::vector<int64_t>().swap(c.blob_offsets);
    std::vector<int64_t>().swap(c.row_offsets);
    std::vector<int64_t>().swap(c.inner_offsets);
    std::vector<uint8_t>().swap(c.mask);
  }
  for (auto& g : res->group_bufs) g.shrink_to_fit();
}

int64_t tfr_result_values(void* h, int32_t i, const void** ptr) {
  ColBuilder* c = get_col(h, i);
  switch (c->dtype) {
    case DT_I64: *ptr = c->i64.data(); return (int64_t)c->i64.size() * 8;
    case DT_I32: *ptr = c->i32.data(); return (int64_t)c->i32.size() * 4;
    case DT_F32: *ptr = c->f32.data(); return (int64_t)c->f32.size() * 4;
    case DT_F64: *ptr = c->f64.data(); return (int64_t)c->f64.size() * 8;
    default: *ptr = nullptr; return 0;
  }
}

int64_t tfr_result_row_offsets(void* h, int32_t i, const int64_t** ptr) {
  ColBuilder* c = get_col(h, i);
  *ptr = c->row_offsets.data();
  return (int64_t)c->row_offsets.size();
}

int64_t tfr_result_inner_offsets(void* h, int32_t i, const int64_t** ptr) {
  ColBuilder* c = get_col(h, i);
  *ptr = c->inner_offsets.data();
  return (int64_t)c->inner_offsets.size();
}

int64_t tfr_result_blob(void* h, int32_t i, const uint8_t** ptr) {
  ColBuilder* c = get_col(h, i);
  *ptr = c->blob.data();
  return (int64_t)c->blob.size();
}

int64_t tfr_result_blob_offsets(void* h, int32_t i, const int64_t** ptr) {
  ColBuilder* c = get_col(h, i);
  *ptr = c->blob_offsets.data();
  return (int64_t)c->blob_offsets.size();
}

int64_t tfr_result_mask(void* h, int32_t i, const uint8_t** ptr) {
  ColBuilder* c = get_col(h, i);
  *ptr = c->mask.data();
  return (int64_t)c->mask.size();
}

int64_t tfr_result_group(void* h, int32_t g, const uint8_t** ptr) {
  auto& buf = static_cast<BatchResult*>(h)->group_bufs[g];
  *ptr = buf.data();
  return (int64_t)buf.size();
}

void tfr_result_free(void* h) { delete static_cast<BatchResult*>(h); }

// Frame + write helpers: frame records into an output buffer.
// Returns bytes written or -1 if out_cap too small.
int64_t tfr_frame_records(const uint8_t* payloads, const uint64_t* offsets,
                          const uint64_t* lengths, int64_t n,
                          uint8_t* out, int64_t out_cap) {
  init_crc32c_table();
  uint64_t pos = 0;
  for (int64_t i = 0; i < n; i++) {
    uint64_t len = lengths[i];
    if ((int64_t)(pos + 16 + len) > out_cap) return -1;
    std::memcpy(out + pos, &len, 8);
    uint32_t lcrc = masked_crc(out + pos, 8);
    std::memcpy(out + pos + 8, &lcrc, 4);
    std::memcpy(out + pos + 12, payloads + offsets[i], len);
    uint32_t dcrc = masked_crc(out + pos + 12, len);
    std::memcpy(out + pos + 12 + len, &dcrc, 4);
    pos += 16 + len;
  }
  return (int64_t)pos;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch encode: columnar buffers -> framed tf.Example records
// ---------------------------------------------------------------------------
//
// The write-side twin of tfr_decode_batch: one call turns a columnar batch
// (same layouts) into a contiguous stream of framed records. Two-phase API:
// tfr_encode_batch with out=null returns the exact byte size; a second call
// fills the caller-allocated buffer (numpy array) and returns bytes written.

namespace {

inline int varint_size(uint64_t v) {
  int n = 1;
  while (v >= 0x80) { v >>= 7; n++; }
  return n;
}

inline void write_varint(uint8_t*& p, uint64_t v) {
  while (v >= 0x80) { *p++ = (uint8_t)(v | 0x80); v >>= 7; }
  *p++ = (uint8_t)v;
}

struct EncCol {
  const char* name;
  size_t name_len;
  int32_t layout;              // LAYOUT_SCALAR / RAGGED / RAGGED2
  int32_t kind;
  int32_t dtype;
  const uint8_t* values;       // typed buffer
  const int64_t* row_offsets;  // null for scalar
  const int64_t* inner_offsets;  // ragged2 only
  const uint8_t* blob;
  const int64_t* blob_offsets;
  const uint8_t* mask;         // null = all present

  inline bool present(int64_t r) const { return mask == nullptr || mask[r]; }

  inline void value_range(int64_t r, int64_t* v0, int64_t* v1) const {
    if (row_offsets) { *v0 = row_offsets[r]; *v1 = row_offsets[r + 1]; }
    else { *v0 = r; *v1 = r + 1; }
  }

  // size of the list payload (the packed values / bytes entries)
  inline uint64_t list_payload_size(int64_t v0, int64_t v1) const {
    uint64_t sz = 0;
    if (kind == KIND_INT64) {
      if (dtype == DT_I64) {
        const int64_t* p = (const int64_t*)values;
        for (int64_t i = v0; i < v1; i++) sz += varint_size((uint64_t)p[i]);
      } else {
        const int32_t* p = (const int32_t*)values;
        for (int64_t i = v0; i < v1; i++) sz += varint_size((uint64_t)(int64_t)p[i]);
      }
    } else if (kind == KIND_FLOAT) {
      sz = (uint64_t)(v1 - v0) * 4;
    } else {
      for (int64_t i = v0; i < v1; i++) {
        uint64_t blen = (uint64_t)(blob_offsets[i + 1] - blob_offsets[i]);
        sz += 1 + varint_size(blen) + blen;  // tag + len + bytes per value
      }
    }
    return sz;
  }

  // Feature submessage (the `kind { values }` oneof) over a value range
  inline uint64_t feature_msg_size(int64_t v0, int64_t v1) const {
    uint64_t list_payload = list_payload_size(v0, v1);
    uint64_t list_msg = (kind == KIND_BYTES)
                            ? list_payload
                            : (v1 > v0 ? 1 + varint_size(list_payload) + list_payload : 0);
    return 1 + varint_size(list_msg) + list_msg;
  }

  inline void write_feature_msg(uint8_t*& p, int64_t v0, int64_t v1) const {
    uint64_t list_payload = list_payload_size(v0, v1);
    uint64_t list_msg = (kind == KIND_BYTES)
                            ? list_payload
                            : (v1 > v0 ? 1 + varint_size(list_payload) + list_payload : 0);
    *p++ = (uint8_t)((kind << 3) | 2);  // oneof submessage tag
    write_varint(p, list_msg);
    if (kind == KIND_BYTES) {
      for (int64_t v = v0; v < v1; v++) {
        uint64_t blen = (uint64_t)(blob_offsets[v + 1] - blob_offsets[v]);
        *p++ = 0x0A;  // value, field 1 LEN
        write_varint(p, blen);
        std::memcpy(p, blob + blob_offsets[v], blen);
        p += blen;
      }
    } else if (v1 > v0) {
      *p++ = 0x0A;  // packed values, field 1 LEN
      write_varint(p, list_payload);
      if (kind == KIND_INT64) {
        if (dtype == DT_I64) {
          const int64_t* vp = (const int64_t*)values;
          for (int64_t v = v0; v < v1; v++) write_varint(p, (uint64_t)vp[v]);
        } else {
          const int32_t* vp = (const int32_t*)values;
          for (int64_t v = v0; v < v1; v++) write_varint(p, (uint64_t)(int64_t)vp[v]);
        }
      } else {
        if (dtype == DT_F32) {
          std::memcpy(p, values + v0 * 4, (size_t)(v1 - v0) * 4);
          p += (v1 - v0) * 4;
        } else {  // f64 -> f32 downcast on the wire
          const double* vp = (const double*)values;
          for (int64_t v = v0; v < v1; v++) {
            float f = (float)vp[v];
            std::memcpy(p, &f, 4);
            p += 4;
          }
        }
      }
    }
  }

  // FeatureList submessage (repeated Feature, one per inner list) for a
  // ragged2 row spanning inner lists [j0, j1)
  inline uint64_t featurelist_msg_size(int64_t j0, int64_t j1) const {
    uint64_t sz = 0;
    for (int64_t j = j0; j < j1; j++) {
      uint64_t f = feature_msg_size(inner_offsets[j], inner_offsets[j + 1]);
      sz += 1 + varint_size(f) + f;
    }
    return sz;
  }

  inline void write_featurelist_msg(uint8_t*& p, int64_t j0, int64_t j1) const {
    for (int64_t j = j0; j < j1; j++) {
      uint64_t f = feature_msg_size(inner_offsets[j], inner_offsets[j + 1]);
      *p++ = 0x0A;  // FeatureList.feature, field 1 LEN
      write_varint(p, f);
      write_feature_msg(p, inner_offsets[j], inner_offsets[j + 1]);
    }
  }

  // map entry (key + value submessage) wrapper
  inline uint64_t entry_size(uint64_t value_msg) const {
    return 1 + varint_size(name_len) + name_len + 1 + varint_size(value_msg) + value_msg;
  }

  inline void write_entry_header(uint8_t*& p, uint64_t value_msg) const {
    *p++ = 0x0A;  // key, field 1 LEN
    write_varint(p, name_len);
    std::memcpy(p, name, name_len);
    p += name_len;
    *p++ = 0x12;  // value, field 2 LEN
    write_varint(p, value_msg);
  }
};

}  // namespace

extern "C" {

// Encode a batch of Example (record_format 0) or SequenceExample (1)
// records from columnar buffers. For SequenceExample, ragged2 columns
// become FeatureLists; scalar/ragged columns go to the context map. If
// out == nullptr, returns the exact total framed size. Otherwise writes and
// returns bytes written (-1 if cap too small, -2 on bad input).
int64_t tfr_encode_batch(
    int64_t n_records, int32_t record_format, int32_t n_fields,
    const char** field_names, const int64_t* name_lens,
    const int32_t* layouts, const int32_t* kinds, const int32_t* dtypes,
    const uint8_t** values, const int64_t** row_offsets,
    const int64_t** inner_offsets,
    const uint8_t** blobs, const int64_t** blob_offsets,
    const uint8_t** masks,
    uint8_t* out, int64_t cap) {
  init_crc32c_table();
  std::vector<EncCol> cols((size_t)n_fields);
  for (int32_t i = 0; i < n_fields; i++) {
    cols[i] = EncCol{field_names[i], (size_t)name_lens[i], layouts[i],
                     kinds[i], dtypes[i], values[i], row_offsets[i],
                     inner_offsets[i], blobs[i], blob_offsets[i], masks[i]};
    if (record_format == 0 && layouts[i] == LAYOUT_RAGGED2) return -2;
  }
  uint64_t total = 0;
  uint8_t* p = out;
  // per-record scratch: each field's value-submessage size, computed once in
  // the size pass and reused by the write pass
  std::vector<uint64_t> msg_size((size_t)n_fields);
  for (int64_t r = 0; r < n_records; r++) {
    // ---- size pass for this record ----
    uint64_t features_payload = 0;   // context / Example features map
    uint64_t lists_payload = 0;      // SequenceExample feature_lists map
    for (int32_t i = 0; i < n_fields; i++) {
      EncCol& c = cols[i];
      if (!c.present(r)) continue;
      if (c.layout == LAYOUT_RAGGED2) {
        int64_t j0 = c.row_offsets[r], j1 = c.row_offsets[r + 1];
        uint64_t fl = msg_size[i] = c.featurelist_msg_size(j0, j1);
        uint64_t entry = c.entry_size(fl);
        lists_payload += 1 + varint_size(entry) + entry;
      } else {
        int64_t v0, v1;
        c.value_range(r, &v0, &v1);
        uint64_t f = msg_size[i] = c.feature_msg_size(v0, v1);
        uint64_t entry = c.entry_size(f);
        features_payload += 1 + varint_size(entry) + entry;
      }
    }
    uint64_t body;
    if (record_format == 0) {
      body = features_payload
                 ? 1 + varint_size(features_payload) + features_payload
                 : 0;
    } else {
      // SequenceExample always carries both submessages (reference
      // serializer sets context and featureLists unconditionally)
      body = 1 + varint_size(features_payload) + features_payload +
             1 + varint_size(lists_payload) + lists_payload;
    }
    uint64_t framed = 16 + body;
    total += framed;
    if (out == nullptr) continue;
    if ((int64_t)(p - out) + (int64_t)framed > cap) return -1;

    // ---- write pass ----
    uint8_t* rec_start = p;
    std::memcpy(p, &body, 8);
    uint32_t lcrc = masked_crc(p, 8);
    std::memcpy(p + 8, &lcrc, 4);
    p += 12;
    uint8_t* data_start = p;
    if (record_format != 0 || features_payload) {
      *p++ = 0x0A;  // features / context, field 1 LEN
      write_varint(p, features_payload);
      for (int32_t i = 0; i < n_fields; i++) {
        EncCol& c = cols[i];
        if (!c.present(r) || c.layout == LAYOUT_RAGGED2) continue;
        int64_t v0, v1;
        c.value_range(r, &v0, &v1);
        uint64_t f = msg_size[i];
        *p++ = 0x0A;  // map entry, field 1 LEN
        write_varint(p, c.entry_size(f));
        c.write_entry_header(p, f);
        c.write_feature_msg(p, v0, v1);
      }
    }
    if (record_format != 0) {
      *p++ = 0x12;  // feature_lists, field 2 LEN
      write_varint(p, lists_payload);
      for (int32_t i = 0; i < n_fields; i++) {
        EncCol& c = cols[i];
        if (!c.present(r) || c.layout != LAYOUT_RAGGED2) continue;
        int64_t j0 = c.row_offsets[r], j1 = c.row_offsets[r + 1];
        uint64_t fl = msg_size[i];
        *p++ = 0x0A;  // map entry, field 1 LEN
        write_varint(p, c.entry_size(fl));
        c.write_entry_header(p, fl);
        c.write_featurelist_msg(p, j0, j1);
      }
    }
    uint32_t dcrc = masked_crc(data_start, body);
    std::memcpy(p, &dcrc, 4);
    p += 4;
    if ((uint64_t)(p - rec_start) != framed) return -2;  // size/write mismatch
  }
  return out == nullptr ? (int64_t)total : (int64_t)(p - out);
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// Hadoop-ecosystem block codecs: raw snappy + lz4 block decompression.
// The Python fallbacks in hadoop_codecs.py are spec-complete but decode
// element-dense (real-compressor) streams at tens of MB/s; these run at
// memory speed. Contract: return decoded length, -1 on corrupt input,
// -2 when dst_cap is too small. NEVER read/write out of bounds — these
// functions face untrusted bytes (fuzz-tested).
// ---------------------------------------------------------------------------

// Raw snappy: preamble varint (uncompressed length), then tagged elements
// (literals + 1/2/4-byte-offset copies; overlapping copies = RLE).
int64_t tfr_snappy_decompress(const uint8_t* src, uint64_t n,
                              uint8_t* dst, uint64_t dst_cap) {
  const uint8_t* p = src;
  const uint8_t* end = src + n;
  uint64_t expected = 0;
  int shift = 0;
  for (;;) {
    if (p >= end || shift > 35) return -1;
    uint8_t b = *p++;
    expected |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  if (expected > dst_cap) return -2;
  uint8_t* d = dst;
  uint8_t* dend = dst + expected;
  while (p < end) {
    uint8_t tag = *p++;
    uint64_t len, offset;
    switch (tag & 0x03) {
      case 0: {  // literal
        len = tag >> 2;
        if (len >= 60) {
          uint32_t extra = (uint32_t)len - 59;
          if ((uint64_t)(end - p) < extra) return -1;
          len = 0;
          for (uint32_t i = 0; i < extra; i++) len |= (uint64_t)p[i] << (8 * i);
          p += extra;
        }
        len += 1;
        if ((uint64_t)(end - p) < len || (uint64_t)(dend - d) < len) return -1;
        std::memcpy(d, p, len);
        d += len;
        p += len;
        continue;
      }
      case 1:  // copy, 1-byte offset
        if (p >= end) return -1;
        len = ((tag >> 2) & 0x07) + 4;
        offset = ((uint64_t)(tag >> 5) << 8) | *p++;
        break;
      case 2:  // copy, 2-byte offset
        if (end - p < 2) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)p[0] | ((uint64_t)p[1] << 8);
        p += 2;
        break;
      default:  // copy, 4-byte offset
        if (end - p < 4) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)p[0] | ((uint64_t)p[1] << 8) |
                 ((uint64_t)p[2] << 16) | ((uint64_t)p[3] << 24);
        p += 4;
        break;
    }
    if (offset == 0 || offset > (uint64_t)(d - dst)) return -1;
    if ((uint64_t)(dend - d) < len) return -1;
    const uint8_t* s = d - offset;
    if (offset >= len) {
      std::memcpy(d, s, len);
      d += len;
    } else {
      for (uint64_t i = 0; i < len; i++) *d++ = s[i];  // RLE semantics
    }
  }
  return (d == dend) ? (int64_t)expected : -1;
}

// LZ4 block: sequences of [token][lit-len ext][literals][offset LE16]
// [match-len ext]; the final sequence is literals-only.
int64_t tfr_lz4_decompress(const uint8_t* src, uint64_t n,
                           uint8_t* dst, uint64_t dst_cap) {
  const uint8_t* p = src;
  const uint8_t* end = src + n;
  uint8_t* d = dst;
  uint8_t* dend = dst + dst_cap;
  while (p < end) {
    uint8_t token = *p++;
    uint64_t lit = token >> 4;
    if (lit == 15) {
      for (;;) {
        if (p >= end) return -1;
        uint8_t b = *p++;
        lit += b;
        if (b != 255) break;
      }
    }
    if ((uint64_t)(end - p) < lit) return -1;
    if ((uint64_t)(dend - d) < lit) return -2;
    std::memcpy(d, p, lit);
    d += lit;
    p += lit;
    if (p >= end) break;  // final literals-only sequence
    if (end - p < 2) return -1;
    uint64_t offset = (uint64_t)p[0] | ((uint64_t)p[1] << 8);
    p += 2;
    if (offset == 0 || offset > (uint64_t)(d - dst)) return -1;
    uint64_t mlen = (token & 0x0F) + 4;
    if ((token & 0x0F) == 15) {
      for (;;) {
        if (p >= end) return -1;
        uint8_t b = *p++;
        mlen += b;
        if (b != 255) break;
      }
    }
    if ((uint64_t)(dend - d) < mlen) return -2;
    const uint8_t* s = d - offset;
    if (offset >= mlen) {
      std::memcpy(d, s, mlen);
      d += mlen;
    } else {
      for (uint64_t i = 0; i < mlen; i++) *d++ = s[i];
    }
  }
  return (int64_t)(d - dst);
}

// ---------------------------------------------------------------------------
// Block COMPRESSORS (round 4): real greedy-matching snappy and lz4-block
// encoders, so SnappyCodec/Lz4Codec WRITES actually compress without any
// optional Python dependency (VERDICT r3 item 7 — the pure-Python
// fallbacks emit valid literal-only streams at ratio 1.0). Standard
// design: a 2^14-entry hash table over 4-byte windows, greedy match
// extension, snappy fragmented into 64KB blocks (2-byte offsets), lz4 over
// the whole input with the 64KB-offset window enforced per match.
// Contract: return bytes written, -2 if dst_cap is below the worst-case
// bound (callers size dst via tfr_*_max_compressed).
// ---------------------------------------------------------------------------

static inline uint32_t load32_le(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

int64_t tfr_snappy_max_compressed(uint64_t n) {
  return 32 + (int64_t)n + (int64_t)(n / 6);  // snappy MaxCompressedLength bound
}

static uint8_t* snappy_emit_literal(uint8_t* d, const uint8_t* lit,
                                    uint64_t len) {
  if (!len) return d;
  uint64_t l = len - 1;
  if (l < 60) {
    *d++ = (uint8_t)(l << 2);
  } else {
    int extra = 0;
    for (uint64_t t = l; t; t >>= 8) extra++;
    *d++ = (uint8_t)((59 + extra) << 2);
    for (int i = 0; i < extra; i++) *d++ = (uint8_t)(l >> (8 * i));
  }
  std::memcpy(d, lit, len);
  return d + len;
}

static uint8_t* snappy_emit_copy_upto64(uint8_t* d, uint64_t offset,
                                        uint64_t len) {
  if (len < 12 && offset < 2048) {
    *d++ = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
    *d++ = (uint8_t)offset;
  } else {
    *d++ = (uint8_t)(2 | ((len - 1) << 2));
    *d++ = (uint8_t)offset;
    *d++ = (uint8_t)(offset >> 8);
  }
  return d;
}

static uint8_t* snappy_emit_copy(uint8_t* d, uint64_t offset, uint64_t len) {
  while (len >= 68) {  // long matches: 64-byte copies, tail kept >= 4
    d = snappy_emit_copy_upto64(d, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    d = snappy_emit_copy_upto64(d, offset, 60);
    len -= 60;
  }
  return snappy_emit_copy_upto64(d, offset, len);
}

int64_t tfr_snappy_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                            uint64_t dst_cap) {
  if ((int64_t)dst_cap < tfr_snappy_max_compressed(n)) return -2;
  uint8_t* d = dst;
  for (uint64_t v = n;;) {  // preamble: uncompressed length varint
    uint8_t b = v & 0x7F;
    v >>= 7;
    if (v) {
      *d++ = b | 0x80;
    } else {
      *d++ = b;
      break;
    }
  }
  constexpr uint64_t kBlock = 1 << 16;  // offsets stay 2-byte
  constexpr int kHashBits = 14;
  uint16_t table[1 << kHashBits];
  for (uint64_t bstart = 0; bstart < n; bstart += kBlock) {
    const uint8_t* base = src + bstart;
    const uint64_t blen = (n - bstart < kBlock) ? (n - bstart) : kBlock;
    const uint8_t* iend = base + blen;
    const uint8_t* ip = base;
    const uint8_t* lit = base;
    if (blen > 4) {
      std::memset(table, 0, sizeof(table));
      const uint8_t* match_limit = iend - 4;  // 4-byte loads stay in bounds
      while (ip < match_limit) {
        const uint32_t h =
            (load32_le(ip) * 0x1e35a7bdu) >> (32 - kHashBits);
        const uint8_t* cand = base + table[h];
        table[h] = (uint16_t)(ip - base);
        if (cand < ip && load32_le(cand) == load32_le(ip)) {
          const uint8_t* q = ip + 4;
          const uint8_t* mp = cand + 4;
          while (q < iend && *q == *mp) {
            q++;
            mp++;
          }
          d = snappy_emit_literal(d, lit, (uint64_t)(ip - lit));
          d = snappy_emit_copy(d, (uint64_t)(ip - cand), (uint64_t)(q - ip));
          ip = q;
          lit = ip;
        } else {
          ip++;
        }
      }
    }
    d = snappy_emit_literal(d, lit, (uint64_t)(iend - lit));
  }
  return (int64_t)(d - dst);
}

int64_t tfr_lz4_max_compressed(uint64_t n) {
  return (int64_t)n + (int64_t)(n / 255) + 16;
}

int64_t tfr_lz4_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                         uint64_t dst_cap) {
  if ((int64_t)dst_cap < tfr_lz4_max_compressed(n)) return -2;
  // The match table stores int32 positions: beyond 2 GiB positions alias
  // (output would stay valid — matches are byte-verified — but the ratio
  // collapses silently). Callers frame in 256 KiB Hadoop blocks; refuse
  // the out-of-contract single-call case instead of degrading.
  if (n > (uint64_t)INT32_MAX) return -2;
  uint8_t* d = dst;
  const uint8_t* iend = src + n;
  const uint8_t* ip = src;
  const uint8_t* lit = src;
  constexpr int kHashBits = 14;
  int32_t table[1 << kHashBits];
  auto emit_len_ext = [&d](uint64_t r) {
    while (r >= 255) {
      *d++ = 255;
      r -= 255;
    }
    *d++ = (uint8_t)r;
  };
  if (n > 16) {
    std::memset(table, -1, sizeof(table));
    // spec: last match starts >= 12 bytes before end; last 5 bytes literal
    const uint8_t* mflimit = iend - 12;
    const uint8_t* match_end_limit = iend - 5;
    while (ip < mflimit) {
      const uint32_t h = (load32_le(ip) * 2654435761u) >> (32 - kHashBits);
      const int32_t cpos = table[h];
      const int64_t pos = ip - src;
      table[h] = (int32_t)pos;
      if (cpos >= 0 && pos - cpos <= 65535 &&
          load32_le(src + cpos) == load32_le(ip)) {
        const uint8_t* cand = src + cpos;
        const uint8_t* q = ip + 4;
        const uint8_t* mp = cand + 4;
        while (q < match_end_limit && *q == *mp) {
          q++;
          mp++;
        }
        const uint64_t ll = (uint64_t)(ip - lit);
        const uint64_t ml = (uint64_t)(q - ip) - 4;
        *d++ = (uint8_t)(((ll < 15 ? ll : 15) << 4) | (ml < 15 ? ml : 15));
        if (ll >= 15) emit_len_ext(ll - 15);
        std::memcpy(d, lit, ll);
        d += ll;
        const uint64_t off = (uint64_t)(ip - cand);
        *d++ = (uint8_t)off;
        *d++ = (uint8_t)(off >> 8);
        if (ml >= 15) emit_len_ext(ml - 15);
        ip = q;
        lit = ip;
      } else {
        ip++;
      }
    }
  }
  const uint64_t ll = (uint64_t)(iend - lit);  // final literals-only sequence
  *d++ = (uint8_t)((ll < 15 ? ll : 15) << 4);
  if (ll >= 15) emit_len_ext(ll - 15);
  std::memcpy(d, lit, ll);
  d += ll;
  return (int64_t)(d - dst);
}

// CRC32C-hash each value in a blob into [0, num_buckets). The categorical
// string -> embedding-row path: strings never reach Python objects or the
// TPU; one call hashes a whole column.
void tfr_hash_blob(const uint8_t* blob, const int64_t* offsets, int64_t n,
                   int64_t num_buckets, int64_t* out) {
  init_crc32c_table();
  for (int64_t i = 0; i < n; i++) {
    uint32_t c = crc32c_impl(blob + offsets[i], (uint64_t)(offsets[i + 1] - offsets[i]), 0);
    out[i] = (int64_t)(c % (uint64_t)num_buckets);
  }
}

// Mixed-layout transfer packing (tpu/bitpack.py's hot path): copy the first
// ``keep`` int32 lanes of each row verbatim, then bit-pack the remaining
// ``n_cols - keep`` values into ``bits``-wide lanes, little-endian within
// and across lanes (the exact layout pack_bits/unpack_bits define). ``out``
// is [n_rows, keep + ceil((n_cols-keep)*bits/32)] int32, fully written
// (trailing pad bits zeroed). Values are masked to ``bits``. Returns -1 on
// success, or the flat index (row * n_cols + col) of the first NEGATIVE
// packed value — sign validation rides the packing pass (a predictable
// branch) instead of costing the wrapper a second full read.
int64_t tfr_pack_mixed(const int32_t* in, int64_t n_rows, int32_t n_cols,
                       int32_t keep, int32_t bits, int32_t* out) {
  const int32_t c = n_cols - keep;
  const int32_t w = (int32_t)(((int64_t)c * bits + 31) / 32);
  const uint64_t vmask = bits >= 32 ? 0xFFFFFFFFull : ((1ull << bits) - 1);
  for (int64_t r = 0; r < n_rows; r++) {
    const int32_t* src = in + r * n_cols;
    int32_t* dst = out + r * (keep + w);
    std::memcpy(dst, src, (size_t)keep * 4);
    uint64_t acc = 0;
    int accbits = 0;
    int32_t* o = dst + keep;
    for (int32_t j = 0; j < c; j++) {
      const int32_t v = src[keep + j];
      if (v < 0) return r * n_cols + keep + j;
      acc |= ((uint64_t)(uint32_t)v & vmask) << accbits;
      accbits += bits;
      if (accbits >= 32) {
        *o++ = (int32_t)(uint32_t)acc;
        acc >>= 32;
        accbits -= 32;
      }
    }
    if (accbits) *o++ = (int32_t)(uint32_t)acc;
    while (o < dst + keep + w) *o++ = 0;
  }
  return -1;
}

// ---------------------------------------------------------------------------
// Fused ragged -> dense padding (+ dtype cast)
// ---------------------------------------------------------------------------
// The host tail of SequenceExample ingest (ref TFRecordDeserializer.scala:
// 37-61's 2-D FeatureLists): the decoder produces ragged value buffers, the
// device wants dense [B, Lo, Li] in the compute dtype. Doing pad + cast in
// numpy costs ~75 ms/batch at the bench shape (per-row Python loop +
// ml_dtypes cast); fused here it is a memset + per-list memcpy/convert.
// in_kind: 0 = f32, 1 = i64. out_kind: 0 = f32, 1 = bf16 (from f32,
// round-to-nearest-even), 2 = i64, 3 = i32 (from i64, two's-complement
// truncation — Scala Long.toInt semantics like the scalar path).

static inline uint16_t f32_to_bf16_rne(uint32_t u) {
  if ((u & 0x7fffffffu) > 0x7f800000u)  // NaN: keep quiet, keep payload bit
    return (uint16_t)((u >> 16) | 0x0040u);
  u += 0x7fffu + ((u >> 16) & 1u);
  return (uint16_t)(u >> 16);
}

// Copy one run of li elements from src[v0..] to dst, converting per the
// (in_kind, out_kind) pair. Returns false for an unsupported combo.
static inline bool pad_copy_run(const void* values, int64_t v0, int64_t li,
                                int32_t in_kind, int32_t out_kind,
                                void* dst) {
  if (in_kind == 0 && out_kind == 0) {
    std::memcpy(dst, (const float*)values + v0, (size_t)li * 4);
  } else if (in_kind == 0 && out_kind == 1) {
    const uint32_t* src = (const uint32_t*)values + v0;
    uint16_t* d = (uint16_t*)dst;
    for (int64_t k = 0; k < li; k++) d[k] = f32_to_bf16_rne(src[k]);
  } else if (in_kind == 1 && out_kind == 2) {
    std::memcpy(dst, (const int64_t*)values + v0, (size_t)li * 8);
  } else if (in_kind == 1 && out_kind == 3) {
    const int64_t* src = (const int64_t*)values + v0;
    int32_t* d = (int32_t*)dst;
    for (int64_t k = 0; k < li; k++) d[k] = (int32_t)src[k];
  } else {
    return false;
  }
  return true;
}

static inline size_t pad_out_esize(int32_t out_kind) {
  return out_kind == 1 ? 2 : out_kind == 2 ? 8 : 4;
}

// One-level ragged [total] + offsets [n_rows+1] -> dense [n_rows, max_len]
// (pad 0) + clipped lengths [n_rows]. Returns 0, or -1 on bad kind combo.
int64_t tfr_pad_ragged(const void* values, int32_t in_kind,
                       const int64_t* offsets, int64_t n_rows,
                       int64_t max_len, int32_t out_kind, void* dense,
                       int32_t* lengths) {
  const size_t esz = pad_out_esize(out_kind);
  std::memset(dense, 0, (size_t)(n_rows * max_len) * esz);
  for (int64_t i = 0; i < n_rows; i++) {
    const int64_t v0 = offsets[i];
    int64_t li = offsets[i + 1] - v0;
    if (li > max_len) li = max_len;
    lengths[i] = (int32_t)li;
    if (li && !pad_copy_run(values, v0, li, in_kind, out_kind,
                            (uint8_t*)dense + (size_t)(i * max_len) * esz))
      return -1;
  }
  return 0;
}

// Two-level ragged -> dense [n_rows, max_outer, max_inner] (pad 0) +
// outer lengths [n_rows] + inner lengths [n_rows, max_outer] (zero beyond
// each row's outer length). Rows/lists beyond the max are truncated, the
// same contract as columnar.pad_ragged2. Returns 0, or -1 on bad kinds.
int64_t tfr_pad_ragged2(const void* values, int32_t in_kind,
                        const int64_t* inner_offsets,
                        const int64_t* row_splits, int64_t n_rows,
                        int64_t max_outer, int64_t max_inner,
                        int32_t out_kind, void* dense, int32_t* outer_len,
                        int32_t* inner_len) {
  const size_t esz = pad_out_esize(out_kind);
  const int64_t cell = max_outer * max_inner;
  std::memset(dense, 0, (size_t)(n_rows * cell) * esz);
  std::memset(inner_len, 0, (size_t)(n_rows * max_outer) * 4);
  for (int64_t i = 0; i < n_rows; i++) {
    const int64_t lo_full = row_splits[i + 1] - row_splits[i];
    const int64_t lo = lo_full < max_outer ? lo_full : max_outer;
    outer_len[i] = (int32_t)lo;
    for (int64_t jo = 0; jo < lo; jo++) {
      const int64_t j = row_splits[i] + jo;
      const int64_t v0 = inner_offsets[j];
      int64_t li = inner_offsets[j + 1] - v0;
      if (li > max_inner) li = max_inner;
      inner_len[i * max_outer + jo] = (int32_t)li;
      if (li && !pad_copy_run(values, v0, li, in_kind, out_kind,
                              (uint8_t*)dense +
                                  (size_t)(i * cell + jo * max_inner) * esz))
        return -1;
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native schema-inference seqOp
// ---------------------------------------------------------------------------
// The reference runs inference as an executor-parallel RDD aggregate
// (TensorFlowInferSchema.scala:40-43). The Python oracle (infer.py) is a
// per-record parse + precedence-lattice fold — pure Python, GIL-bound, so a
// thread pool cannot scale it within a host. This seqOp walks the proto
// wire directly (no value materialization) and aggregates, per feature
// name, the MAX precedence contribution — the lattice is a precedence max
// with null as identity (infer.py:77-115), so the fold is associative and
// a per-shard (name -> max prec) map is a complete partial result. GIL is
// released for the whole batch call; shards scan concurrently for real.
//
// Precedence encoding mirrors infer.py exactly: 0 null, 1 Long, 2 Float,
// 3 String, 4-6 Array(base), 7-9 Array(Array(base)). -1 marks a kind-unset
// feature (infer.py raises SchemaInferenceError) — the error is DEFERRED to
// fold time so a last-wins duplicate key can mask it, matching the oracle,
// which parses each record's maps fully (dict overwrite) before inferring.

namespace {

constexpr int8_t kInferErrorPrec = -1;

struct InferCol {
  std::string name;
  int8_t max_prec = 0;
  int8_t pending = 0;
  int64_t epoch = -1;
  bool has_pending = false;
};

struct InferState {
  // deque: no element moves on growth (FieldMap owns its key strings, so
  // this is about avoiding vector reallocation copies, not key lifetime)
  std::deque<InferCol> cols;
  FieldMap index;
  // Columns contributed-to since the last finalize: the per-record fold
  // touches only these, keeping the seqOp O(features per record), not
  // O(distinct features) per record (wide-sparse data would otherwise
  // erode the native speedup). May hold duplicates; fold is idempotent.
  std::vector<int32_t> touched;
  int64_t records = 0;
  std::string err;

  int lookup_or_add(std::string_view name) {
    auto it = field_find(index, name);
    if (it != index.end()) return it->second;
    cols.emplace_back();
    cols.back().name.assign(name.data(), name.size());
    int idx = (int)cols.size() - 1;
    index.emplace(cols.back().name, idx);
    return idx;
  }

  bool fold(InferCol& c) {
    if (!c.has_pending) return true;
    c.has_pending = false;
    if (c.pending == kInferErrorPrec) {
      err = "unsupported feature kind (oneof unset)";
      return false;
    }
    if (c.pending > c.max_prec) c.max_prec = c.pending;
    return true;
  }

  // Record one (name -> contribution) observation. epoch_tag identifies
  // (record, which map): a repeat within the same tag is a duplicate map
  // key -> last-wins overwrite; a new tag folds the previous pending.
  bool contribute(std::string_view name, int8_t prec, int64_t epoch_tag) {
    int idx = lookup_or_add(name);
    InferCol& c = cols[idx];
    if (c.epoch != epoch_tag) {
      if (!fold(c)) return false;
      c.epoch = epoch_tag;
      touched.push_back(idx);
    }
    c.pending = prec;
    c.has_pending = true;
    return true;
  }

  bool finalize_pending() {
    for (int32_t idx : touched)
      if (!fold(cols[idx])) return false;
    touched.clear();
    return true;
  }
};

// Walk one Feature submessage -> contribution prec (0 empty, 1..6, or
// kInferErrorPrec for kind-unset). Mirrors proto.py _parse_feature's merge
// semantics: a repeated occurrence of the SAME list kind concatenates
// (counts add), a different kind REPLACES (count resets); fields 1..3 with
// a non-LEN wire type are ignored. Counts never materialize values:
// int64 packed counts varint terminators, floats count plen/4.
bool infer_feature_walk(const uint8_t* p, const uint8_t* end, int8_t* out,
                        std::string& err) {
  int kind = 0;
  uint64_t count = 0;
  Cursor c{p, end};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated feature tag"; return false; }
    uint32_t fnum = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (wt != 2 || fnum < 1 || fnum > 3) {
      if (!skip_field(c, wt)) { err = "bad feature field"; return false; }
      continue;
    }
    uint64_t len;
    if (!read_varint(c, &len) || (uint64_t)(c.end - c.p) < len) {
      err = "truncated list"; return false;
    }
    Cursor lc{c.p, c.p + len};
    c.p += len;
    if ((int)fnum != kind) { kind = (int)fnum; count = 0; }
    while (lc.p < lc.end) {
      uint64_t ltag;
      if (!read_varint(lc, &ltag)) { err = "truncated list tag"; return false; }
      uint32_t lnum = (uint32_t)(ltag >> 3), lwt = (uint32_t)(ltag & 7);
      if (lnum != 1) {
        if (!skip_field(lc, lwt)) { err = "bad list field"; return false; }
        continue;
      }
      if (fnum == 1) {  // BytesList
        if (lwt == 2) {
          uint64_t bl;
          if (!read_varint(lc, &bl) || (uint64_t)(lc.end - lc.p) < bl) {
            err = "truncated bytes"; return false;
          }
          lc.p += bl;
          count++;
        } else if (!skip_field(lc, lwt)) { err = "bad bytes enc"; return false; }
      } else if (fnum == 2) {  // FloatList
        if (lwt == 2) {
          uint64_t pl;
          if (!read_varint(lc, &pl) || (uint64_t)(lc.end - lc.p) < pl) {
            err = "truncated packed"; return false;
          }
          if (pl % 4) { err = "packed float payload not 4-aligned"; return false; }
          lc.p += pl;
          count += pl / 4;
        } else if (lwt == 5) {
          if (lc.end - lc.p < 4) { err = "truncated float"; return false; }
          lc.p += 4;
          count++;
        } else if (!skip_field(lc, lwt)) { err = "bad float enc"; return false; }
      } else {  // Int64List
        if (lwt == 2) {
          uint64_t pl;
          if (!read_varint(lc, &pl) || (uint64_t)(lc.end - lc.p) < pl) {
            err = "truncated packed"; return false;
          }
          // count terminators, mirroring the oracle's validation exactly:
          // 10 continuation bytes -> "varint too long" (proto.py shift>63),
          // payload ending mid-varint -> truncated (proto.py boundary check)
          uint32_t run = 0;
          for (const uint8_t* q = lc.p; q < lc.p + pl; q++) {
            if (*q & 0x80) {
              if (++run == 10) { err = "varint too long"; return false; }
            } else {
              run = 0;
              count++;
            }
          }
          if (run) {
            err = "truncated varint in packed int64 list";
            return false;
          }
          lc.p += pl;
        } else if (lwt == 0) {
          uint64_t v;
          if (!read_varint(lc, &v)) { err = "truncated varint"; return false; }
          count++;
        } else if (!skip_field(lc, lwt)) { err = "bad int enc"; return false; }
      }
    }
  }
  if (kind == 0) { *out = kInferErrorPrec; return true; }
  const int8_t base = kind == 1 ? 3 : kind == 2 ? 2 : 1;  // String/Float/Long
  *out = count == 0 ? (int8_t)0 : count == 1 ? base : (int8_t)(base + 3);
  return true;
}

// One Features map region (Example.features / SequenceExample.context).
// Entry semantics mirror proto.py _parse_features_map: nameless entries are
// skipped; the LAST value field within an entry wins; an entry with no
// value field is an empty Feature (kind unset -> deferred error).
bool infer_features_map(InferState& st, const uint8_t* p, const uint8_t* end,
                        int64_t epoch_tag, std::string& err) {
  Cursor c{p, end};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated features tag"; return false; }
    if ((tag >> 3) != 1 || (tag & 7) != 2) {
      if (!skip_field(c, (uint32_t)(tag & 7))) { err = "bad features field"; return false; }
      continue;
    }
    uint64_t elen;
    if (!read_varint(c, &elen) || (uint64_t)(c.end - c.p) < elen) {
      err = "truncated map entry"; return false;
    }
    Cursor ec{c.p, c.p + elen};
    c.p += elen;
    std::string_view name;
    bool has_name = false;
    const uint8_t* fs = nullptr;
    const uint8_t* fe = nullptr;
    bool has_feat = false;
    while (ec.p < ec.end) {
      uint64_t etag;
      if (!read_varint(ec, &etag)) { err = "truncated entry tag"; return false; }
      uint32_t enum_ = (uint32_t)(etag >> 3), ewt = (uint32_t)(etag & 7);
      if (enum_ == 1 && ewt == 2) {
        uint64_t klen;
        if (!read_varint(ec, &klen) || (uint64_t)(ec.end - ec.p) < klen) {
          err = "truncated key"; return false;
        }
        name = std::string_view((const char*)ec.p, klen);
        has_name = true;
        ec.p += klen;
      } else if (enum_ == 2 && ewt == 2) {
        uint64_t flen;
        if (!read_varint(ec, &flen) || (uint64_t)(ec.end - ec.p) < flen) {
          err = "truncated value"; return false;
        }
        fs = ec.p;
        fe = ec.p + flen;
        has_feat = true;
        ec.p += flen;
      } else if (!skip_field(ec, ewt)) { err = "bad entry field"; return false; }
    }
    if (!has_name) continue;
    int8_t prec = kInferErrorPrec;
    if (has_feat && !infer_feature_walk(fs, fe, &prec, err)) return false;
    if (!st.contribute(name, prec, epoch_tag)) return false;
  }
  return true;
}

// One FeatureLists map region: per entry, fold the inner features' precs
// (max), then wrap to the 2-level array band: base m in 1..3 -> m+6,
// array m in 4..6 -> m+3 (matching infer_sequence_example_row_type's
// ArrayType wrapping, infer.py:131-151); an unset-kind inner feature makes
// the whole entry's contribution the deferred error.
bool infer_feature_lists(InferState& st, const uint8_t* p, const uint8_t* end,
                         int64_t epoch_tag, std::string& err) {
  Cursor c{p, end};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated featurelists tag"; return false; }
    if ((tag >> 3) != 1 || (tag & 7) != 2) {
      if (!skip_field(c, (uint32_t)(tag & 7))) { err = "bad featurelists field"; return false; }
      continue;
    }
    uint64_t elen;
    if (!read_varint(c, &elen) || (uint64_t)(c.end - c.p) < elen) {
      err = "truncated fl entry"; return false;
    }
    Cursor ec{c.p, c.p + elen};
    c.p += elen;
    std::string_view name;
    bool has_name = false;
    const uint8_t* ls = nullptr;
    const uint8_t* le = nullptr;
    while (ec.p < ec.end) {
      uint64_t etag;
      if (!read_varint(ec, &etag)) { err = "truncated fl entry tag"; return false; }
      uint32_t enum_ = (uint32_t)(etag >> 3), ewt = (uint32_t)(etag & 7);
      if (enum_ == 1 && ewt == 2) {
        uint64_t klen;
        if (!read_varint(ec, &klen) || (uint64_t)(ec.end - ec.p) < klen) {
          err = "truncated fl key"; return false;
        }
        name = std::string_view((const char*)ec.p, klen);
        has_name = true;
        ec.p += klen;
      } else if (enum_ == 2 && ewt == 2) {
        uint64_t flen;
        if (!read_varint(ec, &flen) || (uint64_t)(ec.end - ec.p) < flen) {
          err = "truncated featurelist"; return false;
        }
        ls = ec.p;  // last value field wins (proto.py reassigns flist)
        le = ec.p + flen;
        ec.p += flen;
      } else if (!skip_field(ec, ewt)) { err = "bad fl entry field"; return false; }
    }
    if (!has_name) continue;
    int8_t m = 0;
    bool entry_err = false;
    Cursor lc{ls ? ls : end, le ? le : end};
    while (lc.p < lc.end) {
      uint64_t ltag;
      if (!read_varint(lc, &ltag)) { err = "truncated fl tag"; return false; }
      if ((ltag >> 3) != 1 || (ltag & 7) != 2) {
        if (!skip_field(lc, (uint32_t)(ltag & 7))) { err = "bad fl field"; return false; }
        continue;
      }
      uint64_t flen;
      if (!read_varint(lc, &flen) || (uint64_t)(lc.end - lc.p) < flen) {
        err = "truncated inner feature"; return false;
      }
      int8_t prec;
      if (!infer_feature_walk(lc.p, lc.p + flen, &prec, err)) return false;
      lc.p += flen;
      if (prec == kInferErrorPrec) entry_err = true;
      else if (prec > m) m = prec;
    }
    int8_t contribution;
    if (entry_err) contribution = kInferErrorPrec;
    else if (m == 0) contribution = 0;
    else if (m <= 3) contribution = (int8_t)(m + 6);
    else contribution = (int8_t)(m + 3);
    if (!st.contribute(name, contribution, epoch_tag)) return false;
  }
  return true;
}

// One record: Example { features = 1 } or SequenceExample { context = 1,
// feature_lists = 2 }. Distinct epoch tags for the two maps: duplicate keys
// WITHIN a map are last-wins, the same name ACROSS maps folds.
bool infer_one_record(InferState& st, const uint8_t* rp, uint64_t rlen,
                      int32_t record_format, std::string& err) {
  const int64_t r = st.records;
  Cursor c{rp, rp + rlen};
  while (c.p < c.end) {
    uint64_t tag;
    if (!read_varint(c, &tag)) { err = "truncated record tag"; return false; }
    uint32_t fnum = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    if (wt == 2 && ((record_format == 0 && fnum == 1) ||
                    (record_format == 1 && (fnum == 1 || fnum == 2)))) {
      uint64_t mlen;
      if (!read_varint(c, &mlen) || (uint64_t)(c.end - c.p) < mlen) {
        err = "truncated message"; return false;
      }
      const uint8_t* ms = c.p;
      const uint8_t* me = c.p + mlen;
      c.p += mlen;
      bool ok = (record_format == 1 && fnum == 2)
                    ? infer_feature_lists(st, ms, me, r * 2 + 1, err)
                    : infer_features_map(st, ms, me, r * 2, err);
      if (!ok) return false;
    } else if (!skip_field(c, wt)) {
      err = "bad record field";
      return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Accumulating inference over a batch of record spans. ``prev`` continues a
// prior accumulation (slab streaming); pass nullptr to start one. Returns
// the handle, or nullptr with errbuf filled (an existing ``prev`` is left
// owned by the caller — free it with tfr_infer_free).
void* tfr_infer_batch(const uint8_t* buf, const uint64_t* offsets,
                      const uint64_t* lengths, int64_t n,
                      int32_t record_format, void* prev, char* errbuf,
                      int64_t errbuf_len) {
  InferState* st = prev ? static_cast<InferState*>(prev) : new InferState();
  for (int64_t i = 0; i < n; i++) {
    // Fold at each record boundary (duplicate masking is within-record, so
    // this is safe): a deferred kind-unset error surfaces at the SAME
    // record index where the Python oracle raises, and entries stay
    // readable after every batch.
    if (!infer_one_record(*st, buf + offsets[i], lengths[i], record_format,
                          st->err) ||
        !st->finalize_pending()) {
      std::snprintf(errbuf, errbuf_len, "record %lld: %s",
                    (long long)st->records, st->err.c_str());
      if (!prev) delete st;
      return nullptr;
    }
    st->records++;
  }
  return st;
}

int64_t tfr_infer_size(void* h) {
  return (int64_t) static_cast<InferState*>(h)->cols.size();
}

// Entry i: writes the name pointer/length, returns its max precedence.
int64_t tfr_infer_entry(void* h, int64_t i, const char** name,
                        int64_t* name_len) {
  InferCol& c = static_cast<InferState*>(h)->cols[(size_t)i];
  *name = c.name.data();
  *name_len = (int64_t)c.name.size();
  return c.max_prec;
}

void tfr_infer_free(void* h) { delete static_cast<InferState*>(h); }

}  // extern "C"
