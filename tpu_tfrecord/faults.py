"""Deterministic chaos filesystem: seeded fault plans + an injecting wrapper.

PR 2 proved the pipeline against faults that RAISE; the stall-defense layer
(tpu_tfrecord.stall) defends against faults that merely hang. Both need a
way to *reproduce* a fault on demand — this module is that reusable
subsystem: a ``FaultPlan`` (JSON-serializable scenario: which ops, which
paths, which call ordinals, what kind of fault) and a ``ChaosFS`` wrapper
over any ``LocalFS``/``FsspecFS`` that executes the plan. Every injected
fault is appended to a replayable ledger, so a test (or
``tools/tfrecord_doctor.py --simulate``) can assert exactly what fired and
a bug report can ship the plan that reproduces a field failure.

Determinism contract: fault decisions depend only on (rule, per-(op, path)
call ordinal) — never on wall clock or thread scheduling. Probabilistic
rules draw from a RNG seeded by (plan.seed, rule index, ordinal), so even
concurrent readers make the same draw for the same call. Same plan + same
access pattern => byte-identical ledger.

Fault kinds:

- ``transient_error``: raise OSError for ``times`` matching calls, then heal
  (the retry-path workout).
- ``permanent_error``: raise OSError on every matching call.
- ``short_read``: cap each matching read at ``cap_bytes`` (object-store
  style partial reads; exercises every reader's refill loop).
- ``stall``: block the matching call for ``stall_ms`` — the hung-read /
  straggler-shard scenario. The wait goes through the plan's injectable
  ``sleep`` seam (default: an interruptible Event wait, released by
  ``plan.release()``), so tests bound wall time or eliminate it entirely.
- ``rename_race``: let the rename LAND, then raise (the object-store
  "copy succeeded, error surfaced anyway" race PR 2's landed-rename
  detection exists for).
- ``flaky_listing``: raise OSError from listdir/glob/walk_files (a dropped
  LIST page; discovery must retry or fail loudly, never shrink).

``install_chaos(plan)`` patches the three raw-open seams the real read and
write paths go through (``fs.filesystem_for``, ``fs.local_open``,
``io.dataset._open_local``) so chaos reaches every read mode — strict,
salvage, mmap, fused — and the writer, with zero overhead when not
installed.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import signal
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

FAULT_KINDS = (
    "transient_error",
    "permanent_error",
    "short_read",
    "stall",
    "rename_race",
    "flaky_listing",
    "disconnect",
    # Dispatcher-targeted kinds (ISSUE 17 HA chaos):
    "torn_write",  # journal record torn at cap_bytes, then the write errors
    # — the host-crash-mid-append scenario journal replay must survive
    "sigkill",  # SIGKILL the CURRENT process at the matching call — the
    # primary-dies-mid-journal-write scenario (subprocess scenarios only)
    "netsplit",  # connect/recv permanently refused: the standby-partition
    # scenario, ledgered distinctly from an ordinary permanent_error
    # HTTP-request kinds (op="http"), executed by the fault-injecting
    # Range server (tpu_tfrecord.httpfs.serve_directory) — faults that
    # fire at the REAL socket level, not inside a wrapped file object:
    "reset",  # RST the connection mid-body (SO_LINGER 0 + close)
    "truncated_body",  # full Content-Length declared, fewer bytes sent
    "http_error",  # `status` (503/429/...) response, Retry-After honored
    "bad_content_range",  # serve range start+shift_bytes, honestly labeled
    "trickle",  # body dribbled cap_bytes per stall_ms — slow-trickle stall
    # Serving-tier kinds (op="serve"), executed by tpu_tfrecord.serving
    # at its reply/recv/load seams (ISSUE 18 chaos certification):
    "slow_client",  # stall the server's reply to ONE client for stall_ms
    # — must block only that client's writer, never the engine tick
    "client_disconnect",  # drop the client's connection mid-generation —
    # the request's slot must free without perturbing neighbors' bytes
    "burst",  # the open-loop load generator injects burst_n extra
    # requests at the matching call — the overload-shedding scenario
)

#: ops a rule may target. ``read`` covers read()/readinto() on handles the
#: wrapped FS opened; ``open`` covers the open call itself; ``rename`` and
#: ``listdir`` cover the write/commit and discovery sides. ``connect`` and
#: ``recv`` are the SOCKET seams of the data service
#: (tpu_tfrecord.service_protocol): the path a rule matches is the peer
#: address string ("host:port"); ``transient_error``/``permanent_error``
#: on connect model refused connections, ``stall`` models a hung peer
#: (bounded, same injectable sleep), ``short_read`` caps one recv (the
#: partial-segment scenario every recv loop must refill past), and
#: ``disconnect`` closes the socket mid-frame — the short-frame scenario
#: the protocol must convert into a loud ProtocolError, never into
#: truncated data. ``http`` is the request seam of the real-network
#: remote tier (tpu_tfrecord.httpfs): the path a rule matches is
#: ``<url path>@<range start>`` — keyed per byte offset so retries of the
#: same block get deterministic ordinals even with concurrent fetches —
#: and the HTTP-specific kinds above fire on the server's side of a real
#: TCP connection. ``connect`` rules also apply to the HTTP client's
#: connection establishment (peer "host:port"): a transient/permanent
#: error there IS connection-refused as the client observes it.
#: ``journal`` is the dispatcher-journal write seam (tpu_tfrecord.service
#: consults the installed plan around every journal append/compaction;
#: the matched path is the journal file path): ``torn_write`` lands a
#: cap_bytes prefix of the record on disk and then errors (the
#: crash-mid-append tear standby replay must absorb), ``sigkill`` kills
#: the dispatcher process at the write, and transient/permanent errors
#: exercise the journal-failure self-demotion path.
#: ``serve`` is the serving tier's seam (tpu_tfrecord.serving): the path
#: a rule matches is the seam point — ``reply:<peer>`` (the server's
#: per-client writer, where ``slow_client`` stalls and
#: ``client_disconnect`` drops the connection), ``recv:<peer>`` (the
#: server's per-client reader, same kinds), and ``load`` (the open-loop
#: generator's admission call, where ``burst`` injects burst_n extra
#: requests). All on the same replayable ledger as the file/socket seams.
FAULT_OPS = ("open", "read", "rename", "listdir", "connect", "recv", "http",
             "journal", "serve")

#: kinds only the fault-injecting HTTP server executes (op="http").
HTTP_ONLY_KINDS = (
    "reset", "truncated_body", "http_error", "bad_content_range", "trickle",
)

#: every kind an ``op="http"`` rule may carry — the HTTP-only kinds plus
#: the generic ones the Range server's dispatch actually executes. A kind
#: outside this set on op="http" (short_read, disconnect, ...) would be
#: LEDGERED as fired while the server serves the object clean — the
#: silent-no-op this vocabulary check exists to refuse.
HTTP_ALLOWED_KINDS = HTTP_ONLY_KINDS + (
    "stall", "transient_error", "permanent_error",
)

#: kinds only the serving tier executes (op="serve").
SERVE_ONLY_KINDS = ("slow_client", "client_disconnect", "burst")

#: every kind an ``op="serve"`` rule may carry — serve-only kinds plus the
#: generic ones the serving seams actually execute. Anything else would be
#: ledgered as fired while the server behaves clean (the silent no-op the
#: http vocabulary check already refuses).
SERVE_ALLOWED_KINDS = SERVE_ONLY_KINDS + (
    "stall", "transient_error", "permanent_error",
)


#: set by install_chaos; the serving tier (tpu_tfrecord.serving) consults
#: it at its reply/recv/load seams. Lives HERE rather than in serving.py
#: so installing chaos never has to import the jax-heavy serving module
#: (and works regardless of import order); an explicit ``fault_plan``
#: passed to a server/load-generator wins over this global.
_SERVE_CHAOS: Optional["FaultPlan"] = None


class InjectedFault(OSError):
    """Every raising fault ChaosFS injects is this OSError subclass, so the
    existing transient-retry nets treat it exactly like a real IO error
    while tests can still tell injected from organic."""


@dataclass
class FaultRule:
    """One line of a scenario: WHAT fires (kind + params), WHERE (op +
    path substring), and WHEN (from call ``ordinal`` on, at most ``times``
    firings; ``probability`` < 1.0 makes eligible calls fire by a seeded,
    ordinal-keyed coin flip)."""

    op: str
    kind: str
    path: str = ""  # substring match against the full path ("" = any)
    ordinal: int = 0  # first per-(op, path-key) call index eligible to fire
    times: Optional[int] = 1  # max firings (None = every eligible call)
    stall_ms: float = 0.0
    cap_bytes: int = 0
    probability: float = 1.0
    error: str = ""
    status: int = 503  # http_error response code (429/503/...)
    retry_after_s: float = 0.0  # Retry-After header on http_error responses
    shift_bytes: int = 64  # bad_content_range: how far the server lies
    burst_n: int = 0  # burst: extra requests the load generator injects

    def __post_init__(self) -> None:
        if self.op not in FAULT_OPS:
            raise ValueError(f"op must be one of {FAULT_OPS}, got {self.op!r}")
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"kind must be one of {FAULT_KINDS}, got {self.kind!r}"
            )
        if self.ordinal < 0:
            raise ValueError("ordinal must be >= 0")
        if self.times is not None and self.times < 1:
            raise ValueError("times must be >= 1 (or None)")
        if not (0.0 < self.probability <= 1.0):
            raise ValueError("probability must be in (0, 1]")
        if self.kind == "short_read" and self.cap_bytes < 1:
            # cap 0 would make read() return b"" — indistinguishable from
            # EOF, i.e. silent truncation instead of a short read
            raise ValueError("short_read requires cap_bytes >= 1")
        if self.kind in ("stall", "trickle") and self.stall_ms <= 0:
            raise ValueError(f"{self.kind} requires stall_ms > 0")
        if self.kind in HTTP_ONLY_KINDS and self.op != "http":
            # these describe server-side wire behavior; a rule asking a
            # file wrapper to RST a connection would silently no-op
            raise ValueError(f"kind {self.kind!r} requires op='http'")
        if self.op == "http" and self.kind not in HTTP_ALLOWED_KINDS:
            raise ValueError(
                f"op='http' supports kinds {HTTP_ALLOWED_KINDS}, got "
                f"{self.kind!r} — the Range server would ledger it as "
                "fired while serving the object clean"
            )
        if self.kind == "http_error" and not 400 <= self.status <= 599:
            raise ValueError("http_error requires a 4xx/5xx status")
        if self.kind == "bad_content_range" and self.shift_bytes == 0:
            raise ValueError("bad_content_range requires shift_bytes != 0")
        if self.kind == "torn_write":
            if self.op != "journal":
                # tearing a record mid-write is a journal-append shape;
                # on any other op it would ledger as fired and do nothing
                raise ValueError("torn_write requires op='journal'")
            if self.cap_bytes < 1:
                raise ValueError("torn_write requires cap_bytes >= 1 (how "
                                 "many record bytes land before the tear)")
        if self.kind == "netsplit" and self.op not in ("connect", "recv"):
            raise ValueError("netsplit requires op='connect' or op='recv'")
        if self.kind in SERVE_ONLY_KINDS and self.op != "serve":
            # these describe serving-tier behavior (a client's half of a
            # request/reply stream, an admission burst); on any other op
            # they would ledger as fired and do nothing
            raise ValueError(f"kind {self.kind!r} requires op='serve'")
        if self.op == "serve" and self.kind not in SERVE_ALLOWED_KINDS:
            raise ValueError(
                f"op='serve' supports kinds {SERVE_ALLOWED_KINDS}, got "
                f"{self.kind!r} — the serving seams would ledger it as "
                "fired while serving clean"
            )
        if self.kind == "slow_client" and self.stall_ms <= 0:
            raise ValueError("slow_client requires stall_ms > 0")
        if self.kind == "burst" and self.burst_n < 1:
            raise ValueError("burst requires burst_n >= 1")

    def matches_path(self, path: str) -> bool:
        return self.path in path


class FaultPlan:
    """A seeded, deterministic, JSON-round-trippable fault scenario plus the
    ledger of what actually fired.

    Thread-safe: per-(op, path) call counters and the ledger are mutated
    under one lock (the pipeline reads from worker threads). ``sleep`` and
    ``clock`` are injectable seams — the default sleep is an interruptible
    wait on the plan's release event, so a test can end every in-flight
    stall at teardown with ``plan.release()``.
    """

    def __init__(
        self,
        rules: List[FaultRule],
        seed: int = 0,
        sleep=None,
        clock=time.monotonic,
    ):
        self.rules = list(rules)
        self.seed = int(seed)
        self.clock = clock
        self._released = threading.Event()
        self.sleep = sleep if sleep is not None else self._default_sleep
        self._lock = threading.Lock()
        self._calls: Dict[tuple, int] = {}  # (op, path) -> calls so far
        self._fired: Dict[int, int] = {}  # rule index -> firings so far
        self.ledger: List[Dict[str, Any]] = []

    # -- construction / serialization ---------------------------------------

    @staticmethod
    def from_json(obj: "str | Dict[str, Any]") -> "FaultPlan":
        if isinstance(obj, str):
            obj = json.loads(obj)
        rules = [FaultRule(**r) for r in obj.get("rules", [])]
        return FaultPlan(rules, seed=int(obj.get("seed", 0)))

    def to_json(self) -> Dict[str, Any]:
        return {"seed": self.seed, "rules": [asdict(r) for r in self.rules]}

    # -- runtime ------------------------------------------------------------

    def _default_sleep(self, seconds: float) -> None:
        self._released.wait(seconds)

    def release(self) -> None:
        """End every in-flight (and future) default-sleep stall immediately
        — test teardown's escape hatch for abandoned reader threads."""
        self._released.set()

    def _coin(self, rule_idx: int, ordinal: int, p: float) -> bool:
        if p >= 1.0:
            return True
        # keyed by (seed, rule, ordinal): the same call makes the same draw
        # no matter how calls from different threads interleave (folded
        # into one int — tuple seeding is deprecated)
        key = (self.seed * 1_000_003 + rule_idx) * 1_000_003 + ordinal
        return random.Random(key).random() < p

    def decide(self, op: str, path: str) -> List[Dict[str, Any]]:
        """Record one (op, path) call and return the faults that fire on it
        (already appended to the ledger), in rule order."""
        fired: List[Dict[str, Any]] = []
        with self._lock:
            key = (op, path)
            n = self._calls.get(key, 0)
            self._calls[key] = n + 1
            for idx, rule in enumerate(self.rules):
                if rule.op != op or not rule.matches_path(path):
                    continue
                if n < rule.ordinal:
                    continue
                if rule.times is not None and self._fired.get(idx, 0) >= rule.times:
                    continue
                if not self._coin(idx, n, rule.probability):
                    continue
                self._fired[idx] = self._fired.get(idx, 0) + 1
                entry = {
                    "rule": idx,
                    "op": op,
                    "path": path,
                    "ordinal": n,
                    "kind": rule.kind,
                }
                if rule.kind in ("stall", "trickle", "slow_client"):
                    entry["stall_ms"] = rule.stall_ms
                if rule.kind == "burst":
                    entry["burst_n"] = rule.burst_n
                if rule.kind in ("short_read", "torn_write"):
                    entry["cap_bytes"] = rule.cap_bytes
                if rule.kind == "http_error":
                    entry["status"] = rule.status
                if rule.kind == "bad_content_range":
                    entry["shift_bytes"] = rule.shift_bytes
                self.ledger.append(entry)
                fired.append(dict(entry, _rule=rule))
        return fired

    def ledger_json(self) -> str:
        """Canonical one-line-per-event encoding — what the determinism
        tests byte-compare across runs."""
        with self._lock:
            return "\n".join(json.dumps(e, sort_keys=True) for e in self.ledger)

    # -- fault execution ----------------------------------------------------

    def _raise_for(self, fault: Dict[str, Any]) -> None:
        rule: FaultRule = fault["_rule"]
        msg = rule.error or (
            f"injected {rule.kind} ({fault['op']} #{fault['ordinal']} "
            f"on {fault['path']})"
        )
        raise InjectedFault(msg)

    def apply(self, op: str, path: str, size: Optional[int] = None) -> Optional[int]:
        """Run the plan for one call: stalls sleep, errors raise, short
        reads return the capped size (None = uncapped). Multiple rules may
        fire on one call (e.g. stall THEN transient error)."""
        cap: Optional[int] = None
        for fault in self.decide(op, path):
            kind = fault["kind"]
            if kind == "stall":
                self.sleep(fault["_rule"].stall_ms / 1000.0)
            elif kind == "short_read":
                c = fault["_rule"].cap_bytes
                if size is None or size < 0 or size > c:
                    cap = c if cap is None else min(cap, c)
            elif kind == "sigkill":
                self._sigkill()
            elif kind in ("transient_error", "permanent_error", "flaky_listing"):
                self._raise_for(fault)
            # rename_race is handled at the rename call site (the rename
            # must LAND before the error) — see ChaosFS.rename;
            # disconnect is socket-only — see apply_socket
        return cap

    @staticmethod
    def _sigkill() -> None:
        """The process-death fault: SIGKILL ourselves, exactly the way a
        chaos test kills a primary dispatcher — no handlers, no cleanup,
        fds closed by the kernel. Only meaningful in subprocess
        scenarios (an in-process test would kill the test runner)."""
        os.kill(os.getpid(), signal.SIGKILL)

    def apply_socket(
        self, op: str, addr: str, sock=None, size: Optional[int] = None
    ) -> Optional[int]:
        """Run the plan for one socket call (``connect``/``recv`` against
        the peer address): stalls sleep, errors raise, short reads return
        the capped recv size, and ``disconnect`` CLOSES the socket before
        raising — so the local side observes the same half-dead-peer state
        a real mid-frame death leaves behind."""
        cap: Optional[int] = None
        for fault in self.decide(op, addr):
            kind = fault["kind"]
            if kind == "stall":
                self.sleep(fault["_rule"].stall_ms / 1000.0)
            elif kind == "short_read":
                c = fault["_rule"].cap_bytes
                if size is None or size < 0 or size > c:
                    cap = c if cap is None else min(cap, c)
            elif kind == "disconnect":
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                self._raise_for(fault)
            elif kind == "sigkill":
                self._sigkill()
            else:
                # transient_error / permanent_error / netsplit: netsplit
                # raises identically to permanent_error but ledgers under
                # its own kind — a partitioned standby and a crashed peer
                # are different scenarios worth telling apart in a replay
                self._raise_for(fault)
        return cap

    def apply_serve(self, point: str, sock=None) -> int:
        """Run the plan for one serving-tier call (``op="serve"`` against
        the seam point — ``reply:<peer>``, ``recv:<peer>``, or ``load``):
        ``slow_client``/``stall`` sleep (the stuck-client scenario, as the
        server's per-client writer observes it), ``client_disconnect``
        CLOSES the peer socket and raises (the mid-generation hangup whose
        slot must free without perturbing neighbors), errors raise, and
        ``burst`` returns how many EXTRA requests the open-loop generator
        must inject at this call (summed across fired rules, 0 = none)."""
        burst = 0
        for fault in self.decide("serve", point):
            kind = fault["kind"]
            if kind in ("stall", "slow_client"):
                self.sleep(fault["_rule"].stall_ms / 1000.0)
            elif kind == "client_disconnect":
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                self._raise_for(fault)
            elif kind == "burst":
                burst += fault["_rule"].burst_n
            else:
                self._raise_for(fault)
        return burst

    def apply_journal(self, path: str, data: bytes) -> None:
        """Run the plan for one dispatcher-journal write (``op="journal"``
        against the journal path, ``data`` the full record about to land):
        stalls sleep, errors raise, ``sigkill`` kills the process, and
        ``torn_write`` writes the first ``cap_bytes`` of the record
        DIRECTLY to the journal and then raises — the bytes a host crash
        mid-append would have left behind, which replay must absorb as a
        torn tail."""
        for fault in self.decide("journal", path):
            kind = fault["kind"]
            if kind == "stall":
                self.sleep(fault["_rule"].stall_ms / 1000.0)
            elif kind == "sigkill":
                self._sigkill()
            elif kind == "torn_write":
                torn = data[: fault["_rule"].cap_bytes]
                with open(path, "ab") as fh:
                    fh.write(torn)
                    fh.flush()
                    os.fsync(fh.fileno())
                self._raise_for(fault)
            else:
                self._raise_for(fault)


class _ChaosFile:
    """Read-side fault executor for one open handle: every read()/readinto()
    routes through the plan (stalls, errors, short-read caps)."""

    def __init__(self, inner, plan: FaultPlan, path: str):
        self._inner = inner
        self._plan = plan
        self._path = path

    def read(self, size: int = -1):
        cap = self._plan.apply("read", self._path, size)
        if cap is not None and (size is None or size < 0 or size > cap):
            size = cap
        return self._inner.read(size)

    def readinto(self, b) -> int:
        # route through read() so every fault kind applies uniformly
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def write(self, data):
        return self._inner.write(data)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self):
        return self._inner.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __iter__(self):
        return iter(self._inner)


class ChaosFS:
    """Fault-injecting wrapper over any FS object (LocalFS, FsspecFS, test
    shims): ``open``/``read``/``rename``/``listdir``-family calls consult
    the plan; everything else passes through untouched."""

    def __init__(self, inner, plan: FaultPlan):
        # name kept so fs.independent_read_handles can walk the wrapper
        # chain to the wrapped backend's capability flag/protocol
        self._fs = inner
        self._plan = plan

    def open(self, path: str, mode: str):
        self._plan.apply("open", path)
        inner = self._fs.open(path, mode)
        if "r" in mode:
            return _ChaosFile(inner, self._plan, path)
        return inner

    def rename(self, src: str, dst: str) -> None:
        fired = self._plan.decide("rename", src)
        for f in fired:
            kind = f["kind"]
            if kind == "stall":
                self._plan.sleep(f["_rule"].stall_ms / 1000.0)
            elif kind in ("transient_error", "permanent_error", "flaky_listing"):
                self._plan._raise_for(f)  # fails BEFORE the rename lands
        self._fs.rename(src, dst)
        if any(f["kind"] == "rename_race" for f in fired):
            raise InjectedFault(
                f"injected rename_race: rename landed but errored ({src})"
            )

    def listdir(self, path: str):
        self._plan.apply("listdir", path)
        return self._fs.listdir(path)

    def glob(self, pattern: str):
        self._plan.apply("listdir", pattern)
        return self._fs.glob(pattern)

    def walk_files(self, root: str, keep):
        self._plan.apply("listdir", root)
        return self._fs.walk_files(root, keep)

    def __getattr__(self, name):
        return getattr(self._fs, name)


@contextlib.contextmanager
def install_chaos(plan: FaultPlan):
    """Route every filesystem access of the package through ``plan`` for
    the duration of the with-block: ``fs.filesystem_for`` results are
    ChaosFS-wrapped (scheme'd paths AND the LocalFS the writer uses),
    ``fs.local_open`` (the raw-open seam wire.open_compressed uses for
    plain paths) and ``io.dataset._open_local`` (the mmap fast path's
    seam) open through the plan, and the data service's socket seams
    (``service_protocol`` connect/recv) consult it for ``connect``/
    ``recv`` rules. Restores everything on exit and releases any
    in-flight default-sleep stalls."""
    from tpu_tfrecord import fs as _fs
    from tpu_tfrecord import httpfs as _httpfs
    from tpu_tfrecord import service as _service
    from tpu_tfrecord import service_protocol as _sp
    from tpu_tfrecord.io import dataset as _dataset

    orig_filesystem_for = _fs.filesystem_for
    orig_local_open = _fs.local_open
    orig_open_local = _dataset._open_local
    orig_chaos_plan = _sp._CHAOS_PLAN
    orig_http_plan = _httpfs._CHAOS_PLAN
    orig_journal_plan = _service._JOURNAL_CHAOS
    global _SERVE_CHAOS
    orig_serve_plan = _SERVE_CHAOS

    def chaos_filesystem_for(path: str):
        return ChaosFS(orig_filesystem_for(path), plan)

    def chaos_local_open(path: str, mode: str):
        if "r" in mode:
            plan.apply("open", path)
            return _ChaosFile(orig_local_open(path, mode), plan, path)
        return orig_local_open(path, mode)

    _fs.filesystem_for = chaos_filesystem_for
    _fs.local_open = chaos_local_open
    _dataset._open_local = chaos_local_open
    # the socket seams: service_protocol consults this plan at every
    # connect and recv, and the HTTP remote client (httpfs) at every
    # connection establishment — a ``connect`` transient/permanent rule
    # there is connection-refused exactly as the client observes it
    _sp._CHAOS_PLAN = plan
    _httpfs._CHAOS_PLAN = plan
    # the dispatcher-journal write seam: every journal append/compaction
    # consults the plan under op="journal" (torn_write / sigkill / errors)
    _service._JOURNAL_CHAOS = plan
    # the serving-tier seam (tpu_tfrecord.serving reads this module's
    # global at its reply/recv/load points — op="serve" rules)
    _SERVE_CHAOS = plan
    try:
        yield plan
    finally:
        _fs.filesystem_for = orig_filesystem_for
        _fs.local_open = orig_local_open
        _dataset._open_local = orig_open_local
        _sp._CHAOS_PLAN = orig_chaos_plan
        _httpfs._CHAOS_PLAN = orig_http_plan
        _service._JOURNAL_CHAOS = orig_journal_plan
        _SERVE_CHAOS = orig_serve_plan
        plan.release()
