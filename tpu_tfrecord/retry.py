"""One shared retry/backoff policy for every transient-fault site.

Before this module the repo had three copy-pasted hard-coded backoff loops
in io/dataset.py (``time.sleep(min(0.1 * 2**attempt, 2.0))``) and ZERO
retries on the write-side commit path — untestable without real sleeping,
and impossible to tune per deployment. ``RetryPolicy`` is the single owner
of the budget (attempts + optional wall-clock deadline), the capped
exponential backoff with full jitter (the AWS-recommended shape: uniform in
[0, cap] so synchronized failures don't retry in lockstep), and — crucially
for tests — injectable ``sleep``/``clock``/``rand`` seams so retry behavior
is provable in microseconds.

Two usage shapes:

- ``policy.call(fn, retry_on=(OSError,))`` for plain calls (write-side
  commit ops).
- the pause protocol for generator-resume loops (read-side shard decode,
  which must re-enter with its own skip accounting)::

      attempt, start = 0, policy.clock()
      while True:
          try:
              ...  # one attempt
              return
          except RETRYABLE:
              attempt += 1
              if not policy.pause(attempt, start):
                  raise
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry configuration + the clock/sleep seams.

    ``max_retries`` counts RETRIES, not attempts: 0 means one attempt and
    no retry (the historical ``read_retries=0`` default). ``deadline``
    bounds total elapsed time since the caller's ``start`` timestamp: once
    it is exhausted no retry is taken, and a backoff that would overrun it
    is CAPPED to the remaining budget — the policy never sleeps past its
    own deadline (it used to refuse such retries outright, giving up
    budget that was still available).
    """

    max_retries: int = 0
    base_delay: float = 0.1
    max_delay: float = 2.0
    jitter: bool = True
    deadline: Optional[float] = None
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)
    clock: Callable[[], float] = field(default=time.monotonic, repr=False)
    rand: Callable[[], float] = field(default=random.random, repr=False)

    def __post_init__(self) -> None:
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based): capped exponential,
        full jitter (uniform in [0, cap]) unless ``jitter=False``."""
        cap = min(self.max_delay, self.base_delay * (2 ** max(0, attempt - 1)))
        return cap * self.rand() if self.jitter else cap

    def pause(self, attempt: int, start: Optional[float] = None) -> bool:
        """Sleep before retry ``attempt`` and return True, or return False
        (without sleeping) when the budget — attempt count, or deadline
        measured from ``start`` — is exhausted and the caller must raise."""
        if attempt > self.max_retries:
            return False
        delay = self.backoff(attempt)
        if self.deadline is not None and start is not None:
            remaining = self.deadline - (self.clock() - start)
            if remaining <= 0:
                return False
            # never sleep past the deadline: spend exactly the remaining
            # budget on this backoff instead of refusing the retry
            delay = min(delay, remaining)
        if delay > 0:
            self.sleep(delay)
        return True

    def call(
        self,
        fn: Callable,
        *args,
        retry_on: Tuple[Type[BaseException], ...] = (OSError,),
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        **kwargs,
    ):
        """Run ``fn`` under this policy. ``on_retry(attempt, exc)`` fires
        once per retry actually taken (metrics hooks go here)."""
        attempt = 0
        start = self.clock()
        while True:
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                attempt += 1
                if not self.pause(attempt, start):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)


#: Zero-retry policy: one attempt, fail fast (the historical default for
#: both the read and write paths).
NO_RETRY = RetryPolicy(max_retries=0)
