"""Pluggable filesystem layer.

The reference reads and writes through Hadoop's ``FileSystem`` — any scheme
(HDFS, GCS, S3) for free via ``CodecStreams.createOutputStream``
(TFRecordOutputWriter.scala:19) and the Hadoop input format
(TFRecordFileReader.scala:24-32). Here the same pluggability comes from a
minimal FS interface: paths with a URI scheme (``gs://``, ``s3://``,
``memory://``, ...) route through fsspec when it is installed; plain paths
use the standard library directly (zero overhead on the hot path).

Semantics notes:
- ``rename`` is the commit primitive. Local rename is atomic; object stores
  have no rename, so fsspec's ``mv`` is copy+delete there — the commit is
  then idempotent-but-not-atomic (the same tradeoff Hadoop's
  FileOutputCommitter v2 makes on object stores).
- Paths returned by listing/glob/walk keep their scheme prefix, so every
  downstream consumer (codec detection, shard bookkeeping) works on full
  URLs unchanged. This module is Linux-first: URL path arithmetic uses '/',
  which equals ``os.sep`` everywhere this framework runs.
"""

from __future__ import annotations

import glob as _glob
import io
import os
import re
import shutil
from time import monotonic as _time_monotonic
from typing import BinaryIO, Iterator, List, Optional

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")


def has_scheme(path: str) -> bool:
    return bool(_SCHEME_RE.match(str(path)))


def local_open(path: str, mode: str) -> BinaryIO:
    """The ONE raw-open seam for plain (scheme-less) paths on the record
    read/write hot paths (wire.open_compressed routes through this).
    Deliberately just ``open``: zero overhead by default, and a single
    place the deterministic chaos injector (tpu_tfrecord.faults) patches
    to reach every read mode without touching real deployments."""
    return open(path, mode)  # noqa: SIM115


class LocalFS:
    """Standard-library filesystem — the default for plain paths."""

    def normalize(self, path: str) -> str:
        return path

    def open(self, path: str, mode: str) -> BinaryIO:
        return open(path, mode)  # noqa: SIM115

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isfile(self, path: str) -> bool:
        return os.path.isfile(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str) -> List[str]:
        return os.listdir(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        os.remove(path)

    def rmtree(self, path: str, ignore_errors: bool = False) -> None:
        shutil.rmtree(path, ignore_errors=ignore_errors)

    def rmdir(self, path: str) -> None:
        os.rmdir(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def size(self, path: str) -> int:
        return os.path.getsize(path)

    def glob(self, pattern: str) -> List[str]:
        return sorted(_glob.glob(pattern))

    def walk_files(self, root: str, keep):
        """Deterministic (sorted) walk yielding (path, size) for files under
        root, descending only into directories ``keep`` accepts and yielding
        only files it accepts. Sizes come from the directory listing
        (scandir stat) — no per-file stat round. Directory SYMLINKS are not
        followed (os.walk's default): a link cycle must not hang discovery,
        and a link into the same tree must not double-count shards."""
        stack = [root]
        while stack:
            dirpath = stack.pop()
            files, dirs = [], []
            with os.scandir(dirpath) as entries:
                for e in entries:
                    if not keep(e.name):
                        continue
                    if e.is_dir(follow_symlinks=False):
                        dirs.append(e.path)
                    elif e.is_dir(follow_symlinks=True):
                        pass  # directory symlink: neither followed nor a file
                    else:
                        files.append((e.path, e.stat().st_size))
            for fpath, size in sorted(files):
                yield fpath, size
            stack.extend(sorted(dirs, reverse=True))  # pop() visits in order

    def touch(self, path: str) -> None:
        # graftlint: allow(atomic-write: zero-byte marker create; no content to tear)
        with open(path, "wb"):
            pass


class FsspecFS:
    """fsspec-backed filesystem for scheme'd URLs. All returned paths carry
    the scheme prefix (``fs.unstrip_protocol``)."""

    def __init__(self, url: str):
        import fsspec

        self._fs, _ = fsspec.core.url_to_fs(url)

    def _strip(self, path: str) -> str:
        return self._fs._strip_protocol(path)

    def _unstrip(self, path: str) -> str:
        return self._fs.unstrip_protocol(path)

    def normalize(self, path: str) -> str:
        """Canonical URL form — listing/walk results are unstripped, so
        callers comparing against an input root must normalize it the same
        way (e.g. ``memory:///x`` vs ``memory://x``)."""
        return self._unstrip(self._strip(path))

    def open(self, path: str, mode: str) -> BinaryIO:
        return self._fs.open(self._strip(path), mode)

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def isfile(self, path: str) -> bool:
        return self._fs.isfile(self._strip(path))

    def isdir(self, path: str) -> bool:
        return self._fs.isdir(self._strip(path))

    def listdir(self, path: str) -> List[str]:
        base = self._strip(path)
        return sorted(
            p.rstrip("/").rsplit("/", 1)[-1]
            for p in self._fs.ls(base, detail=False)
            if p.rstrip("/") != base.rstrip("/")
        )

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(self._strip(path), exist_ok=True)

    def remove(self, path: str) -> None:
        self._fs.rm_file(self._strip(path))

    def rmtree(self, path: str, ignore_errors: bool = False) -> None:
        try:
            self._fs.rm(self._strip(path), recursive=True)
        except Exception:
            if not ignore_errors:
                raise

    def rmdir(self, path: str) -> None:
        # Object stores have no real directories; an "empty dir" marker may
        # not even exist. Only remove when actually empty, like os.rmdir.
        sp = self._strip(path)
        if self._fs.exists(sp):
            if self._fs.ls(sp, detail=False):
                raise OSError(f"Directory not empty: {path}")
            self._fs.rmdir(sp)

    def rename(self, src: str, dst: str) -> None:
        # copy+delete on stores without native rename (see module docstring)
        self._fs.mv(self._strip(src), self._strip(dst))

    def size(self, path: str) -> int:
        return self._fs.size(self._strip(path))

    def info(self, path: str) -> dict:
        """Backend metadata dict (size plus whatever freshness stamp the
        store exposes — mtime / LastModified / ETag); the epoch cache keys
        remote-source invalidation on it (tpu_tfrecord.cache.source_stat)."""
        return self._fs.info(self._strip(path))

    def glob(self, pattern: str) -> List[str]:
        return sorted(
            self._unstrip(p) for p in self._fs.glob(self._strip(pattern))
        )

    def walk_files(self, root: str, keep):
        """(path, size) pairs via an explicit SORTED stack walk over
        ``ls(detail=True)`` — one list call per directory, not one HEAD per
        shard, and deterministic recursion order: fsspec's own walk recurses
        in ls/dict order, which differs between hosts/backends and would
        silently skew the global shard order every host must agree on.
        Listing failures raise (a dropped subtree must never look like a
        smaller dataset)."""
        stack = [self._strip(root)]
        while stack:
            dirpath = stack.pop()
            files, dirs = [], []
            for info in self._fs.ls(dirpath, detail=True):
                name = info["name"].rstrip("/")
                if name == dirpath.rstrip("/"):
                    continue  # some backends include the dir itself
                if not keep(name.rsplit("/", 1)[-1]):
                    continue
                if info.get("type") == "directory":
                    dirs.append(name)
                else:
                    files.append((name, int(info.get("size") or 0)))
            for fpath, size in sorted(files):
                yield self._unstrip(fpath), size
            stack.extend(sorted(dirs, reverse=True))  # pop() visits in order

    def touch(self, path: str) -> None:
        self._fs.touch(self._strip(path))


class PrefetchReader(io.RawIOBase):
    """Sequential-read pipeline over a remote object: ``depth`` block
    fetches in flight at once, each on its OWN reader handle (the analog of
    parallel HTTP range GETs — and of the Hadoop FS connectors' readahead
    the reference streams HDFS/GCS/S3 through, TFRecordFileReader.scala:
    24-32). A serial ``fh.read`` loop pays one link round-trip per block;
    pipelining hides that latency behind the consumer's decode, so a cold
    remote read saturates the link (pinned by tests/test_fs.py::
    TestRemotePrefetch on a simulated link, tests/test_http_remote.py on
    real sockets).

    Contract: forward sequential reads only (exactly what the slab
    streamer issues). With a ``retry_policy``, block fetches SELF-HEAL: a
    transient fetch error (reset, truncated body, 503 — anything OSError)
    is retried on a FRESH handle resuming from the exact byte offset the
    last attempt reached (``read.retries``/``remote.fetch_retries``), and
    a server's Retry-After hint is honored through the policy's sleep
    seam. Errors that outlive the budget surface on the consumer's next
    read; the shard-level retry machinery reopens the stream. A short
    block mid-object (clean EOF) yields a short read, which the framing
    layer reports as truncation."""

    def __init__(
        self,
        fs,
        path: str,
        size: int,
        block_bytes: int,
        depth: int,
        serialize_fetches: bool = False,
        retry_policy=None,
    ):
        super().__init__()
        import threading
        from concurrent.futures import ThreadPoolExecutor

        self._fs = fs
        self._path = path
        self._size = size
        self._block = max(64 << 10, int(block_bytes))
        depth = max(1, int(depth))
        self._nblocks = (size + self._block - 1) // self._block
        self._pool = ThreadPoolExecutor(
            max_workers=depth, thread_name_prefix="tfr-prefetch"
        )
        self._depth = depth
        self._retry_policy = retry_policy
        # fsspec's memory backend hands every open() the SAME file object
        # (shared seek cursor) — fetches there must serialize to stay
        # correct; real object-store backends give independent handles and
        # fetch fully in parallel.
        self._fetch_lock = threading.Lock() if serialize_fetches else None
        self._futs = {}
        self._next = 0
        self._pos = 0
        self._cur = b""
        self._cur_idx = -1
        self._schedule()

    def _fetch(self, idx: int) -> bytes:
        start = idx * self._block
        n = min(self._block, self._size - start)
        if self._fetch_lock is not None:
            with self._fetch_lock:
                return self._fetch_retrying(start, n)
        return self._fetch_retrying(start, n)

    def _fetch_retrying(self, start: int, n: int) -> bytes:
        """One block fetch under the retry policy: each attempt resumes
        from the EXACT byte offset the previous one reached (a fresh
        handle re-ranges at start+got — no byte is refetched, none is
        skipped). Without a policy: one attempt, the historical
        behavior."""
        pol = self._retry_policy
        if pol is None:
            return self._fetch_one(start, n)
        parts: list = []
        attempt = 0
        t0 = pol.clock()
        while True:
            try:
                self._fetch_into(start + sum(map(len, parts)),
                                 n - sum(map(len, parts)), parts)
                return b"".join(parts)
            except OSError as e:
                attempt += 1
                if not _grant_retry(pol, attempt, t0, e):
                    raise

    def _fetch_one(self, start: int, n: int) -> bytes:
        parts: list = []
        self._fetch_into(start, n, parts)
        return b"".join(parts)

    def _fetch_into(self, start: int, n: int, parts: list) -> None:
        """Read [start, start+n) into ``parts`` chunk by chunk; on an
        error the chunks already read stay in ``parts``, so the retry
        resumes from the exact byte the connection died at instead of
        refetching the block."""
        with self._fs.open(self._path, "rb") as fh:
            fh.seek(start)
            got = 0
            while got < n:
                chunk = fh.read(n - got)
                if not chunk:
                    return  # short object: surfaces as a short read
                parts.append(chunk)
                got += len(chunk)

    def _schedule(self) -> None:
        while self._next < self._nblocks and len(self._futs) < self._depth:
            self._futs[self._next] = self._pool.submit(self._fetch, self._next)
            self._next += 1

    def _block_data(self, idx: int) -> bytes:
        if idx != self._cur_idx:
            fut = self._futs.pop(idx, None)
            if fut is None:  # out-of-order use: fetch inline (correct, slow)
                fut = self._pool.submit(self._fetch, idx)
            self._cur = fut.result()
            self._cur_idx = idx
            self._schedule()
        return self._cur

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        mv = memoryview(b)
        want = len(mv)
        done = 0
        while done < want and self._pos < self._size:
            idx = self._pos // self._block
            off = self._pos - idx * self._block
            blk = self._block_data(idx)
            if off >= len(blk):
                break  # short block: truncated object
            take = min(want - done, len(blk) - off)
            mv[done : done + take] = blk[off : off + take]
            done += take
            self._pos += take
        return done

    def tell(self) -> int:
        return self._pos

    _CLOSE_TIMEOUT_S = 10.0

    def close(self) -> None:
        """Bounded-wait close (ADVICE r5 #2): cancel queued fetches, then
        WAIT for in-flight fetch threads — an in-flight fetch holds a live
        backend handle, and letting it outlive close() races tempdir
        cleanup and backends that assume no reads after close. The wait is
        bounded (TFR_REMOTE_CLOSE_TIMEOUT_S): a fetch wedged in a dead
        socket must not wedge close() too — that one thread is abandoned
        exactly like a stall-guard worker, and its handle closes when the
        blocked call finally returns (the with-block in _fetch_into)."""
        if not self.closed:
            futs = list(self._futs.values())
            self._futs.clear()
            for fut in futs:
                fut.cancel()
            self._pool.shutdown(wait=False, cancel_futures=True)
            timeout = float(
                os.environ.get("TFR_REMOTE_CLOSE_TIMEOUT_S", self._CLOSE_TIMEOUT_S)
            )
            deadline = _time_monotonic() + timeout
            for t in list(getattr(self._pool, "_threads", ()) or ()):
                t.join(max(0.0, deadline - _time_monotonic()))
        super().close()


#: sanity ceiling on honoring a server's Retry-After: a hostile or buggy
#: server must not be able to park a reader for an hour with one header.
_RETRY_AFTER_CAP_S = 30.0


def _grant_retry(pol, attempt: int, t0: float, exc: BaseException) -> bool:
    """ONE owner for the remote-fetch retry grant (shared by the block
    prefetcher and the plain self-healing stream): consult the policy's
    budget, and only for a GRANTED retry honor the server's Retry-After
    pacing hint (through the injectable sleep seam) and bump the
    counters. False = budget exhausted, caller re-raises.

    The hint is BOUNDED like the policy's own backoff: capped at
    ``_RETRY_AFTER_CAP_S`` and never past the policy's remaining
    wall-clock deadline — ``pause`` promises not to sleep past the
    deadline, and the hint must not smuggle that promise away."""
    if not pol.pause(attempt, t0):
        return False
    retry_after = getattr(exc, "retry_after", None)
    if retry_after:
        delay = min(float(retry_after), _RETRY_AFTER_CAP_S)
        if pol.deadline is not None:
            delay = min(delay, max(0.0, pol.deadline - (pol.clock() - t0)))
        if delay > 0:
            pol.sleep(delay)
    from tpu_tfrecord.metrics import METRICS

    METRICS.count("read.retries")
    METRICS.count("remote.fetch_retries")
    return True


class RetryingReadStream:
    """Self-healing wrapper for a PLAIN (non-prefetched) remote read
    handle: objects below the PrefetchReader engagement bar get the SAME
    contract — a transient read fault reopens a fresh handle positioned
    at the exact byte offset already consumed and resumes
    (``read.retries``/``remote.fetch_retries``, Retry-After honored).
    Forward sequential reads; seek supported (resets position)."""

    def __init__(self, fs, path: str, retry_policy, fh=None):
        self._fs = fs
        self._path = path
        self._pol = retry_policy
        self._fh = fh if fh is not None else fs.open(path, "rb")
        self._pos = 0
        self._closed = False

    def _drop_fh(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except Exception:  # graftlint: swallow(dropping an already-broken handle before reopen)
                pass

    _CHUNK = 8 << 20

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            # chunk the read-to-EOF HERE: delegating it to the inner
            # handle would lose its partial progress on a fault and
            # restart from byte 0 instead of the exact consumed offset
            parts = []
            while True:
                chunk = self.read(self._CHUNK)
                if not chunk:
                    return b"".join(parts)
                parts.append(chunk)
        pol = self._pol
        attempt = 0
        t0 = pol.clock()
        while True:
            try:
                # the reopen runs INSIDE the retried block: a transient
                # open-time fault spends the same budget as a read fault
                # instead of escaping it
                if self._fh is None:
                    fh = self._fs.open(self._path, "rb")
                    seek_to(fh, self._pos)
                    self._fh = fh
                data = self._fh.read(size)
                self._pos += len(data)
                return data
            except OSError as e:
                self._drop_fh()
                attempt += 1
                if not _grant_retry(pol, attempt, t0, e):
                    raise

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            # SEEK_CUR needs no handle — the stream owns its position
            return self.seek(self._pos + pos)
        if whence == 2:
            if self._fh is None:
                self._fh = self._fs.open(self._path, "rb")
            pos = self._fh.seek(pos, 2)
            self._pos = pos
            return pos
        if whence != 0:
            raise ValueError(f"unsupported whence {whence}")
        if self._fh is not None:
            try:
                self._fh.seek(pos)
            except OSError:
                # a dead handle repositions lazily: the next read reopens
                # at the requested offset under the retry budget
                self._drop_fh()
        self._pos = pos
        return pos

    def tell(self) -> int:
        return self._pos

    def readable(self) -> bool:
        return True

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._drop_fh()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "RetryingReadStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def seek_to(fh, pos: int) -> None:
    """Position a fresh handle at byte ``pos``: seek when supported,
    read-and-discard otherwise (non-seekable remote wrappers). The ONE
    owner of this idiom — the stall guard's hedge reopen and the
    self-healing stream's resume both route here."""
    if pos <= 0:
        return
    seek = getattr(fh, "seek", None)
    if seek is not None:
        try:
            seek(pos)
            return
        except (OSError, ValueError):
            pass
    left = pos
    while left > 0:
        chunk = fh.read(min(left, 8 << 20))
        if not chunk:
            return
        left -= len(chunk)


def _remote_prefetch_params() -> tuple:
    """(block_bytes, depth); env-tunable, read per open so tests can vary."""
    block = int(os.environ.get("TFR_REMOTE_BLOCK_BYTES", 8 << 20))
    depth = int(os.environ.get("TFR_REMOTE_PREFETCH_DEPTH", 4))
    return block, depth


#: fsspec protocols whose ``open()`` is known to hand out an INDEPENDENT
#: file object per call (its own cursor), so PrefetchReader may run its
#: block fetches concurrently. Everything else — including ``memory://``
#: (one shared file object per path) and any scheme not listed here —
#: serializes: on an unknown backend, concurrent seek+read on a possibly
#: shared handle would silently return corrupted blocks, while needless
#: serialization merely costs parallelism (ADVICE r5 #1 / ROADMAP #3 —
#: the old protocol SNIFF defaulted unknown schemes to the corrupting
#: parallel path).
_INDEPENDENT_HANDLE_PROTOCOLS = frozenset(
    {
        "file", "local",
        "s3", "s3a",
        "gs", "gcs",
        "az", "abfs", "abfss", "adl",
        "http", "https",
        "hdfs", "webhdfs",
        "oss",
    }
)
# NOT listed (deliberately): ftp funnels every file object through ONE
# shared ftplib control connection, and sftp/ssh multiplex one paramiko
# channel — concurrent range fetches there interleave protocol traffic on
# a shared session, which is exactly the corruption mode this flag
# exists to rule out. They serialize like any unknown scheme.


def independent_read_handles(fs) -> bool:
    """Explicit capability flag: may PrefetchReader fetch blocks of one
    object CONCURRENTLY through ``fs.open()``?

    Resolution order, walking the ``_fs`` wrapper chain (FsspecFS wraps
    the fsspec filesystem; ChaosFS and test shims wrap either):

    1. an ``independent_read_handles`` attribute anywhere on the chain —
       the capability declaration; wrappers that change handle semantics
       (or backends fsspec cannot classify) set it explicitly;
    2. a declared fsspec ``protocol``, classified against the known
       independent-handle allowlist above;
    3. neither found, or an unknown protocol: **False** — serialize.
       Unknown backends default to the SAFE path: slower, never corrupt.
    """
    obj = fs
    for _ in range(8):
        if obj is None:
            return False
        cap = getattr(obj, "independent_read_handles", None)
        if cap is not None and not callable(cap):
            return bool(cap)
        proto = getattr(obj, "__dict__", {}).get("protocol", None) or getattr(
            type(obj), "protocol", None
        )
        if proto is not None:
            protos = (
                tuple(proto) if isinstance(proto, (list, tuple)) else (str(proto),)
            )
            return all(p in _INDEPENDENT_HANDLE_PROTOCOLS for p in protos)
        obj = getattr(obj, "_fs", None)
    return False


def open_for_read(fs, path: str, retry_policy=None) -> BinaryIO:
    """Open a scheme'd path for streaming read: block-pipelined
    PrefetchReader for objects big enough to benefit, the plain handle
    otherwise (or when size probing / prefetch setup is impossible).
    TFR_REMOTE_PREFETCH_DEPTH=0 disables pipelining. ``retry_policy``
    makes the prefetcher's block fetches self-heal (resume from the exact
    byte offset on transient faults); None = TFR_REMOTE_FETCH_RETRIES
    retries (default 0, the fail-fast historical behavior)."""
    block, depth = _remote_prefetch_params()
    if retry_policy is None:
        retry_policy = _default_fetch_retry_policy()
    size: Optional[int] = None
    if depth > 0:
        try:
            size = fs.size(path)
        except Exception:  # graftlint: swallow(size probe failed: prefetch engagement degrades to a plain stream)
            size = None
    if size is not None and size >= 2 * block:
        return PrefetchReader(
            fs, path, size, block, depth,
            serialize_fetches=not independent_read_handles(fs),
            retry_policy=retry_policy,
        )
    if retry_policy is not None:
        # below the prefetch bar the SAME self-healing contract applies:
        # a plain handle whose reads reopen + resume at the exact offset
        return RetryingReadStream(fs, path, retry_policy)
    return fs.open(path, "rb")


def _default_fetch_retry_policy():
    """Block-fetch retry budget when the caller supplied no policy
    (row-level readers, tools): TFR_REMOTE_FETCH_RETRIES (default 0 —
    one attempt, exactly the historical behavior)."""
    retries = int(os.environ.get("TFR_REMOTE_FETCH_RETRIES", 0))
    if retries <= 0:
        return None
    from tpu_tfrecord.retry import RetryPolicy

    return RetryPolicy(max_retries=retries)


_LOCAL = LocalFS()


def filesystem_for(path: str):
    """The FS for a path: the stdlib HTTP client for ``http://`` /
    ``https://`` (real sockets, Range requests, Content-Range
    verification — no fsspec/aiohttp needed; tpu_tfrecord.httpfs), fsspec
    for every other scheme'd URL, the standard library for plain paths.
    Non-HTTP scheme'd paths without fsspec installed raise with a clear
    message (fsspec is an optional dependency)."""
    spath = os.fspath(path)
    if has_scheme(spath):
        scheme = spath.split("://", 1)[0].lower()
        if scheme in ("http", "https"):
            from tpu_tfrecord.httpfs import HttpFS

            return HttpFS(spath)
        try:
            import fsspec  # noqa: F401
        except ImportError as e:
            raise ImportError(
                f"path {path!r} has a URL scheme, which requires the optional "
                "fsspec dependency (pip install fsspec)"
            ) from e
        # other ImportErrors (e.g. missing s3fs/gcsfs protocol package)
        # propagate with fsspec's own actionable message
        return FsspecFS(spath)
    return _LOCAL
