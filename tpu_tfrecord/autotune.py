"""Closed-loop autotuning: the flight recorder drives the knobs.

tf.data's core lesson (PAPERS.md, "tf.data: A Machine Learning Data
Processing Framework") is that static ``workers`` / ``prefetch`` /
``readahead_mb`` / ``hedge_after_ms`` settings are always wrong somewhere:
the right decode parallelism depends on the box, the schema, and whatever
else shares the cores, and the right stall thresholds depend on the store's
latency distribution — none of which are known at config-writing time.
PR 5 built the sensors (per-stage p50/p99 histograms, the
``prefetch.occupancy`` EMA, the producer/consumer bound-ness verdict);
this module is the actuator.

Three pieces:

- **``PipelineControl``** — the live-adjustment surface of ONE iterator's
  pipeline: resize the decode worker pool (``set_workers``; the parallel
  shard pipeline in io/dataset.py spawns/retires workers mid-epoch without
  touching output order), resize the prefetch queue (``set_prefetch``),
  retarget the readahead window (``set_readahead_bytes``), and reach the
  dataset's ``StallGuard`` (whose deadline/hedge thresholds are read live
  by guarded streams — see stall.py). Every adjustment preserves the
  pipeline's guarantees: chunk boundaries and emit order are a function of
  the data and the decode options, never of the worker count, so row
  output stays byte-identical and IteratorState checkpoints resume
  interchangeably across any resize.

- **``AutotuneController``** — bounded hill-climbing at pulse boundaries.
  Each ``telemetry.Pulse`` tick hands the controller the interval's
  payload (per-interval stage deltas, cumulative quantiles, gauges, the
  bound-ness verdict); the controller applies at most one pool move per
  cooldown window:

  * ``producer_bound`` for ``hysteresis`` consecutive ticks → grow the
    decode pool by one worker (and keep the prefetch queue deep enough to
    absorb the extra producer).
  * ``consumer_bound`` for ``hysteresis`` consecutive ticks → shrink the
    pool toward the floor (decode is already ahead; spare threads only
    steal cycles from the consumer).
  * ``readahead`` retargets to ``read.io`` bandwidth × a time horizon
    (keep ~`readahead_horizon_s` of IO in flight), band-limited so it only
    moves on a real regime change.
  * ``hedge_after_ms`` / ``read_deadline_ms`` / ``open_deadline_ms``
    derive from the OBSERVED open/read p99 (×`hedge_p99_mult` /
    ×`deadline_p99_mult`) instead of hand-set milliseconds — a threshold
    that tracks the store's actual latency distribution hedges stragglers
    without false-positives on a slow-but-healthy store.

  Hysteresis, per-knob min/max clamps, and a wall-clock cooldown keep
  chaos-injected stalls (or one noisy interval) from whipsawing the pool.
  Every decision is auditable: one ``autotune.adjustments`` counter bump +
  ``autotune.<knob>`` gauge write + ``autotune.adjust`` trace instant per
  move, the full decision log on ``controller.log``, and an ``autotune``
  block merged into every pulse line.

- **Wiring** — ``TFRecordOptions(autotune="on")``: the iterator builds a
  ``PipelineControl``, a controller, and (if none was configured) a pulse
  at ``autotune_interval_s``; the controller runs as a pulse observer.
  ``tfrecord_doctor tune DATA_DIR`` runs the loop offline and prints the
  converged knob set; ``bench.py`` reports an ``autotune`` block
  (convergence trajectory + final knobs + throughput vs fixed-knob).

Everything here is opt-in: with ``autotune="off"`` (the default) no
controller, no control object, and no extra per-batch work exists.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tpu_tfrecord import telemetry

__all__ = [
    "AutotuneController",
    "AutotunePolicy",
    "BoundedClimber",
    "PipelineControl",
    "DEFAULT_INTERVAL_S",
    "default_max_workers",
]

#: Pulse cadence when autotune is on but no pulse_interval_s /
#: autotune_interval_s was configured.
DEFAULT_INTERVAL_S = 1.0


def default_max_workers() -> int:
    """Decode-pool ceiling when the caller sets none: enough headroom to
    matter on IO-stalled pipelines (sleeping reads release the GIL, so
    useful parallelism can exceed the core count) without unbounded thread
    growth."""
    try:
        ncpu = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        ncpu = os.cpu_count() or 1
    return min(32, max(4, 2 * ncpu))


class BoundedClimber:
    """Verdict-streak hysteresis + wall-clock cooldown — the guard-rail
    bookkeeping every bounded hill-climber here shares. One instance per
    climber: the per-iterator pool controller (``AutotuneController``)
    and the fleet-level scaler (``tpu_tfrecord.elastic.FleetScaler``)
    both pace their moves through it, so "chaos-injected stalls can't
    whipsaw the pool" is ONE invariant with one owner, not two
    re-implementations that can drift.

    ``observe(verdict)`` returns the verdict when it is actionable —
    the same verdict for ``hysteresis`` consecutive observations AND the
    cooldown window since the last move has passed — else None. The
    caller reports a move with ``acted()`` (stamps the cooldown, resets
    the streak). Verdicts outside ``actionable`` reset the streak: one
    quiet tick between two producer_bound ticks means the boundness was
    noise, not a regime.
    """

    def __init__(
        self,
        hysteresis: int,
        cooldown_s: float,
        clock: Callable[[], float] = time.monotonic,
        actionable: tuple = ("producer_bound", "consumer_bound"),
    ):
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.actionable = tuple(actionable)
        self._verdict: Optional[str] = None
        self._streak = 0
        self._last_move = -float("inf")

    @property
    def streak(self) -> int:
        return self._streak

    def observe(self, verdict: Optional[str]) -> Optional[str]:
        if verdict not in self.actionable:
            self._verdict = None
            self._streak = 0
            return None
        if verdict == self._verdict:
            self._streak += 1
        else:
            self._verdict = verdict
            self._streak = 1
        if self._streak < self.hysteresis:
            return None
        if self.clock() - self._last_move < self.cooldown_s:
            return None
        return verdict

    def acted(self) -> None:
        self._last_move = self.clock()
        self._streak = 0

    def cooldown_remaining(self) -> float:
        return max(0.0, self.cooldown_s - (self.clock() - self._last_move))


class PipelineControl:
    """Live-adjustable knobs of one iterator's pipeline.

    Thread-safety: ``set_*`` are called from the pulse thread (or tests)
    while workers run; every pool-accounting mutation happens under one
    lock. Worker threads participate through three hooks wired by
    ``_parallel_chunks`` (io/dataset.py): ``bind_spawn`` registers the
    thread factory (and brings the pool up to target), ``should_exit``
    lets a worker volunteer to retire when the pool is over target (the
    exit is reserved under the lock, so exactly the surplus retires), and
    ``note_exit`` balances the books on any exit path.
    """

    def __init__(
        self,
        workers: int,
        max_workers: Optional[int] = None,
        queue=None,
        dataset=None,
        guard=None,
    ):
        self._lock = threading.Lock()
        # the ceiling never clamps a user-CONFIGURED starting pool: someone
        # who asked for num_workers=48 gets 48 (autotune may shrink it
        # later on evidence, which is the contract — a silent startup
        # downgrade is not)
        self.max_workers = max(int(workers), max_workers or default_max_workers())
        self.target_workers = max(1, int(workers))
        self._alive = 0
        self._exit_permits = 0
        self._spawn: Optional[Callable[[], None]] = None
        self.queue = queue
        self._dataset = dataset
        self.guard = guard
        self._prefetch = queue.maxsize if queue is not None else None
        self._readahead = (
            getattr(dataset, "readahead_bytes", None) if dataset is not None else None
        )

    # -- decode worker pool --------------------------------------------------

    @property
    def workers(self) -> int:
        return self.target_workers

    def bind_spawn(self, spawn: Callable[[], None]) -> None:
        """Register the worker thread factory and bring the pool up to the
        current target (one call per _parallel_chunks run)."""
        with self._lock:
            self._spawn = spawn
            deficit = self.target_workers - (self._alive - self._exit_permits)
            if deficit > 0:
                self._alive += deficit
        for _ in range(max(0, deficit)):
            spawn()

    def set_workers(self, n: int) -> int:
        """Retarget the decode pool to ``n`` workers (clamped to
        [1, max_workers]); growth spawns immediately, shrink retires
        workers as they finish their current shard. Returns the clamped
        target."""
        n = max(1, min(int(n), self.max_workers))
        to_spawn = 0
        with self._lock:
            self.target_workers = n
            if self._spawn is not None:
                deficit = n - (self._alive - self._exit_permits)
                if deficit > 0:
                    self._alive += deficit
                    to_spawn = deficit
        for _ in range(to_spawn):
            self._spawn()
        return n

    def should_exit(self) -> bool:
        """Worker hook: True reserves one retirement when the pool is over
        target (the caller must exit WITHOUT claiming new work and then
        call ``note_exit(permitted=True)``)."""
        with self._lock:
            if self._alive - self._exit_permits > self.target_workers:
                self._exit_permits += 1
                return True
        return False

    def note_exit(self, permitted: bool = False) -> None:
        """Worker hook: balance the pool books on ANY worker exit."""
        with self._lock:
            self._alive -= 1
            if permitted and self._exit_permits:
                self._exit_permits -= 1

    # -- prefetch queue ------------------------------------------------------

    @property
    def prefetch(self) -> Optional[int]:
        q = self.queue
        return q.maxsize if q is not None else self._prefetch

    def set_prefetch(self, n: int) -> int:
        n = max(1, int(n))
        q = self.queue
        if q is not None:
            q.resize(n)
        self._prefetch = n
        return n

    # -- readahead window ----------------------------------------------------

    @property
    def readahead_bytes(self) -> Optional[int]:
        ds = self._dataset
        if ds is not None:
            return ds.readahead_bytes
        return self._readahead

    def set_readahead_bytes(self, n: int) -> int:
        """Retarget the sliding WILLNEED window; picked up at the next
        shard open (the per-shard hinter captures the window once)."""
        n = max(0, int(n))
        ds = self._dataset
        if ds is not None:
            ds.readahead_bytes = n
        self._readahead = n
        return n


@dataclass
class AutotunePolicy:
    """Bounds and pacing for the hill-climber. Every knob move is clamped
    to its [min, max]; the pool only moves after ``hysteresis`` consecutive
    same-verdict ticks and at most once per ``cooldown_s`` wall-clock
    window; derived thresholds only move on a relative change beyond
    ``threshold_rel_band`` (so a quiet store doesn't twitch them every
    tick)."""

    hysteresis: int = 2
    cooldown_s: float = 2.0
    min_workers: int = 1
    max_workers: int = field(default_factory=default_max_workers)
    min_prefetch: int = 2
    max_prefetch: int = 32
    # readahead retarget: keep ~horizon seconds of observed read.io
    # bandwidth in flight, moved only on a >50% regime change
    min_readahead_mb: int = 8
    max_readahead_mb: int = 256
    readahead_horizon_s: float = 0.5
    readahead_rel_band: float = 0.5
    # stall thresholds derived from observed latency quantiles. Deadline
    # multiples are deliberately wide (a false deadline miss RAISES and can
    # kill an epoch under on_stall="raise"); a false hedge is benign — it
    # just opens a backup read whose loser is discarded — so it sits much
    # closer to the observed p99.
    hedge_p99_mult: float = 4.0
    deadline_p99_mult: float = 20.0
    min_hedge_ms: float = 100.0
    min_deadline_ms: float = 2_000.0
    max_deadline_ms: float = 120_000.0
    threshold_rel_band: float = 0.25
    # quantiles are cumulative: require this many observations before
    # trusting a p99 enough to derive a deadline from it
    min_latency_samples: int = 20


class AutotuneController:
    """Pulse-boundary hill-climber over one pipeline's knobs.

    Run it as a pulse observer (``pulse.add_observer(c.on_pulse)``): each
    tick it reads the pulse payload, applies bounded adjustments through
    its ``PipelineControl``, and returns an ``{"autotune": {...}}`` block
    merged into the emitted pulse line — every decision lands in the same
    trace the flight recorder already writes.
    """

    def __init__(
        self,
        control: PipelineControl,
        interval_s: float = DEFAULT_INTERVAL_S,
        policy: Optional[AutotunePolicy] = None,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.control = control
        # default cooldown scales with the tick cadence: two quiet ticks
        # between pool moves, whatever the interval
        self.policy = policy or AutotunePolicy(
            cooldown_s=max(0.25, 2.0 * interval_s)
        )
        self.metrics = metrics
        self.clock = clock
        self.interval_s = interval_s
        #: full decision log: one dict per adjustment (knob, from, to,
        #: reason, tick) — the convergence trajectory bench/doctor report
        self.log: List[Dict[str, Any]] = []
        self._tick = 0
        # guard-rail bookkeeping (hysteresis streaks + cooldown) is shared
        # with the fleet scaler — one owner (BoundedClimber); the policy's
        # knobs are re-read every tick so a policy mutated after
        # construction still governs
        self._climber = BoundedClimber(
            self.policy.hysteresis, self.policy.cooldown_s, clock=clock
        )
        # clamp the control's pool ceiling to the policy's — but never
        # below the configured starting pool (see PipelineControl)
        self.control.max_workers = max(
            self.control.target_workers,
            min(self.control.max_workers, self.policy.max_workers),
        )

    # -- knob application ----------------------------------------------------

    def _adjust(self, knob: str, old, new, reason: str, apply) -> bool:
        """Apply one knob move; record it everywhere a reader might look."""
        if new == old:
            return False
        apply(new)
        decision = {
            "tick": self._tick,
            "knob": knob,
            "from": old,
            "to": new,
            "reason": reason,
        }
        self.log.append(decision)
        self.metrics.count("autotune.adjustments")
        self.metrics.gauge(f"autotune.{knob}", float(new))
        telemetry.instant(
            "autotune.adjust", knob=knob, old=old, new=new, reason=reason
        )
        return True

    # -- the tick ------------------------------------------------------------

    def on_pulse(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """One control step. ``payload`` is a ``telemetry.Pulse.tick``
        dict (stages / counters / gauges / quantiles / verdict); returns
        the ``autotune`` block for the pulse line."""
        self._tick += 1
        n_before = len(self.log)
        self._step_pool(payload)
        self._step_readahead(payload)
        self._step_thresholds(payload)
        adjusted = self.log[n_before:]
        return {"autotune": self.snapshot(adjusted)}

    def snapshot(self, adjusted: Optional[List[Dict]] = None) -> Dict[str, Any]:
        """Current knob values (+ this tick's moves when given) — the
        shape the pulse line, doctor ``tune``, and bench all emit."""
        c = self.control
        guard = c.guard
        out: Dict[str, Any] = {
            "workers": c.workers,
            "prefetch": c.prefetch,
            "readahead_mb": (
                round(c.readahead_bytes / (1 << 20), 1)
                if c.readahead_bytes is not None
                else None
            ),
            "adjustments": len(self.log),
        }
        if guard is not None:
            out["thresholds_ms"] = {
                "read_deadline_ms": _to_ms(guard.read_deadline),
                "open_deadline_ms": _to_ms(guard.open_deadline),
                "hedge_after_ms": _to_ms(guard.hedge_after),
            }
        if adjusted is not None:
            out["adjusted"] = adjusted
        return out

    # -- pool sizing from the bound-ness verdict -----------------------------

    def _step_pool(self, payload: Dict[str, Any]) -> None:
        pol = self.policy
        self._climber.hysteresis = pol.hysteresis
        self._climber.cooldown_s = pol.cooldown_s
        verdict = self._climber.observe(payload.get("verdict"))
        if verdict is None:
            return
        c = self.control
        workers = c.workers
        if verdict == "producer_bound":
            target = min(workers + 1, pol.max_workers, c.max_workers)
            reason = "producer_bound"
        else:
            target = max(workers - 1, pol.min_workers)
            reason = "consumer_bound"
        moved = self._adjust("workers", workers, target, reason, c.set_workers)
        # keep the queue deep enough to absorb the pool (and no deeper
        # than it needs to be when shrinking): bursty producers otherwise
        # immediately re-block on a too-shallow queue
        if c.prefetch is not None:
            want = max(pol.min_prefetch, min(target + 2, pol.max_prefetch))
            if (target > workers and want > c.prefetch) or (
                target < workers and want < c.prefetch
            ):
                moved |= self._adjust(
                    "prefetch", c.prefetch, want, reason, c.set_prefetch
                )
        if moved:
            self._climber.acted()

    # -- readahead from observed IO bandwidth --------------------------------

    def _step_readahead(self, payload: Dict[str, Any]) -> None:
        pol = self.policy
        c = self.control
        cur = c.readahead_bytes
        if cur is None or not cur:
            return  # readahead disabled: nothing to scale
        io = (payload.get("stages") or {}).get("read.io")
        if not io:
            return
        bps = io.get("bytes_per_sec") or 0.0
        if bps <= 0:
            return
        want = bps * pol.readahead_horizon_s
        want_mb = max(pol.min_readahead_mb, min(pol.max_readahead_mb, want / (1 << 20)))
        want_bytes = int(round(want_mb)) << 20
        lo = cur * (1.0 - pol.readahead_rel_band)
        hi = cur * (1.0 + pol.readahead_rel_band)
        if lo <= want_bytes <= hi:
            return
        self._adjust(
            "readahead_mb",
            round(cur / (1 << 20), 1),
            want_bytes >> 20,
            "read_io_bandwidth",
            lambda mb: c.set_readahead_bytes(int(mb) << 20),
        )

    # -- stall thresholds from observed latency quantiles --------------------

    def _step_thresholds(self, payload: Dict[str, Any]) -> None:
        guard = self.control.guard
        if guard is None:
            return
        pol = self.policy
        q = payload.get("quantiles") or {}

        def p99_ms(stage: str) -> Optional[float]:
            entry = q.get(stage)
            if not entry or entry.get("count", 0) < pol.min_latency_samples:
                return None
            return entry.get("p99_ms")

        read_p99 = p99_ms("read.io") or p99_ms("read")
        open_p99 = p99_ms("read.open")
        updates: Dict[str, float] = {}
        if read_p99 is not None:
            # deadlines are only ADAPTED, never introduced: a user who set
            # no read/open deadline opted out of raise-on-stall semantics,
            # and a derived deadline that false-positives would kill their
            # epoch. Hedging has no such failure mode (the losing side is
            # discarded, first byte-identical result wins), so it may be
            # introduced on observation alone.
            if guard.read_deadline is not None:
                updates["read_deadline_ms"] = _clamp(
                    pol.deadline_p99_mult * read_p99,
                    pol.min_deadline_ms,
                    pol.max_deadline_ms,
                )
            updates["hedge_after_ms"] = _clamp(
                pol.hedge_p99_mult * read_p99,
                pol.min_hedge_ms,
                pol.max_deadline_ms,
            )
        if open_p99 is not None and guard.open_deadline is not None:
            updates["open_deadline_ms"] = _clamp(
                pol.deadline_p99_mult * open_p99,
                pol.min_deadline_ms,
                pol.max_deadline_ms,
            )
        current = {
            "read_deadline_ms": _to_ms(guard.read_deadline),
            "open_deadline_ms": _to_ms(guard.open_deadline),
            "hedge_after_ms": _to_ms(guard.hedge_after),
        }
        apply_kw: Dict[str, float] = {}
        for knob, want in updates.items():
            cur = current[knob]
            if cur is not None and abs(want - cur) <= pol.threshold_rel_band * cur:
                continue  # within the no-twitch band
            apply_kw[knob] = want
        if not apply_kw:
            return

        def apply_one(knob):
            def _apply(v):
                guard.update_thresholds(**{knob: v})

            return _apply

        for knob, want in apply_kw.items():
            self._adjust(
                knob,
                round(current[knob], 1) if current[knob] is not None else None,
                round(want, 1),
                "observed_p99",
                apply_one(knob),
            )


def _to_ms(seconds: Optional[float]) -> Optional[float]:
    return round(seconds * 1000.0, 1) if seconds is not None else None


def _clamp(v: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, v))
