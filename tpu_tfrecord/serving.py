"""Overload-proof serving tier: continuous batching over LMStream (ISSUE 18).

PR 15 opened the inference path — one `LMStream`, one client, no failure
story. This module is the multiplexer that makes that path survive real
traffic: N concurrent clients share the ONE compiled per-tick step, and
the tier sheds load, honors deadlines, and degrades under chaos instead
of falling over.

Three layers, separable for tests:

- :class:`ServingEngine` — the continuous-batching scheduler. Each tick
  packs up to ``mb`` schedulable requests into one microbatch
  (`models.lm.pack_slots`), pushes it through the stream with a host-side
  slot tag (`LMStream.submit_tagged` — the tag never enters the compiled
  step), and settles whatever popped: greedy argmax on the last position,
  slide the window, reschedule or finish. A finishing / expiring /
  disconnecting request frees its slot for the very next tick — no batch
  drain. Admission is a bounded queue with LOUD rejection
  (``serve.rejected`` + a Retry-After hint) and per-request deadlines are
  enforced at admission AND at every tick (an expired in-flight request
  is dropped and counted ``serve.deadline_expired`` — never silently
  served late). Because every model op is batch-row independent (the
  per-slot isolation pin in tests/test_pipeline_stream.py), the bytes a
  request receives are EXACTLY the bytes a solo sequential run produces
  (:func:`sequential_reference`), no matter what shares its microbatch.

- :class:`ServeServer` / :class:`ServeClient` — the socket tier on the
  data service's wire protocol (`service_protocol` framing). Each
  connection gets a reader and a writer thread with a bounded outbound
  queue, so a SLOW client blocks only its own writer, never the engine
  tick; a disconnecting client cancels its live requests (slots free
  next tick, neighbors' bytes untouched, ``serve.disconnects``). The
  client walks a replica list (connection failure rotates — the
  SIGKILLed-replica story) and treats "overloaded"/"draining" replies
  with the `retry.py` policy vocabulary: capped exponential backoff with
  the server's Retry-After hint as the floor.

- chaos — op="serve" rules on the shared replayable FaultPlan ledger
  (`faults.apply_serve`): ``slow_client`` stalls one reply seam,
  ``client_disconnect`` drops a connection mid-generation, ``burst``
  tells an open-loop load generator to over-admit. The server consults
  the plan installed by ``faults.install_chaos`` (or one passed
  explicitly) at its ``reply:<peer>``/``recv:<peer>`` seams.

Telemetry rides the PR 7/13 spool: per-request latency
(``serve.latency`` histogram → fleet-exact p50/p99), queue depth and
in-flight gauges, and the shed counters, so ``tfrecord_doctor serve``
can give a latency-SLO verdict (`telemetry.serving_verdict`) and
``elastic.ServingScaler`` can scale replicas on queue-depth/p99.

Deadline and latency math goes through the injectable ``clock`` seam
(graftlint clock-discipline covers this module).
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import signal
import socket
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_tfrecord import faults as _faults
from tpu_tfrecord import retry as _retry
from tpu_tfrecord import service_protocol as sp
from tpu_tfrecord import telemetry as _telemetry
from tpu_tfrecord.metrics import METRICS, logger

__all__ = [
    "ServePolicy",
    "ServeRejected",
    "DeadlineExpired",
    "ServingEngine",
    "ServeServer",
    "ServeClient",
    "sequential_reference",
    "run_server",
    "main",
]


class ServeRejected(RuntimeError):
    """Admission refused the request (queue full or replica draining).
    Retriable: ``retry_after_s`` is the server's hint — the client-side
    backoff floor, exactly the Retry-After vocabulary httpfs honors."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before its last token — at
    admission, in the queue, or mid-generation. NOT retriable as-is (the
    answer would still be late); the caller owns the next move."""


@dataclass(frozen=True)
class ServePolicy:
    """Admission/scheduling knobs for one serving replica.

    ``mb`` is the microbatch row count — the slot count of the ONE
    compiled per-tick step (a different mb is a different program; pick
    it at startup). ``max_queue`` bounds requests admitted but not yet
    generating; the ``max_queue+1``-th concurrent arrival is shed with
    ``retry_after_s`` scaled by queue pressure. ``default_deadline_s``
    applies to requests that carry none (None = no deadline).
    ``slo_p99_ms`` is the target `telemetry.serving_verdict` and the
    scaler judge against."""

    mb: int = 4
    max_queue: int = 16
    default_deadline_s: Optional[float] = None
    retry_after_s: float = 0.05
    slo_p99_ms: float = 250.0

    def __post_init__(self) -> None:
        if self.mb < 1:
            raise ValueError(f"mb must be >= 1, got {self.mb}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.retry_after_s < 0:
            raise ValueError("retry_after_s must be >= 0")

    def hint(self, queue_depth: int) -> float:
        """Retry-After for a rejection observed at ``queue_depth``: the
        base hint scaled by how far over capacity the queue is —
        deterministic (no jitter server-side; the CLIENT's RetryPolicy
        owns jitter, so synchronized clients still spread out)."""
        return self.retry_after_s * (1.0 + queue_depth / max(1, self.mb))


class _Request:
    """One admitted generation request: its sliding window, its budget,
    and its completion latch. State transitions happen on the engine
    thread; ``cancel`` may flip the flag from a connection thread — the
    engine observes it at the next pack/settle and frees the slot."""

    __slots__ = (
        "rid", "window", "n_new", "out", "deadline", "birth",
        "cancelled", "done", "status", "on_done",
        "trace_id", "span_id", "parent_span_id", "first_pack",
    )

    def __init__(self, rid, window, n_new, deadline, birth, on_done=None,
                 ctx: Optional[_telemetry.TraceContext] = None):
        self.rid = rid
        self.window = window  # np [L] int32, slides as tokens generate
        self.n_new = n_new
        self.out: List[int] = []
        self.deadline = deadline  # absolute clock() time, or None
        self.birth = birth
        self.cancelled = False
        self.done = threading.Event()
        self.status: Optional[str] = None  # "ok"|"deadline_expired"|"cancelled"
        self.on_done = on_done
        # request-scoped trace identity: the span the client minted for
        # THIS request (or a locally minted child) — the serve.request
        # root span records under these ids, and the latency exemplar
        # points at them
        self.trace_id = ctx.trace_id if ctx is not None else ""
        self.span_id = ctx.span_id if ctx is not None else ""
        self.parent_span_id = (
            ctx.parent_span_id if ctx is not None else None
        )
        self.first_pack: Optional[float] = None  # engine clock, first slot claim

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request settles; the generated tokens, or the
        loud failure (`DeadlineExpired` / `ServeRejected` on cancel)."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.rid} still in flight")
        if self.status == "ok":
            return list(self.out)
        if self.status == "deadline_expired":
            raise DeadlineExpired(f"request {self.rid} missed its deadline")
        raise ServeRejected(f"request {self.rid} {self.status}")


#: Synthetic Chrome-trace lane base for per-request spans: concurrent
#: requests render as parallel tracks in Perfetto instead of overlapping
#: X events on the engine thread's track. Lanes recycle mod 512 — far
#: wider than any real in-flight set.
_REQUEST_LANE_BASE = 1 << 22


def _request_lane(rid: int) -> int:
    return _REQUEST_LANE_BASE + rid % 512


def _request_context(trace: Any) -> _telemetry.TraceContext:
    """The request's trace identity: the TraceContext the client stamped
    into the wire message (already a per-request child — ids propagate),
    or a locally minted child of this process's context for direct
    ``submit`` callers. A malformed wire payload degrades to the local
    child — tracing never rejects a request."""
    if isinstance(trace, _telemetry.TraceContext):
        return trace
    if isinstance(trace, dict):
        try:
            ctx = _telemetry.TraceContext.from_json(trace)
            if ctx.trace_id and ctx.span_id:
                return ctx
        except (TypeError, ValueError):
            pass
    return _telemetry.current_context().child("serve.request")


class ServingEngine:
    """The continuous-batching request multiplexer over one `LMStream`.

    Thread model: any thread may ``submit``/``cancel``; exactly ONE
    thread (the engine loop, or a test calling ``step`` directly) drives
    the stream. Two queues feed the packer — ``_cont`` (requests whose
    previous step popped: they keep generating, priority) and ``_ready``
    (admitted, not yet started: the bounded admission queue) — so a
    finishing slot refills from ``_ready`` on the very next tick while
    in-progress requests never starve behind new arrivals."""

    def __init__(
        self,
        params,
        cfg,
        mesh,
        pipe_axis: str = "pipe",
        policy: Optional[ServePolicy] = None,
        metrics=METRICS,
        clock: Callable[[], float] = time.monotonic,
    ):
        from tpu_tfrecord.models import lm as _lm

        self._lm = _lm
        self.cfg = cfg
        self.policy = policy or ServePolicy()
        self.stream = _lm.LMStream(params, cfg, mesh, pipe_axis=pipe_axis)
        self._metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._ready: collections.deque = collections.deque()
        self._cont: collections.deque = collections.deque()
        self._packed = 0  # requests riding microbatches not yet popped
        self._draining = False
        self._stop = False
        self._next_rid = 0
        self._thread: Optional[threading.Thread] = None

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        window,
        n_new: int,
        deadline_s: Optional[float] = None,
        on_done: Optional[Callable[["_Request"], None]] = None,
        trace: Any = None,
    ) -> _Request:
        """Admit one generation request (``window`` [L] int32, generate
        ``n_new`` tokens greedily) or refuse it LOUDLY: `ServeRejected`
        when the queue is at ``max_queue`` or the replica is draining
        (with a Retry-After hint), `DeadlineExpired` when the deadline is
        already unmeetable at admission. Never silently queues past
        either bound.

        ``trace`` is the request's trace identity — a TraceContext (or
        its ``to_json`` dict, as shipped over the wire by `ServeClient`);
        the ``serve.request`` root span and its children record under
        those ids, and a shed/expiry at admission lands a ``serve.shed``/
        ``serve.deadline_expired`` instant carrying the same trace id so
        a refused request is still attributable in the merged timeline."""
        window = np.asarray(window, dtype=np.int32)
        if window.shape != (self.cfg.max_len,):
            raise ValueError(
                f"window shape {window.shape} != ({self.cfg.max_len},)"
            )
        if n_new < 1:
            raise ValueError(f"n_new must be >= 1, got {n_new}")
        tracing = _telemetry.RECORDER.enabled
        ctx = _request_context(trace) if (tracing or trace is not None) else None
        now = self._clock()
        if deadline_s is None:
            deadline_s = self.policy.default_deadline_s
        deadline = None if deadline_s is None else now + deadline_s
        with self._cv:
            if self._draining or self._stop:
                if tracing:
                    _telemetry.record_instant(
                        "serve.shed", int(now * 1e9),
                        reason="draining",
                        trace_id=ctx.trace_id, span_id=ctx.span_id,
                    )
                raise ServeRejected(
                    "replica draining", self.policy.hint(len(self._ready))
                )
            if deadline is not None and deadline <= now:
                self._metrics.count("serve.deadline_expired")
                if tracing:
                    _telemetry.record_instant(
                        "serve.deadline_expired", int(now * 1e9),
                        at="admission",
                        trace_id=ctx.trace_id, span_id=ctx.span_id,
                    )
                raise DeadlineExpired("deadline expired at admission")
            if len(self._ready) >= self.policy.max_queue:
                self._metrics.count("serve.rejected")
                if tracing:
                    _telemetry.record_instant(
                        "serve.shed", int(now * 1e9),
                        reason="queue_full",
                        queue_depth=len(self._ready),
                        trace_id=ctx.trace_id, span_id=ctx.span_id,
                    )
                raise ServeRejected(
                    f"queue full ({self.policy.max_queue})",
                    self.policy.hint(len(self._ready)),
                )
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, window, int(n_new), deadline, now, on_done, ctx=ctx
            )
            self._ready.append(req)
            self._metrics.gauge("serve.queue_depth", float(len(self._ready)))
            self._cv.notify_all()
        return req

    def cancel(self, req: _Request) -> None:
        """Client-side abandonment (disconnect): the request's slot frees
        at the engine's next pack/settle without touching any other
        slot's bytes. Idempotent; completed requests are unaffected."""
        req.cancelled = True
        with self._cv:
            self._cv.notify_all()

    # -- completion paths (engine thread) ------------------------------------

    def _finish(self, req: _Request, status: str, now: float) -> None:
        req.status = status
        if status == "ok":
            self._metrics.count("serve.requests")
            exemplar = (
                (req.trace_id, req.span_id) if req.trace_id else None
            )
            self._metrics.observe(
                "serve.latency", now - req.birth, exemplar=exemplar
            )
            # the latency decomposition the bench probe reads: time spent
            # waiting for a slot vs time being served (first pack ->
            # completion). Both on the engine clock, both exemplar-tagged.
            if req.first_pack is not None:
                self._metrics.observe(
                    "serve.queue_wait", req.first_pack - req.birth,
                    exemplar=exemplar,
                )
                self._metrics.observe(
                    "serve.service", now - req.first_pack,
                    exemplar=exemplar,
                )
        elif status == "deadline_expired":
            self._metrics.count("serve.deadline_expired")
            if _telemetry.RECORDER.enabled and req.trace_id:
                _telemetry.record_instant(
                    "serve.deadline_expired", int(now * 1e9),
                    tid=_request_lane(req.rid), at="tick", rid=req.rid,
                    trace_id=req.trace_id, span_id=req.span_id,
                )
        if _telemetry.RECORDER.enabled and req.trace_id:
            # THE request root span: admission -> completion on the
            # engine's own (injectable) clock, so its duration equals the
            # serve.latency observation exactly. span_id is the id the
            # client minted — the client's spool and this replica's spool
            # merge into one causal timeline per request.
            _telemetry.record_span(
                "serve.request", int(req.birth * 1e9),
                int((now - req.birth) * 1e9),
                tid=_request_lane(req.rid),
                rid=req.rid, status=status, n_new=req.n_new,
                trace_id=req.trace_id, span_id=req.span_id,
                parent_span_id=req.parent_span_id,
            )
        req.done.set()
        if req.on_done is not None:
            try:
                req.on_done(req)
            except Exception:  # noqa: BLE001  # graftlint: swallow(counted serve.errors on the injected registry; a reply callback must never take the engine tick down)
                self._metrics.count("serve.errors")
                logger.exception(
                    "tfrecord.serving on_done callback failed (rid=%d)",
                    req.rid,
                )

    # -- the tick ------------------------------------------------------------

    def _pack(self, now: float) -> List[_Request]:
        """Pop up to ``mb`` schedulable requests (continuations first),
        enforcing deadlines and cancellations as slots are claimed — an
        expired or abandoned request never occupies a slot."""
        slots: List[_Request] = []
        with self._cv:
            for q in (self._cont, self._ready):
                while q and len(slots) < self.policy.mb:
                    req = q.popleft()
                    if req.cancelled:
                        self._finish(req, "cancelled", now)
                        continue
                    if req.deadline is not None and now > req.deadline:
                        self._finish(req, "deadline_expired", now)
                        continue
                    if req.first_pack is None:
                        req.first_pack = now
                        if _telemetry.RECORDER.enabled and req.trace_id:
                            # queue_wait closes the moment the request
                            # first claims a slot: admission -> first pack
                            _telemetry.record_span(
                                "serve.queue_wait", int(req.birth * 1e9),
                                int((now - req.birth) * 1e9),
                                tid=_request_lane(req.rid), rid=req.rid,
                                trace_id=req.trace_id,
                                parent_span_id=req.span_id,
                            )
                    slots.append(req)
            self._packed += len(slots)
            self._metrics.gauge("serve.queue_depth", float(len(self._ready)))
            self._metrics.gauge(
                "serve.in_flight", float(self._packed)
            )
        return slots

    def _settle(self, outs: List[Tuple[np.ndarray, Any]]) -> None:
        """Fold popped microbatches back into request state: one greedy
        token per valid slot, then finish or reschedule. Deadlines are
        re-checked HERE too — an in-flight request whose deadline passed
        while its microbatch was in the pipeline frees its slot now and
        is never served late."""
        for logits, tag in outs:
            if not tag:
                continue  # idle-advance microbatch: no valid slots
            now = self._clock()
            cont: List[_Request] = []
            for row, req in enumerate(tag):
                if req.cancelled:
                    self._finish(req, "cancelled", now)
                    continue
                if req.deadline is not None and now > req.deadline:
                    self._finish(req, "deadline_expired", now)
                    continue
                nxt = int(np.argmax(logits[row, -1]))
                req.out.append(nxt)
                if len(req.out) >= req.n_new:
                    self._finish(req, "ok", now)
                else:
                    req.window = np.concatenate(
                        [req.window[1:], [np.int32(nxt)]]
                    ).astype(np.int32)
                    cont.append(req)
            with self._cv:
                self._packed -= len(tag)
                self._cont.extend(cont)
                self._metrics.gauge(
                    "serve.in_flight", float(self._packed)
                )
                self._cv.notify_all()

    def step(self) -> int:
        """One scheduler tick: pack → push → settle. Returns the number
        of slots packed (0 with an idle-advance push still counts the
        in-flight work via the return of 1), or 0 when fully idle."""
        now = self._clock()
        slots = self._pack(now)
        if not slots:
            with self._cv:
                packed = self._packed
            if packed == 0:
                return 0
            # nothing schedulable but microbatches are in the pipeline:
            # advance one tick with an all-invalid microbatch (empty tag)
            # rather than draining — the no-drain half of continuous
            # batching: a continuation popping next tick gets its slot
            # back immediately
            tokens = self._lm.pack_slots([], self.policy.mb, self.cfg.max_len)
            self._settle(self.stream.submit_tagged(tokens, ()))
            return 1
        tokens = self._lm.pack_slots(
            [r.window for r in slots], self.policy.mb, self.cfg.max_len
        )
        self._metrics.count("serve.ticks")
        self._settle(self.stream.submit_tagged(tokens, tuple(slots)))
        if _telemetry.RECORDER.enabled:
            # one serve.tick slice per occupied slot, attributed to
            # slot + request id and parented under the request span —
            # the per-request timeline shows exactly which ticks (and
            # which slot) served it
            end = self._clock()
            t0_ns = int(now * 1e9)
            dur_ns = max(0, int((end - now) * 1e9))
            for row, req in enumerate(slots):
                if not req.trace_id:
                    continue
                _telemetry.record_span(
                    "serve.tick", t0_ns, dur_ns,
                    tid=_request_lane(req.rid),
                    slot=row, rid=req.rid,
                    trace_id=req.trace_id, parent_span_id=req.span_id,
                )
        return len(slots)

    def run_until_idle(self) -> None:
        """Drive ticks until no request is queued, continuing, or in
        flight — the synchronous mode tests and the bench probe use."""
        while self.step() > 0:
            pass

    # -- engine loop ---------------------------------------------------------

    def start(self) -> "ServingEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tfr-serving-engine", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            with self._cv:
                while (
                    not self._stop
                    and not self._ready
                    and not self._cont
                    and self._packed == 0
                ):
                    if self._draining:
                        self._stop = True
                        self._cv.notify_all()
                        break
                    self._cv.wait(0.05)
                if self._stop and not self._ready and not self._cont and not self._packed:
                    return
            try:
                self.step()
            except Exception:  # noqa: BLE001  # graftlint: swallow(counted serve.errors on the injected registry; a poisoned tick stops the loop loudly instead of spinning)
                self._metrics.count("serve.errors")
                logger.exception("tfrecord.serving engine tick failed")
                with self._cv:
                    self._stop = True
                    self._cv.notify_all()
                return

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish every in-flight and queued request,
        then stop the loop — the goodbye half of scale-down and of
        graceful signal shutdown. Returns True when fully drained."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        if self._thread is None:
            self.run_until_idle()
            with self._cv:
                self._stop = True
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Hard stop: the loop exits after the current tick; queued
        requests are cancelled (their waiters unblock loudly)."""
        with self._cv:
            self._stop = True
            pending = list(self._cont) + list(self._ready)
            self._cont.clear()
            self._ready.clear()
            self._cv.notify_all()
        now = self._clock()
        for req in pending:
            self._finish(req, "cancelled", now)
        if self._thread is not None:
            self._thread.join(5.0)

    # -- introspection -------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The status surface the server's ``status`` op, the doctor, and
        the scaler read: queue/in-flight depth, shed counters, per-request
        p50/p99 (ms), and the `telemetry.serving_verdict`."""
        with self._cv:
            queue_depth = len(self._ready)
            in_flight = self._packed + len(self._cont)
            draining = self._draining
        q = self._metrics.quantiles("serve.latency").get("serve.latency", {})
        p50 = q.get("p50_s")
        p99 = q.get("p99_s")
        p50_ms = None if p50 is None else p50 * 1e3
        p99_ms = None if p99 is None else p99 * 1e3
        qw = self._metrics.quantiles("serve.queue_wait").get(
            "serve.queue_wait", {}
        )
        sv = self._metrics.quantiles("serve.service").get("serve.service", {})
        qw99 = qw.get("p99_s")
        sv99 = sv.get("p99_s")
        return {
            "role": "serving",
            "draining": draining,
            "queue_depth": queue_depth,
            "in_flight": in_flight,
            "mb": self.policy.mb,
            "max_queue": self.policy.max_queue,
            "slo_p99_ms": self.policy.slo_p99_ms,
            "p50_ms": p50_ms,
            "p99_ms": p99_ms,
            "queue_wait_p99_ms": None if qw99 is None else qw99 * 1e3,
            "service_p99_ms": None if sv99 is None else sv99 * 1e3,
            "completed": q.get("count", 0),
            "counters": {
                name: self._metrics.counter(name)
                for name in (
                    "serve.requests",
                    "serve.rejected",
                    "serve.deadline_expired",
                    "serve.disconnects",
                )
            },
            "verdict": _telemetry.serving_verdict(
                p99_ms, queue_depth, self.policy.slo_p99_ms,
                max_queue=self.policy.max_queue,
            ),
        }


def sequential_reference(
    params, cfg, mesh, requests: Sequence[Tuple[Any, int]],
    mb: int, pipe_axis: str = "pipe",
) -> List[List[int]]:
    """Each ``(window, n_new)`` run SOLO — one request per microbatch,
    flushed to completion before the next — through the same pack/argmax/
    slide loop the engine runs. THE parity oracle: N concurrent clients
    through one server must produce exactly these bytes (the per-slot
    isolation pin makes slot position and neighbors irrelevant)."""
    from tpu_tfrecord.models import lm as _lm

    stream = _lm.LMStream(params, cfg, mesh, pipe_axis=pipe_axis)
    results: List[List[int]] = []
    for window, n_new in requests:
        w = np.asarray(window, dtype=np.int32)
        toks: List[int] = []
        for _ in range(int(n_new)):
            outs = stream.submit_tagged(_lm.pack_slots([w], mb, cfg.max_len))
            outs += stream.flush_tagged()
            logits = outs[-1][0]
            nxt = int(np.argmax(logits[0, -1]))
            toks.append(nxt)
            w = np.concatenate([w[1:], [np.int32(nxt)]]).astype(np.int32)
        results.append(toks)
    return results


# ---------------------------------------------------------------------------
# Socket tier
# ---------------------------------------------------------------------------


class _Conn:
    """One accepted client connection: a bounded outbound queue drained
    by a dedicated writer thread, so one slow or dead client can only
    ever block ITSELF. Replies outrunning a stuck client past
    ``max_outbound`` drop the connection (counted as a disconnect) —
    bounded memory beats an unbounded buffer for a client that stopped
    reading."""

    def __init__(self, sock: socket.socket, peer: str, max_outbound: int):
        self.sock = sock
        self.peer = peer
        self.max_outbound = max_outbound
        self.outbound: collections.deque = collections.deque()
        self.cv = threading.Condition()
        self.closed = False
        self.live: Dict[int, _Request] = {}  # client req id -> engine request

    def enqueue(self, msg: Dict[str, Any]) -> None:
        with self.cv:
            if self.closed:
                return
            if len(self.outbound) >= self.max_outbound:
                self.closed = True
                self.cv.notify_all()
                return
            self.outbound.append(msg)
            self.cv.notify_all()

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()
        try:
            self.sock.close()
        except OSError:
            pass


class ServeServer:
    """The serving replica: accepts connections on the service wire
    protocol and multiplexes their generation requests through one
    :class:`ServingEngine`.

    Ops: ``generate`` (tokens window + n_new + optional deadline_s),
    ``status`` (the engine report — what the scaler's census and
    ``tfrecord_doctor serve --probe`` read), ``drain`` (stop admitting,
    finish in-flight, goodbye), ``ping``. Chaos: the plan passed here (or
    installed via ``faults.install_chaos``) is consulted at every
    ``recv:<peer>`` and ``reply:<peer>`` seam — ``slow_client`` stalls
    one writer, ``client_disconnect`` drops one connection; either way
    the engine tick never blocks and neighbors' bytes never change."""

    def __init__(
        self,
        engine: ServingEngine,
        host: str = "127.0.0.1",
        port: int = 0,
        fault_plan: Optional[_faults.FaultPlan] = None,
        max_outbound: int = 256,
        timeout_s: float = 30.0,
    ):
        self.engine = engine
        self._plan = fault_plan
        self._max_outbound = max_outbound
        self._timeout_s = timeout_s
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.addr = sp.format_addr(host, self._sock.getsockname()[1])
        self._conns: List[_Conn] = []
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self.drained = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def _chaos(self) -> Optional[_faults.FaultPlan]:
        return self._plan if self._plan is not None else _faults._SERVE_CHAOS

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeServer":
        self.engine.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tfr-serving-accept", daemon=True
        )
        self._accept_thread.start()
        logger.info("tfrecord.serving replica listening on %s", self.addr)
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish every in-flight request, then stop —
        scale-down's goodbye and the SIGTERM path. Idempotent."""
        ok = self.engine.drain(timeout)
        self.stop()
        if ok:
            self.drained.set()
        return ok

    def stop(self) -> None:
        if self._stopping.is_set():
            return
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close()
        self.engine.stop()

    # -- accept / per-connection ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, peer = self._sock.accept()
            except OSError:
                return  # listener closed: shutdown
            sp.enable_nodelay(sock)
            sock.settimeout(self._timeout_s)
            conn = _Conn(
                sock, sp.format_addr(peer[0], peer[1]), self._max_outbound
            )
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._read_loop, args=(conn,),
                name=f"tfr-serving-read-{conn.peer}", daemon=True,
            ).start()
            threading.Thread(
                target=self._write_loop, args=(conn,),
                name=f"tfr-serving-write-{conn.peer}", daemon=True,
            ).start()

    def _drop(self, conn: _Conn) -> None:
        """Connection teardown: cancel the client's live requests (their
        slots free at the engine's next tick) and count the mid-request
        loss once."""
        with conn.cv:
            live = list(conn.live.values())
            conn.live.clear()
        if live and any(not r.done.is_set() for r in live):
            self.engine._metrics.count("serve.disconnects")
        for req in live:
            self.engine.cancel(req)
        conn.close()
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def _read_loop(self, conn: _Conn) -> None:
        try:
            while not conn.closed:
                plan = self._chaos()
                if plan is not None:
                    plan.apply_serve(f"recv:{conn.peer}", sock=conn.sock)
                msg = sp.recv_msg(conn.sock, conn.peer, allow_eof=True)
                if msg is None:
                    break
                self._handle(conn, msg)
        except (OSError, ConnectionError):
            pass
        finally:
            self._drop(conn)

    def _write_loop(self, conn: _Conn) -> None:
        try:
            while True:
                with conn.cv:
                    while not conn.outbound and not conn.closed:
                        conn.cv.wait(0.5)
                    if conn.closed and not conn.outbound:
                        return
                    msg = conn.outbound.popleft()
                plan = self._chaos()
                if plan is not None:
                    # the slow/dead-client seam: a slow_client stall here
                    # blocks only THIS writer thread; client_disconnect
                    # closes the socket and unwinds to _drop
                    plan.apply_serve(f"reply:{conn.peer}", sock=conn.sock)
                sp.send_msg(conn.sock, msg)
        except (OSError, ConnectionError):
            pass
        finally:
            self._drop(conn)

    # -- request handling ----------------------------------------------------

    def _handle(self, conn: _Conn, msg: Dict[str, Any]) -> None:
        ver = msg.get("v", sp.PROTO_VERSION)
        if ver != sp.PROTO_VERSION:
            conn.enqueue({
                "ok": False, "error": "version_skew",
                "v": sp.PROTO_VERSION, "req": msg.get("req"),
            })
            return
        op = msg.get("op")
        if op == "ping":
            conn.enqueue({"ok": True, "req": msg.get("req")})
        elif op == "status":
            rep = dict(self.engine.report(), addr=self.addr, pid=os.getpid())
            conn.enqueue(dict(rep, ok=True, req=msg.get("req")))
        elif op == "drain":
            conn.enqueue({"ok": True, "draining": True, "req": msg.get("req")})
            threading.Thread(
                target=self.drain, name="tfr-serving-drain", daemon=True
            ).start()
        elif op == "generate":
            self._generate(conn, msg)
        else:
            conn.enqueue({
                "ok": False, "error": f"unknown op {op!r}",
                "req": msg.get("req"),
            })

    def _generate(self, conn: _Conn, msg: Dict[str, Any]) -> None:
        cid = msg.get("req")

        def on_done(req: _Request) -> None:
            with conn.cv:
                conn.live.pop(cid, None)
            if req.status == "ok":
                conn.enqueue({"ok": True, "req": cid, "tokens": req.out})
            elif req.status == "deadline_expired":
                conn.enqueue({
                    "ok": False, "req": cid, "error": "deadline_expired",
                })
            # cancelled: the connection is gone — nothing to send

        try:
            req = self.engine.submit(
                np.asarray(msg["tokens"], dtype=np.int32),
                int(msg["n_new"]),
                deadline_s=msg.get("deadline_s"),
                on_done=on_done,
                trace=msg.get("trace"),
            )
        except ServeRejected as e:
            conn.enqueue({
                "ok": False, "req": cid, "error": "overloaded",
                "retry_after_s": e.retry_after_s,
            })
            return
        except DeadlineExpired:
            conn.enqueue({
                "ok": False, "req": cid, "error": "deadline_expired",
            })
            return
        except (KeyError, ValueError, TypeError) as e:
            conn.enqueue({"ok": False, "req": cid, "error": f"bad request: {e}"})
            return
        with conn.cv:
            conn.live[cid] = req


class ServeClient:
    """Replica-walking client on the service wire protocol, speaking the
    `retry.py` vocabulary: an "overloaded" reply backs off with the
    server's Retry-After hint as the FLOOR under the policy's capped
    exponential (full jitter client-side — synchronized rejects don't
    re-arrive in lockstep); a dead replica (connection error) rotates to
    the next address, which is how a SIGKILLed replica's queue drains
    through the survivor."""

    def __init__(
        self,
        addrs: Sequence[str],
        policy: Optional[_retry.RetryPolicy] = None,
        timeout_s: float = 30.0,
    ):
        if not addrs:
            raise ValueError("ServeClient needs at least one replica addr")
        self._addrs = list(addrs)
        self._i = 0
        self._sock: Optional[socket.socket] = None
        self._timeout_s = timeout_s
        self.policy = policy or _retry.RetryPolicy(
            max_retries=8, base_delay=0.05, max_delay=2.0
        )
        self._next_req = 0

    @property
    def addr(self) -> str:
        return self._addrs[self._i % len(self._addrs)]

    def _rotate(self) -> None:
        self._close()
        self._i += 1

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _connected(self) -> socket.socket:
        if self._sock is None:
            self._sock = sp.connect(self.addr, timeout=self._timeout_s)
        return self._sock

    def _request(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """One round trip with rotation on connection failure — every
        replica tried once per attempt before the attempt is charged."""
        attempt, start = 0, self.policy.clock()
        while True:
            for _ in range(len(self._addrs)):
                try:
                    return sp.request(self._connected(), self.addr, obj)
                except (OSError, ConnectionError):
                    self._rotate()
            attempt += 1
            if not self.policy.pause(attempt, start):
                raise ConnectionError(
                    f"no serving replica reachable ({self._addrs})"
                )

    def generate(
        self,
        window,
        n_new: int,
        deadline_s: Optional[float] = None,
    ) -> List[int]:
        """One generation request, retried through overload sheds and
        replica deaths under the client's RetryPolicy budget. Raises
        `DeadlineExpired` (not retriable — late is late), `ServeRejected`
        when the budget exhausts against a saturated fleet."""
        self._next_req += 1
        # one per-request trace child rides the wire: the replica records
        # its serve.request root span under THIS span id (parented to the
        # client's process root), so client + replica spools merge into
        # one causal timeline per request. Extra message keys are
        # protocol-legal; an old server ignores it.
        ctx = _telemetry.current_context().child("serve.request")
        obj = {
            "v": sp.PROTO_VERSION,
            "op": "generate",
            "req": self._next_req,
            "tokens": np.asarray(window, dtype=np.int32).tolist(),
            "n_new": int(n_new),
            "deadline_s": deadline_s,
            "trace": ctx.to_json(),
        }
        attempt, start = 0, self.policy.clock()
        while True:
            rep = self._request(obj)
            if rep.get("ok"):
                return [int(t) for t in rep["tokens"]]
            err = rep.get("error")
            if err == "deadline_expired":
                raise DeadlineExpired("server reported deadline_expired")
            if err in ("overloaded", "draining"):
                hint = float(rep.get("retry_after_s", 0.0))
                if err == "draining":
                    self._rotate()  # this replica is saying goodbye
                attempt += 1
                if not self.policy.pause(attempt, start):
                    raise ServeRejected(
                        f"rejected after {attempt} attempts: {err}", hint
                    )
                if hint > 0:
                    # the Retry-After floor under the policy's jittered
                    # backoff (pause already slept the jittered part)
                    self.policy.sleep(hint)
                continue
            raise sp.ProtocolError(f"serving replica error: {rep!r}")

    def status(self) -> Dict[str, Any]:
        self._next_req += 1
        return self._request(
            {"v": sp.PROTO_VERSION, "op": "status", "req": self._next_req}
        )

    def drain(self) -> Dict[str, Any]:
        self._next_req += 1
        return self._request(
            {"v": sp.PROTO_VERSION, "op": "drain", "req": self._next_req}
        )

    def close(self) -> None:
        self._close()


# ---------------------------------------------------------------------------
# Process harness: signals, spool, CLI (the scaler's spawn target)
# ---------------------------------------------------------------------------


def run_server(
    server: ServeServer,
    spool_dir: Optional[str] = None,
    role: str = "serving",
    install_signals: bool = True,
    ready_fh=None,
    trace_out: Optional[str] = None,
) -> int:
    """Run a started server to completion: optionally announce readiness
    (one JSON line: addr + pid), land per-request telemetry on the fleet
    spool, and on SIGTERM/SIGINT drain gracefully — stop admitting,
    finish in-flight requests, write the spool's ``final: true`` snapshot
    — then return 0. The scaler's drain RPC takes the same exit path.
    ``trace_out`` turns the flight recorder on for the process lifetime
    and saves the replica's Chrome trace (per-request ``serve.request``
    timelines) there on exit — `tfrecord_doctor merge-trace` fuses it
    with client-side traces."""
    from tpu_tfrecord import fleet as _fleet

    if trace_out:
        _telemetry.current_context()  # adopt an identity for the track label
        _telemetry.enable()
    spool = None
    if spool_dir:
        spool = _fleet.acquire_spool(spool_dir, role=role, interval_s=0.2)
    stop = threading.Event()

    if install_signals:
        def _on_signal(signum, frame):
            logger.info(
                "tfrecord.serving got signal %d: draining", signum
            )
            threading.Thread(
                target=server.drain, name="tfr-serving-sigdrain", daemon=True
            ).start()
            stop.set()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    if ready_fh is not None:
        ready_fh.write(
            json.dumps({"addr": server.addr, "pid": os.getpid()}) + "\n"
        )
        ready_fh.flush()
    try:
        while not server.drained.wait(0.1):
            if server._stopping.is_set():
                break
        # the drain already finished every admitted request; give the
        # writer threads a beat to flush final replies before teardown
        server.stop()
    finally:
        if spool is not None:
            _fleet.release_spool(spool_dir)
        if trace_out:
            try:
                _telemetry.RECORDER.save_chrome_trace(trace_out)
            except OSError:
                logger.exception(
                    "tfrecord.serving could not save trace to %s", trace_out
                )
            _telemetry.disable()
    return 0


def _build_synthetic(args) -> Tuple[Any, Any, Any]:
    """A tiny seeded LM + CPU pipe mesh for subprocess scenarios (tests,
    verify.sh, the scaler's default spawn): same seed => same params =>
    the client can compute the byte-exact sequential reference locally."""
    import jax
    from jax.sharding import Mesh

    from tpu_tfrecord.models import lm as _lm

    cfg = _lm.LMConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, max_len=args.max_len,
        n_micro=args.mb, n_virtual=args.virtual,
    )
    params = _lm.init_params(jax.random.key(args.seed), cfg)
    devs = np.array(jax.devices()[: args.stages])
    if len(devs) < args.stages:
        raise SystemExit(
            f"need {args.stages} devices for the pipe mesh, have {len(devs)}"
            " (set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    return params, cfg, Mesh(devs, ("pipe",))


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m tpu_tfrecord.serving`` — a synthetic-model serving
    replica for chaos/scale scenarios. Prints one ready line (JSON: addr,
    pid) on stdout, serves until drained (drain RPC or SIGTERM/SIGINT),
    exits 0 after the final spool snapshot."""
    p = argparse.ArgumentParser(prog="tpu_tfrecord.serving", description=main.__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--mb", type=int, default=4)
    p.add_argument("--max-queue", type=int, default=16)
    p.add_argument("--default-deadline-s", type=float, default=None)
    p.add_argument("--slo-p99-ms", type=float, default=250.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--virtual", type=int, default=1)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=32)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--vocab", type=int, default=96)
    p.add_argument("--max-len", type=int, default=16)
    p.add_argument("--spool-dir", default=None)
    p.add_argument("--role", default="serving")
    p.add_argument("--fault-plan", default=None,
                   help="path to a FaultPlan JSON (op='serve' rules)")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="record per-request spans and save the Chrome "
                   "trace here on exit (merge-trace fuses it with client "
                   "traces)")
    args = p.parse_args(argv)

    params, cfg, mesh = _build_synthetic(args)
    policy = ServePolicy(
        mb=args.mb, max_queue=args.max_queue,
        default_deadline_s=args.default_deadline_s,
        slo_p99_ms=args.slo_p99_ms,
    )
    plan = None
    if args.fault_plan:
        with open(args.fault_plan, "r", encoding="utf-8") as fh:
            plan = _faults.FaultPlan.from_json(fh.read())
    engine = ServingEngine(params, cfg, mesh, policy=policy)
    server = ServeServer(
        engine, host=args.host, port=args.port, fault_plan=plan
    ).start()
    return run_server(
        server, spool_dir=args.spool_dir, role=args.role,
        ready_fh=sys.stdout, trace_out=args.trace_out,
    )


if __name__ == "__main__":
    raise SystemExit(main())
