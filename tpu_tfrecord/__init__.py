"""tpu-tfrecord: a TPU-native TFRecord framework.

A from-scratch re-design of the capabilities of linkedin/spark-tfrecord
(reference: /root/reference) for the JAX/TPU ecosystem:

- TFRecord wire format (length + masked CRC32C framing)  [ref: §2.8, shaded
  org.tensorflow:tensorflow-hadoop]                        -> `tpu_tfrecord.wire`
- tf.Example / tf.SequenceExample protobuf codec (hand-rolled, no TF dep)
  [ref: §2.9, shaded protobuf]                             -> `tpu_tfrecord.proto`
- Schema model (the StructType equivalent)                 -> `tpu_tfrecord.schema`
- Schema-driven row<->record serde
  [ref: TFRecordSerializer.scala / TFRecordDeserializer.scala]
                                                           -> `tpu_tfrecord.serde`
- Schema inference with the numeric-precedence lattice
  [ref: TensorFlowInferSchema.scala]                       -> `tpu_tfrecord.infer`
- Dataset read/write: shard discovery, Hive-style partitionBy, save modes,
  compression codecs [ref: DefaultSource.scala, TFRecordFileReader.scala,
  TFRecordOutputWriter.scala]                              -> `tpu_tfrecord.io`
- TPU ingestion: columnar batches -> sharded jax.Array on a device mesh,
  ragged SequenceExample padding/bucketing, multi-host shard assignment
  (the reference's data-parallel axis, re-imagined for a TPU pod)
                                                           -> `tpu_tfrecord.tpu`
- Stall defense: per-op read/open deadlines, hedged shard reads, the
  pipeline watchdog and the on_stall policy                -> `tpu_tfrecord.stall`
- Deterministic chaos-FS fault injection (seeded FaultPlan + ChaosFS with
  a replayable fault ledger)                               -> `tpu_tfrecord.faults`
- Pipeline flight recorder: span tracing (Chrome-trace export), latency
  histograms, the telemetry pulse + Prometheus endpoint, and the
  producer/consumer bound-ness verdict                     -> `tpu_tfrecord.telemetry`
"""

from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    StructField,
    StructType,
)
from tpu_tfrecord.options import RecordType, TFRecordOptions
from tpu_tfrecord.registry import lookup_format, register_format
from tpu_tfrecord.retry import RetryPolicy
from tpu_tfrecord.stall import DeadlineError, StallError, WatchdogError

__version__ = "0.1.0"


def ensure_jax_platform() -> None:
    """Mirror ``JAX_PLATFORMS`` into ``jax.config`` before first backend use.

    Some environments import jax at interpreter start (sitecustomize),
    registering accelerator plugins whose backend DISCOVERY can hang inside
    C when the device link is dead — the env var's platform filter applies
    too late to help. ``jax.config.update("jax_platforms", ...)``
    short-circuits discovery to the named platform(s). One owner for the
    recipe used by bench.py, the examples, and tests/conftest.py; call it
    before any jax device/mesh call. No-op when JAX_PLATFORMS is unset or
    jax is unavailable.
    """
    import os as _os

    platforms = _os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platforms)
    except ImportError:
        pass


__all__ = [
    "ArrayType",
    "BinaryType",
    "DataType",
    "DecimalType",
    "DoubleType",
    "FloatType",
    "IntegerType",
    "LongType",
    "NullType",
    "StringType",
    "StructField",
    "StructType",
    "RecordType",
    "TFRecordOptions",
    "RetryPolicy",
    "StallError",
    "DeadlineError",
    "WatchdogError",
    "register_format",
    "lookup_format",
    "ensure_jax_platform",
    "__version__",
]
