"""Format registry: name -> data source implementation.

TPU-native equivalent of Spark's ServiceLoader-based DataSourceRegister
(reference META-INF/services file + DefaultSource.shortName at
DefaultSource.scala:23-24; SURVEY.md §2.10/§3.4): a process-local registry
keyed by short format name, populated at import time by the io layer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

_REGISTRY: Dict[str, Any] = {}


def register_format(short_name: str, factory: Callable[[], Any]) -> None:
    """Register a data-source factory under a short name (e.g. 'tfrecord')."""
    _REGISTRY[short_name.lower()] = factory


def lookup_format(short_name: str) -> Any:
    """Resolve a short name to a data-source instance, like Spark resolving
    ``format("tfrecord")``; unknown names raise."""
    key = short_name.lower()
    if key not in _REGISTRY:
        # Importing the io layer registers the built-in 'tfrecord' format,
        # mirroring the lazy ServiceLoader resolution.
        if key == "tfrecord":
            import tpu_tfrecord.io  # noqa: F401  (registers on import)
        if key not in _REGISTRY:
            raise ValueError(
                f"Unknown data source format {short_name!r}; "
                f"registered: {sorted(_REGISTRY)}"
            )
    return _REGISTRY[key]()
