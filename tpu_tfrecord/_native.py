"""Loader + ctypes bindings for the C++ fast path (csrc/tfrecord_native.cc).

The native library provides hardware CRC32C, TFRecord frame scanning, and
batch Example/SequenceExample -> columnar decoding (the components the
reference delegates to shaded JVM libraries, SURVEY.md §2.8-2.9). ctypes
releases the GIL during each call, so decode overlaps Python-side work.

The .so is compiled on first import if missing (g++, ~2s, cached under
tpu_tfrecord/_lib/). Set TPU_TFRECORD_NO_NATIVE=1 to force the pure-Python
path (the correctness oracle).
"""

from __future__ import annotations

import ctypes
import os
import platform
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_tfrecord import proto
from tpu_tfrecord.columnar import Column, ColumnarBatch
from tpu_tfrecord.options import RecordType
from tpu_tfrecord.schema import (
    ArrayType,
    BinaryType,
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    StructType,
)
from tpu_tfrecord.serde import NullValueError

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "csrc", "tfrecord_native.cc")
_LIB_DIR = os.path.join(_PKG_DIR, "_lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libtfrecord_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None


def _build() -> None:
    os.makedirs(_LIB_DIR, exist_ok=True)
    # Compile to a per-process temp name and os.replace into place: multiple
    # processes (process_count > 1 on one host) may race the first build, and
    # a half-written .so must never be visible under the final name.
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-std=c++20", "-O3", "-fPIC", "-shared", "-o", tmp_path, _SRC]
    if platform.machine() == "x86_64":
        # BMI2 (PEXT varint decode) is NOT forced here: it compiles via a
        # per-function target attribute and dispatches at runtime on
        # __builtin_cpu_supports, so the .so stays safe on pre-Haswell CPUs.
        cmd.insert(1, "-msse4.2")
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp_path, _LIB_PATH)
    finally:
        if os.path.exists(tmp_path):
            os.remove(tmp_path)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)

    lib.tfr_crc32c.restype = ctypes.c_uint32
    lib.tfr_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]

    lib.tfr_scan.restype = ctypes.c_int64
    lib.tfr_scan.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int32, u64p, u64p, ctypes.c_int64]

    lib.tfr_scan_partial.restype = ctypes.c_int64
    lib.tfr_scan_partial.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int32, u64p, u64p,
        ctypes.c_int64, u64p,
    ]

    lib.tfr_decode_batch.restype = ctypes.c_void_p
    lib.tfr_decode_batch.argtypes = [
        ctypes.c_char_p, u64p, u64p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_char_p),
        i32p, i32p, i32p, u8p, i64p,
        i32p, i64p, ctypes.c_int32, i64p,
        ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.tfr_scan_decode.restype = ctypes.c_void_p
    lib.tfr_scan_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_char_p),
        i32p, i32p, i32p, u8p, i64p,
        i32p, i64p, ctypes.c_int32, i64p,
        i64p, i64p, u64p,
        ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.tfr_result_group.restype = ctypes.c_int64
    lib.tfr_result_group.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(u8p)]
    for name in ("tfr_result_values",):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_void_p)]
    for name in ("tfr_result_row_offsets", "tfr_result_inner_offsets", "tfr_result_blob_offsets"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(i64p)]
    lib.tfr_result_blob.restype = ctypes.c_int64
    lib.tfr_result_blob.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(u8p)]
    lib.tfr_result_mask.restype = ctypes.c_int64
    lib.tfr_result_mask.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(u8p)]
    lib.tfr_result_trim.restype = None
    lib.tfr_result_trim.argtypes = [ctypes.c_void_p]
    lib.tfr_result_free.restype = None
    lib.tfr_result_free.argtypes = [ctypes.c_void_p]

    lib.tfr_frame_records.restype = ctypes.c_int64
    lib.tfr_frame_records.argtypes = [
        ctypes.c_char_p, u64p, u64p, ctypes.c_int64, u8p, ctypes.c_int64
    ]
    lib.tfr_hash_blob.restype = None
    lib.tfr_hash_blob.argtypes = [
        ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_int64, i64p
    ]
    lib.tfr_pack_mixed.restype = ctypes.c_int64
    lib.tfr_pack_mixed.argtypes = [
        i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, i32p,
    ]
    lib.tfr_infer_batch.restype = ctypes.c_void_p
    lib.tfr_infer_batch.argtypes = [
        ctypes.c_char_p, u64p, u64p, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.tfr_infer_size.restype = ctypes.c_int64
    lib.tfr_infer_size.argtypes = [ctypes.c_void_p]
    lib.tfr_infer_entry.restype = ctypes.c_int64
    lib.tfr_infer_entry.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.tfr_infer_free.restype = None
    lib.tfr_infer_free.argtypes = [ctypes.c_void_p]

    lib.tfr_pad_ragged.restype = ctypes.c_int64
    lib.tfr_pad_ragged.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p, i32p,
    ]
    lib.tfr_pad_ragged2.restype = ctypes.c_int64
    lib.tfr_pad_ragged2.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, i64p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        i32p, i32p,
    ]
    lib.tfr_snappy_decompress.restype = ctypes.c_int64
    lib.tfr_snappy_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, u8p, ctypes.c_uint64
    ]
    lib.tfr_lz4_decompress.restype = ctypes.c_int64
    lib.tfr_lz4_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, u8p, ctypes.c_uint64
    ]
    lib.tfr_snappy_max_compressed.restype = ctypes.c_int64
    lib.tfr_snappy_max_compressed.argtypes = [ctypes.c_uint64]
    lib.tfr_snappy_compress.restype = ctypes.c_int64
    lib.tfr_snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, u8p, ctypes.c_uint64
    ]
    lib.tfr_lz4_max_compressed.restype = ctypes.c_int64
    lib.tfr_lz4_max_compressed.argtypes = [ctypes.c_uint64]
    lib.tfr_lz4_compress.restype = ctypes.c_int64
    lib.tfr_lz4_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, u8p, ctypes.c_uint64
    ]
    lib.tfr_encode_batch.restype = ctypes.c_int64
    lib.tfr_encode_batch.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_char_p), i64p, i32p, i32p, i32p,
        ctypes.POINTER(u8p), ctypes.POINTER(i64p), ctypes.POINTER(i64p),
        ctypes.POINTER(u8p), ctypes.POINTER(i64p),
        ctypes.POINTER(u8p),
        u8p, ctypes.c_int64,
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if os.environ.get("TPU_TFRECORD_NO_NATIVE"):
        _load_error = "disabled via TPU_TFRECORD_NO_NATIVE"
        return None
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
            ):
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except Exception as e:  # pragma: no cover - depends on toolchain  # graftlint: swallow(toolchain-dependent build: _load_error recorded, python fallback serves)
            _load_error = str(e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def load_error() -> Optional[str]:
    load()
    return _load_error


# ---------------------------------------------------------------------------
# High-level wrappers
# ---------------------------------------------------------------------------


def crc32c(data: bytes) -> int:
    lib = load()
    assert lib is not None
    return lib.tfr_crc32c(bytes(data), len(data))


_SCAN_ERRORS = {
    -1: "corrupt TFRecord: bad length CRC",
    -2: "truncated TFRecord",
    -3: "corrupt TFRecord: bad data CRC",
    -4: "scan capacity exceeded",
}


def scan(buf: bytes, verify_crc: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Scan framing over an in-memory buffer -> (offsets, lengths) arrays."""
    from tpu_tfrecord.wire import TFRecordCorruptionError

    lib = load()
    assert lib is not None
    cap = max(1, len(buf) // 16)
    offsets = np.empty(cap, dtype=np.uint64)
    lengths = np.empty(cap, dtype=np.uint64)
    n = lib.tfr_scan(
        buf,
        len(buf),
        1 if verify_crc else 0,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
    )
    if n < 0:
        raise TFRecordCorruptionError(_SCAN_ERRORS.get(int(n), f"scan error {n}"))
    # Copy out of the worst-case-capacity backing arrays (sized len(buf)/16
    # entries) so holding the result doesn't pin ~buf-sized allocations.
    return offsets[:n].copy(), lengths[:n].copy()


def scan_partial(
    buf: bytes, verify_crc: bool = True, max_records: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Scan complete frames; a record extending past the end of the buffer is
    a tail, not an error. Returns (offsets, lengths, consumed_bytes).
    ``max_records`` stops the scan cleanly after that many records — bytes
    past them are neither framed nor CRC-checked (record-limited sampling)."""
    from tpu_tfrecord.wire import TFRecordCorruptionError

    lib = load()
    assert lib is not None
    cap = max(1, len(buf) // 16)
    if max_records is not None:
        if max_records <= 0:
            return np.empty(0, np.uint64), np.empty(0, np.uint64), 0
        cap = min(cap, max_records)
    offsets = np.empty(cap, dtype=np.uint64)
    lengths = np.empty(cap, dtype=np.uint64)
    consumed = ctypes.c_uint64(0)
    n = lib.tfr_scan_partial(
        buf,
        len(buf),
        1 if verify_crc else 0,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
        ctypes.byref(consumed),
    )
    if n < 0:
        raise TFRecordCorruptionError(_SCAN_ERRORS.get(int(n), f"scan error {n}"))
    return offsets[:n].copy(), lengths[:n].copy(), int(consumed.value)


# layout/kind/dtype codes must match tfrecord_native.cc
_LAYOUT_SCALAR, _LAYOUT_RAGGED, _LAYOUT_RAGGED2 = 0, 1, 2
_DT_I64, _DT_I32, _DT_F32, _DT_F64, _DT_BYTES = 0, 1, 2, 3, -1
_DT_NP = {_DT_I64: np.int64, _DT_I32: np.int32, _DT_F32: np.float32, _DT_F64: np.float64}


class UnsupportedSchemaError(ValueError):
    """Schema not representable natively — callers fall back to Python.
    Distinct from configuration errors (bad pack/hash_buckets), which always
    raise to the user instead of silently disabling the fast path."""


def _field_spec(name: str, dtype: DataType) -> Tuple[int, int, int]:
    """(layout, kind, out_dtype) for a schema field; raises
    UnsupportedSchemaError if unsupported natively."""
    elem: DataType = dtype
    layout = _LAYOUT_SCALAR
    if isinstance(dtype, ArrayType):
        if isinstance(dtype.element_type, ArrayType):
            layout = _LAYOUT_RAGGED2
            elem = dtype.element_type.element_type
            if isinstance(elem, ArrayType):
                raise UnsupportedSchemaError(">2-level nesting")
        else:
            layout = _LAYOUT_RAGGED
            elem = dtype.element_type
    if isinstance(elem, IntegerType):
        return layout, proto.INT64_LIST, _DT_I32
    if isinstance(elem, LongType):
        return layout, proto.INT64_LIST, _DT_I64
    if isinstance(elem, FloatType):
        return layout, proto.FLOAT_LIST, _DT_F32
    if isinstance(elem, (DoubleType, DecimalType)):
        return layout, proto.FLOAT_LIST, _DT_F64
    if isinstance(elem, (StringType, BinaryType)):
        return layout, proto.BYTES_LIST, _DT_BYTES
    raise UnsupportedSchemaError(f"unsupported native type {elem}")


def validate_hash_buckets(schema: StructType, hash_buckets) -> Dict[str, int]:
    """Shared eager validation for hash_buckets (used by NativeDecoder AND
    TFRecordDataset so a config typo can never silently disable the fast
    path)."""
    out: Dict[str, int] = {}
    for name, buckets in (hash_buckets or {}).items():
        if name not in schema:
            raise ValueError(
                f"hash_buckets[{name!r}]: no such data column (have {schema.names})"
            )
        dt = schema[name].data_type
        # scalar bytes column (single-hot) or array-of-bytes (multi-hot)
        if isinstance(dt, ArrayType):
            dt = dt.element_type
        if not isinstance(dt, (StringType, BinaryType)):
            raise ValueError(f"hash_buckets[{name!r}]: not a string/binary column")
        b = int(buckets)
        if b <= 0:
            raise ValueError(f"hash_buckets[{name!r}] must be positive, got {b}")
        out[name] = b
    return out


def validate_pack(schema: StructType, pack, hash_buckets) -> Dict[str, List[str]]:
    """Shared eager validation for column-group packing: group names must not
    collide with columns; members must exist, be scalar, be numeric (or
    hashed bytes), be listed exactly once anywhere, share one output dtype;
    groups must be non-empty."""
    hash_buckets = hash_buckets or {}
    seen_members: Dict[str, str] = {}
    out: Dict[str, List[str]] = {}
    for gname, members in (pack or {}).items():
        if gname in schema:
            raise ValueError(f"pack group {gname!r} collides with a column name")
        if not members:
            raise ValueError(f"pack[{gname}]: group has no members")
        dtypes = set()
        for m in members:
            if m in seen_members:
                raise ValueError(
                    f"pack[{gname}]: column {m!r} already in group "
                    f"{seen_members[m]!r} — a column may be packed once"
                )
            seen_members[m] = gname
            if m not in schema:
                raise ValueError(
                    f"pack[{gname}]: no such data column {m!r} (have {schema.names})"
                )
            mdt = schema[m].data_type
            if isinstance(mdt, ArrayType):
                raise ValueError(f"pack[{gname}]: {m} is not a scalar column")
            if isinstance(mdt, (StringType, BinaryType)):
                if m not in hash_buckets:
                    raise ValueError(
                        f"pack[{gname}]: {m} is a bytes column (add it to "
                        "hash_buckets to pack it)"
                    )
                dtypes.add(_DT_I32)
            else:
                dtypes.add(_field_spec(m, mdt)[2])
        if len(dtypes) != 1:
            raise ValueError(
                f"pack[{gname}]: members must share one dtype"
            )
        out[gname] = list(members)
    return out


class NativeDecoder:
    """Batch decoder backed by the C++ library. Interface mirrors
    columnar.ColumnarDecoder but consumes (buf, offsets, lengths) spans."""

    def __init__(
        self,
        schema: StructType,
        record_type: RecordType = RecordType.EXAMPLE,
        hash_buckets: Optional[Dict[str, int]] = None,
        pack: Optional[Dict[str, List[str]]] = None,
    ):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_load_error}")
        self._lib = lib
        self.schema = schema
        self.record_type = RecordType.parse(record_type)
        if self.record_type == RecordType.BYTE_ARRAY:
            raise ValueError("ByteArray decoding has no native path (trivial in Python)")
        n = len(schema)
        self._names = [f.name.encode("utf-8") for f in schema]
        self._c_names = (ctypes.c_char_p * n)(*self._names)
        specs = [_field_spec(f.name, f.data_type) for f in schema]
        self._layouts = np.array([s[0] for s in specs], dtype=np.int32)
        self._kinds = np.array([s[1] for s in specs], dtype=np.int32)
        self._dtypes = np.array([s[2] for s in specs], dtype=np.int32)
        # Fused categorical hashing: a hashed bytes column decodes straight
        # to int32 bucket indices (no blob materialization at all).
        self.hash_buckets = validate_hash_buckets(schema, hash_buckets)
        self._hash = np.zeros(n, dtype=np.int64)
        for i, f in enumerate(schema):
            if f.name in self.hash_buckets:
                self._hash[i] = self.hash_buckets[f.name]
                self._dtypes[i] = _DT_I32
        self._nullables = np.array([1 if f.nullable else 0 for f in schema], dtype=np.uint8)
        self._fmt = 0 if self.record_type == RecordType.EXAMPLE else 1
        # Column-group packing: same-dtype scalar fields decode straight into
        # one [n_records, width] matrix per group.
        self.pack = validate_pack(schema, pack, self.hash_buckets)
        self._group_ids = np.full(n, -1, dtype=np.int32)
        self._group_offs = np.zeros(n, dtype=np.int64)
        self._group_strides = np.zeros(len(self.pack), dtype=np.int64)
        self._group_meta: List[Tuple[str, np.dtype, int]] = []  # (name, dtype, width)
        for g, (gname, members) in enumerate(self.pack.items()):
            np_dt = np.dtype(_DT_NP[int(self._dtypes[schema.field_index(members[0])])])
            self._group_strides[g] = np_dt.itemsize * len(members)
            for pos, m in enumerate(members):
                i = schema.field_index(m)
                self._group_ids[i] = g
                self._group_offs[i] = np_dt.itemsize * pos
            self._group_meta.append((gname, np_dt, len(members)))

    def decode_spans(
        self, buf: bytes, offsets: np.ndarray, lengths: np.ndarray
    ) -> ColumnarBatch:
        lib = self._lib
        n_records = len(offsets)
        errbuf = ctypes.create_string_buffer(512)
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lengths = np.ascontiguousarray(lengths, dtype=np.uint64)
        handle = lib.tfr_decode_batch(
            buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n_records,
            self._fmt,
            len(self.schema),
            self._c_names,
            self._layouts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._dtypes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._nullables.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self._hash.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._group_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._group_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(self._group_meta),
            self._group_strides.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            errbuf,
            len(errbuf),
        )
        if not handle:
            msg = errbuf.value.decode("utf-8", "replace")
            if "does not allow null values" in msg:
                raise NullValueError(msg)
            raise ValueError(f"native decode failed: {msg}")
        return self._extract_owned(handle, n_records)

    def scan_decode(
        self,
        buf,
        start: int,
        verify_crc: bool,
        skip_records: int,
        max_records: int,
        length: Optional[int] = None,
        max_record_bytes: int = 0,
    ) -> Tuple[Optional[ColumnarBatch], int, int, int]:
        """Fused frame scan + decode in ONE pass over ``buf`` from ``start``:
        CRC-verify and skip ``skip_records`` frames (resume), then decode up
        to ``max_records`` records — each parsed right after its CRC while
        its bytes are cache-hot; no offsets/lengths arrays materialize.
        ``buf`` is bytes or a uint8 numpy array (reused IO buffers);
        ``length`` bounds the valid bytes (default: whole buffer). Returns
        (batch_or_None, n_skipped, n_decoded, consumed_abs); stops without
        error at a partial tail frame."""
        from tpu_tfrecord.wire import TFRecordCorruptionError

        lib = self._lib
        errbuf = ctypes.create_string_buffer(512)
        n_sk = ctypes.c_int64(0)
        n_de = ctypes.c_int64(0)
        consumed = ctypes.c_uint64(start)
        if isinstance(buf, np.ndarray):
            ptr = buf.ctypes.data_as(ctypes.c_char_p)
            blen = buf.nbytes
        else:
            ptr = buf
            blen = len(buf)
        if length is not None:
            blen = length
        handle = lib.tfr_scan_decode(
            ptr,
            blen,
            start,
            1 if verify_crc else 0,
            skip_records,
            max_records,
            max_record_bytes,
            self._fmt,
            len(self.schema),
            self._c_names,
            self._layouts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._dtypes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._nullables.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self._hash.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._group_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._group_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(self._group_meta),
            self._group_strides.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.byref(n_sk),
            ctypes.byref(n_de),
            ctypes.byref(consumed),
            errbuf,
            len(errbuf),
        )
        if not handle:
            msg = errbuf.value.decode("utf-8", "replace")
            if msg.startswith("corrupt TFRecord"):
                raise TFRecordCorruptionError(msg)
            if "does not allow null values" in msg:
                raise NullValueError(msg)
            raise ValueError(f"native decode failed: {msg}")
        n_decoded = int(n_de.value)
        if n_decoded:
            cb = self._extract_owned(handle, n_decoded)
        else:
            cb = None
            lib.tfr_result_free(handle)
        return cb, int(n_sk.value), n_decoded, int(consumed.value)

    def decode_batch(self, records) -> ColumnarBatch:
        """List-of-bytes interface (drop-in for ColumnarDecoder): records are
        packed into one contiguous buffer then decoded in a single call."""
        lengths = np.array([len(r) for r in records], dtype=np.uint64)
        offsets = np.zeros(len(records), dtype=np.uint64)
        np.cumsum(lengths[:-1], out=offsets[1:])
        buf = b"".join(records)
        return self.decode_spans(buf, offsets, lengths)

    def _extract_owned(self, handle, n_records: int) -> ColumnarBatch:
        """Extract a batch, taking ownership of ``handle``: it is freed on
        return UNLESS zero-copy views took it over (then the last view's GC
        frees it — even if extraction failed midway)."""
        owner_box: List[Optional[_NativeResult]] = [None]
        try:
            return self._extract(handle, n_records, owner_box)
        finally:
            if owner_box[0] is None:
                self._lib.tfr_result_free(handle)

    def _extract(self, handle, n_records: int, owner_box) -> ColumnarBatch:
        lib = self._lib
        cols: Dict[str, Column] = {}
        # Non-group columns are COPIED out first; then the handle is trimmed
        # (per-column vectors dropped, group slack released) BEFORE group
        # pointers are taken — trim may reallocate group buffers, and a
        # pinned handle must not hold more than the group matrices.
        self._extract_fields(handle, cols)
        if self._group_meta:
            lib.tfr_result_trim(handle)
        for g, (gname, np_dt, width) in enumerate(self._group_meta):
            gptr = ctypes.POINTER(ctypes.c_uint8)()
            gbytes = lib.tfr_result_group(handle, g, ctypes.byref(gptr))
            if gbytes:
                # Zero-copy: view straight into the C++ group matrix; the
                # result handle stays alive until the LAST view dies (the
                # owner sits on the arrays' base chain), so the batch can
                # flow into device_put without a host-side memcpy.
                if owner_box[0] is None:
                    owner_box[0] = _NativeResult(lib, handle)
                values = _np_view(gptr, gbytes, np_dt, owner_box[0]).reshape(
                    n_records, width
                )
            else:
                values = np.empty((n_records, width), dtype=np_dt)
            # Group columns use the first member's schema dtype; per-field
            # validity is intentionally dropped (missing -> 0).
            first = self.pack[gname][0]
            cols[gname] = Column(gname, self.schema[first].data_type, values=values)
        return ColumnarBatch(cols, n_records)

    def _extract_fields(self, handle, cols: Dict[str, Column]) -> None:
        lib = self._lib
        for i, field in enumerate(self.schema):
            if int(self._group_ids[i]) >= 0:
                continue  # lives in a group matrix
            layout = int(self._layouts[i])
            dt = int(self._dtypes[i])
            col = Column(
                field.name,
                field.data_type,
                hash_buckets=int(self._hash[i]) if self._hash[i] else None,
            )

            mptr = ctypes.POINTER(ctypes.c_uint8)()
            mlen = lib.tfr_result_mask(handle, i, ctypes.byref(mptr))
            col.mask = _np_copy(mptr, mlen, np.uint8).astype(bool)

            if layout != _LAYOUT_SCALAR:
                optr = ctypes.POINTER(ctypes.c_int64)()
                olen = lib.tfr_result_row_offsets(handle, i, ctypes.byref(optr))
                col.offsets = _np_copy(optr, olen * 8, np.int64)
            if layout == _LAYOUT_RAGGED2:
                iptr = ctypes.POINTER(ctypes.c_int64)()
                ilen = lib.tfr_result_inner_offsets(handle, i, ctypes.byref(iptr))
                col.inner_offsets = _np_copy(iptr, ilen * 8, np.int64)

            if dt == _DT_BYTES:
                bptr = ctypes.POINTER(ctypes.c_uint8)()
                blen = lib.tfr_result_blob(handle, i, ctypes.byref(bptr))
                col.blob = _np_copy(bptr, blen, np.uint8).tobytes()
                boptr = ctypes.POINTER(ctypes.c_int64)()
                bolen = lib.tfr_result_blob_offsets(handle, i, ctypes.byref(boptr))
                col.blob_offsets = _np_copy(boptr, bolen * 8, np.int64)
            else:
                vptr = ctypes.c_void_p()
                vbytes = lib.tfr_result_values(handle, i, ctypes.byref(vptr))
                col.values = _np_copy(
                    ctypes.cast(vptr, ctypes.POINTER(ctypes.c_uint8)), vbytes, _DT_NP[dt]
                )
            cols[field.name] = col


class _NativeResult:
    """Owns a BatchResult handle: freed when the last zero-copy view dies.
    Sits at the bottom of the numpy base chain of every group-matrix view,
    so Python's GC, not the decode call, decides when the C++ buffers go."""

    __slots__ = ("_lib", "_handle")

    def __init__(self, lib, handle):
        self._lib = lib
        self._handle = handle

    def __del__(self):
        if self._handle:
            self._lib.tfr_result_free(self._handle)
            self._handle = None


def _np_view(ptr, nbytes: int, dtype, owner: "_NativeResult") -> np.ndarray:
    """Zero-copy numpy view over a C++-owned buffer, lifetime-tied to the
    result owner via the array base chain."""
    raw = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8 * nbytes)).contents
    raw._owner = owner  # ctypes instances carry attributes; keeps owner alive
    return np.frombuffer(raw, dtype=dtype)


def _np_copy(ptr, nbytes: int, dtype) -> np.ndarray:
    if nbytes == 0 or not ptr:
        return np.empty(0, dtype=dtype)
    raw = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8 * nbytes)).contents
    # single copy out of the C++-owned buffer
    return np.frombuffer(raw, dtype=dtype).copy()


def hash_blob(blob: bytes, blob_offsets: np.ndarray, num_buckets: int) -> np.ndarray:
    """CRC32C-hash each blob value into [0, num_buckets) — one native call."""
    lib = load()
    assert lib is not None
    n = len(blob_offsets) - 1
    out = np.empty(n, dtype=np.int64)
    bo = np.ascontiguousarray(blob_offsets, dtype=np.int64)
    lib.tfr_hash_blob(
        blob,
        bo.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        num_buckets,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def pack_mixed(arr: np.ndarray, keep: int, bits: int) -> Optional[np.ndarray]:
    """[B, C] int32 -> [B, keep + ceil((C-keep)*bits/32)] int32: first
    ``keep`` lanes copied, the rest bit-packed (tpu/bitpack.py layout).
    None if the native lib is unavailable (caller falls back to numpy);
    raises ValueError on a negative packed value (sign check rides the
    kernel's packing pass)."""
    lib = load()
    if lib is None:
        return None
    n_rows, n_cols = arr.shape
    c = n_cols - keep
    w = (c * bits + 31) // 32
    src = np.ascontiguousarray(arr, dtype=np.int32)
    out = np.empty((n_rows, keep + w), dtype=np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    bad = lib.tfr_pack_mixed(
        src.ctypes.data_as(i32p), n_rows, n_cols, keep, bits,
        out.ctypes.data_as(i32p),
    )
    if bad >= 0:
        r, j = divmod(int(bad), n_cols)
        raise ValueError(
            "pack_mixed requires non-negative values in packed columns "
            f"(found {int(src[r, j])} at row {r}, column {j})"
        )
    return out


class InferScanner:
    """Accumulating native schema-inference seqOp (the within-host analog of
    the reference's executor-parallel aggregate, TensorFlowInferSchema.scala:
    40-43). Feed batches of record spans with ``update``; ``result()`` yields
    the per-feature max-precedence map (infer.py's lattice encoding, see
    infer.type_map_from_precedences). The whole walk runs in C++ with the
    GIL released — no values materialize, so it both outruns the Python
    oracle ~50x single-threaded AND scales across shards in a thread pool.
    """

    def __init__(self, record_type):
        from tpu_tfrecord.options import RecordType

        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        rt = RecordType.parse(record_type)
        if rt == RecordType.EXAMPLE:
            self._fmt = 0
        elif rt == RecordType.SEQUENCE_EXAMPLE:
            self._fmt = 1
        else:
            raise ValueError(f"InferScanner does not support {rt}")
        self._lib = lib
        self._handle = None
        self._records = 0

    @property
    def records(self) -> int:
        return self._records

    def update(self, buf, offsets: np.ndarray, lengths: np.ndarray) -> None:
        """Accumulate one batch of record spans (buf may be bytes or a
        uint8 array; offsets/lengths as from scan_partial)."""
        if isinstance(buf, np.ndarray):
            buf_arg = buf.ctypes.data_as(ctypes.c_char_p)
        else:
            buf_arg = buf
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lengths = np.ascontiguousarray(lengths, dtype=np.uint64)
        errbuf = ctypes.create_string_buffer(512)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        handle = self._lib.tfr_infer_batch(
            buf_arg,
            offsets.ctypes.data_as(u64p),
            lengths.ctypes.data_as(u64p),
            len(offsets),
            self._fmt,
            self._handle,
            errbuf,
            len(errbuf),
        )
        if not handle:
            msg = errbuf.value.decode("utf-8", "replace")
            self.close()
            if "unsupported feature kind" in msg:
                from tpu_tfrecord.infer import SchemaInferenceError

                raise SchemaInferenceError(msg)
            from tpu_tfrecord.proto import ProtoDecodeError

            raise ProtoDecodeError(msg)
        self._handle = handle
        self._records += len(offsets)

    def result(self) -> Dict[str, int]:
        """Current (feature name -> max precedence) map."""
        if self._handle is None:
            return {}
        out: Dict[str, int] = {}
        name_ptr = ctypes.c_void_p()
        name_len = ctypes.c_int64()
        for i in range(self._lib.tfr_infer_size(self._handle)):
            prec = self._lib.tfr_infer_entry(
                self._handle, i, ctypes.byref(name_ptr), ctypes.byref(name_len)
            )
            name = ctypes.string_at(name_ptr.value, name_len.value).decode("utf-8")
            out[name] = int(prec)
        return out

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tfr_infer_free(self._handle)
            self._handle = None

    def __enter__(self) -> "InferScanner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # last-resort cleanup; close() is the contract
        try:
            self.close()
        except Exception:  # graftlint: swallow(interpreter-teardown destructor; nowhere to report)
            pass


# Fused pad+cast kind tables (mirror tfr_pad_ragged/_ragged2's contract).
# bf16 output uses ml_dtypes.bfloat16 as the numpy dtype; imported lazily so
# the wrapper stays importable where ml_dtypes is absent.
_PAD_IN_KINDS = {np.dtype(np.float32): 0, np.dtype(np.int64): 1}


def _pad_out_kind(in_kind: int, out_dtype) -> Optional[int]:
    dt = np.dtype(out_dtype)
    if in_kind == 0:
        if dt == np.float32:
            return 0
        if dt.name == "bfloat16":
            return 1
    else:
        if dt == np.int64:
            return 2
        if dt == np.int32:
            return 3
    return None


def pad_ragged_dense(values, offsets, max_len, out_dtype=None, pad_value=0):
    """Native fused pad(+cast): ragged [total]+offsets -> dense [N, max_len]
    + clipped lengths [N] int32. None when unavailable/unsupported (caller
    falls back to columnar.pad_ragged + astype)."""
    lib = load()
    if lib is None or pad_value != 0:
        return None
    values = np.ascontiguousarray(values)
    in_kind = _PAD_IN_KINDS.get(values.dtype)
    if in_kind is None:
        return None
    out_dtype = values.dtype if out_dtype is None else np.dtype(out_dtype)
    out_kind = _pad_out_kind(in_kind, out_dtype)
    if out_kind is None:
        return None
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    if n and offsets[-1] > len(values):
        # The kernel is offset-driven with no values-length parameter; keep
        # the numpy path's failure mode instead of reading out of bounds.
        raise IndexError(
            f"pad_ragged offsets end at {int(offsets[-1])} but values has "
            f"{len(values)} elements"
        )
    dense = np.empty((n, max_len), dtype=out_dtype)
    lengths = np.empty(n, dtype=np.int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    rc = lib.tfr_pad_ragged(
        values.ctypes.data_as(ctypes.c_void_p), in_kind,
        offsets.ctypes.data_as(i64p), n, max_len, out_kind,
        dense.ctypes.data_as(ctypes.c_void_p),
        lengths.ctypes.data_as(i32p),
    )
    if rc != 0:  # pragma: no cover - kinds validated above
        return None
    return dense, lengths


def pad_ragged2_dense(
    values, inner_offsets, row_splits, max_outer, max_inner,
    out_dtype=None, pad_value=0,
):
    """Native fused pad(+cast): ragged^2 buffers -> dense [N, Lo, Li] +
    outer lengths [N] + inner lengths [N, Lo] (both int32). None when
    unavailable/unsupported (caller falls back to columnar.pad_ragged2)."""
    lib = load()
    if lib is None or pad_value != 0:
        return None
    values = np.ascontiguousarray(values)
    in_kind = _PAD_IN_KINDS.get(values.dtype)
    if in_kind is None:
        return None
    out_dtype = values.dtype if out_dtype is None else np.dtype(out_dtype)
    out_kind = _pad_out_kind(in_kind, out_dtype)
    if out_kind is None:
        return None
    inner_offsets = np.ascontiguousarray(inner_offsets, dtype=np.int64)
    row_splits = np.ascontiguousarray(row_splits, dtype=np.int64)
    n = len(row_splits) - 1
    # Offset-driven kernel, no length parameters: keep the numpy path's
    # IndexError on inconsistent buffers instead of reading out of bounds.
    if n and row_splits[-1] > len(inner_offsets) - 1:
        raise IndexError(
            f"pad_ragged2 row_splits end at {int(row_splits[-1])} but "
            f"inner_offsets describes {len(inner_offsets) - 1} lists"
        )
    if len(inner_offsets) > 1 and inner_offsets[-1] > len(values):
        raise IndexError(
            f"pad_ragged2 inner_offsets end at {int(inner_offsets[-1])} but "
            f"values has {len(values)} elements"
        )
    dense = np.empty((n, max_outer, max_inner), dtype=out_dtype)
    outer_len = np.empty(n, dtype=np.int32)
    inner_len = np.empty((n, max_outer), dtype=np.int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    rc = lib.tfr_pad_ragged2(
        values.ctypes.data_as(ctypes.c_void_p), in_kind,
        inner_offsets.ctypes.data_as(i64p),
        row_splits.ctypes.data_as(i64p), n, max_outer, max_inner, out_kind,
        dense.ctypes.data_as(ctypes.c_void_p),
        outer_len.ctypes.data_as(i32p),
        inner_len.ctypes.data_as(i32p),
    )
    if rc != 0:  # pragma: no cover - kinds validated above
        return None
    return dense, outer_len, inner_len


# A valid snappy stream expands at most ~21x per compressed byte (a 3-byte
# copy2 element emits up to 64 bytes); far beyond that, the length varint
# is corrupt — refuse BEFORE allocating what untrusted bytes claim.
_SNAPPY_MAX_EXPANSION = 100


def snappy_decompress(data: bytes) -> Optional[bytes]:
    """Native raw-snappy decode; None if the native lib is unavailable.
    Raises ValueError / TFRecordCorruptionError on corrupt input."""
    lib = load()
    if lib is None:
        return None
    # parse the preamble with the shared (oracle) varint: its exact
    # truncation/overflow errors, and one decoder to keep in sync
    from tpu_tfrecord.hadoop_codecs import _read_varint

    expected, _ = _read_varint(memoryview(data), 0)
    if expected > _SNAPPY_MAX_EXPANSION * len(data) + 1024:
        raise ValueError(
            f"snappy: declared output {expected} is impossible for "
            f"{len(data)} compressed bytes — corrupt length varint"
        )
    out = np.empty(expected, dtype=np.uint8)
    rc = lib.tfr_snappy_decompress(
        data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), expected,
    )
    if rc < 0:
        raise ValueError(f"corrupt snappy input (rc={rc})")
    return out.tobytes()


def lz4_decompress(
    data: bytes,
    expected: Optional[int] = None,
    max_out: Optional[int] = None,
) -> Optional[bytes]:
    """Native lz4-block decode; None if the native lib is unavailable.
    ``expected`` = exact output size (strictly enforced); ``max_out`` = an
    upper bound (initial capacity only — e.g. the Hadoop block header's
    remaining bytes). With neither, the buffer grows geometrically on
    rc=-2."""
    lib = load()
    if lib is None:
        return None
    if expected is not None:
        cap = expected
    elif max_out is not None:
        cap = max_out
    else:
        cap = max(4 * len(data) + 64, 1 << 16)
    while True:
        out = np.empty(cap, dtype=np.uint8)
        rc = lib.tfr_lz4_decompress(
            data, len(data),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
        )
        if rc >= 0:
            if expected is not None and rc != expected:
                raise ValueError(
                    f"lz4: decoded {rc} bytes, framing promised {expected}"
                )
            return out[:rc].tobytes()
        if rc == -2 and expected is None and max_out is None and cap < (1 << 31):
            cap *= 4
            continue
        raise ValueError(f"corrupt lz4 input (rc={rc})")


def snappy_compress(data: bytes) -> Optional[bytes]:
    """Native raw-snappy ENCODE (greedy hash matcher, 64KB blocks): real
    compression with zero optional dependencies. None if the native lib is
    unavailable (callers fall back to the literal-only pure-Python
    encoder)."""
    lib = load()
    if lib is None:
        return None
    cap = lib.tfr_snappy_max_compressed(len(data))
    out = np.empty(cap, dtype=np.uint8)
    rc = lib.tfr_snappy_compress(
        data, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap
    )
    if rc < 0:  # cannot happen with cap from max_compressed; defensive
        raise ValueError(f"snappy compress failed (rc={rc})")
    return out[:rc].tobytes()


def lz4_compress(data: bytes) -> Optional[bytes]:
    """Native lz4-block ENCODE (greedy hash matcher, 64KB offset window);
    None if the native lib is unavailable or the input exceeds the
    kernel's int32 match-table contract (callers frame in 256 KiB Hadoop
    blocks, so a >=2 GiB single call is out of contract — the pure-Python
    fallback handles it instead of silently degrading)."""
    lib = load()
    if lib is None or len(data) > 2**31 - 1:
        return None
    cap = lib.tfr_lz4_max_compressed(len(data))
    out = np.empty(cap, dtype=np.uint8)
    rc = lib.tfr_lz4_compress(
        data, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap
    )
    if rc < 0:
        raise ValueError(f"lz4 compress failed (rc={rc})")
    return out[:rc].tobytes()


class NativeEncoder:
    """Columnar batch -> framed tf.Example/SequenceExample stream, one
    native call per batch.

    The write-side twin of NativeDecoder (reference write hot loop,
    TFRecordOutputWriter.scala:26-38, done batch-at-a-time). For
    SequenceExample, ragged2 columns become FeatureLists and scalar/ragged
    columns go to the context map (mirroring TFRecordSerializer.scala:37-60).
    """

    def __init__(self, schema: StructType, record_type: RecordType = RecordType.EXAMPLE):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_load_error}")
        self._lib = lib
        self.schema = schema
        self.record_type = RecordType.parse(record_type)
        if self.record_type == RecordType.BYTE_ARRAY:
            raise UnsupportedSchemaError("ByteArray encode is trivial in Python")
        n = len(schema)
        specs = [_field_spec(f.name, f.data_type) for f in schema]
        if self.record_type == RecordType.EXAMPLE and any(
            s[0] == _LAYOUT_RAGGED2 for s in specs
        ):
            raise ValueError(
                "array-of-array columns require recordType=SequenceExample"
            )
        self._names = [f.name.encode("utf-8") for f in schema]
        self._c_names = (ctypes.c_char_p * n)(*self._names)
        self._name_lens = np.array([len(b) for b in self._names], dtype=np.int64)
        self._layouts_np = np.array([s[0] for s in specs], dtype=np.int32)
        self._layouts = self._layouts_np.tolist()  # single source of truth
        self._kinds = np.array([s[1] for s in specs], dtype=np.int32)
        self._dtypes = np.array([s[2] for s in specs], dtype=np.int32)
        self._non_nullable = [not f.nullable for f in schema]
        self._fmt = 0 if self.record_type == RecordType.EXAMPLE else 1

    def encode_batch(self, batch: ColumnarBatch) -> np.ndarray:
        """Returns a uint8 array holding the framed record stream."""
        lib = self._lib
        n_fields = len(self.schema)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        values_arr = (u8p * n_fields)()
        rowoff_arr = (i64p * n_fields)()
        inneroff_arr = (i64p * n_fields)()
        blob_arr = (u8p * n_fields)()
        bloboff_arr = (i64p * n_fields)()
        mask_arr = (u8p * n_fields)()
        keepalive = []
        for i, f in enumerate(self.schema):
            col = batch[f.name]
            if col.mask is not None and not col.mask.all():
                if self._non_nullable[i]:
                    raise NullValueError(f"{f.name} does not allow null values")
                m = np.ascontiguousarray(col.mask, dtype=np.uint8)
                keepalive.append(m)
                mask_arr[i] = m.ctypes.data_as(u8p)
            if self._layouts[i] != _LAYOUT_SCALAR:
                ro = np.ascontiguousarray(col.offsets, dtype=np.int64)
                keepalive.append(ro)
                rowoff_arr[i] = ro.ctypes.data_as(i64p)
            if self._layouts[i] == _LAYOUT_RAGGED2:
                io_ = np.ascontiguousarray(col.inner_offsets, dtype=np.int64)
                keepalive.append(io_)
                inneroff_arr[i] = io_.ctypes.data_as(i64p)
            if int(self._dtypes[i]) == _DT_BYTES:
                blob = col.blob if col.blob is not None else b""
                keepalive.append(blob)
                blob_arr[i] = ctypes.cast(ctypes.c_char_p(blob), u8p)
                bo = np.ascontiguousarray(col.blob_offsets, dtype=np.int64)
                keepalive.append(bo)
                bloboff_arr[i] = bo.ctypes.data_as(i64p)
            else:
                v = np.ascontiguousarray(col.values, dtype=_DT_NP[int(self._dtypes[i])])
                keepalive.append(v)
                values_arr[i] = ctypes.cast(v.ctypes.data_as(ctypes.c_void_p), u8p)
        args = (
            batch.num_rows, self._fmt, n_fields, self._c_names,
            self._name_lens.ctypes.data_as(i64p),
            self._layouts_np.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._dtypes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values_arr, rowoff_arr, inneroff_arr, blob_arr, bloboff_arr, mask_arr,
        )
        size = lib.tfr_encode_batch(*args, None, 0)
        if size < 0:
            raise ValueError(f"native encode sizing failed: {size}")
        out = np.empty(int(size), dtype=np.uint8)
        written = lib.tfr_encode_batch(*args, out.ctypes.data_as(u8p), int(size))
        if written != size:
            raise ValueError(f"native encode failed: wrote {written} of {size}")
        return out


def make_encoder(schema: StructType, record_type) -> Optional["NativeEncoder"]:
    """NativeEncoder if supported, else None (Python row fallback)."""
    rt = RecordType.parse(record_type) if not isinstance(record_type, RecordType) else record_type
    if rt == RecordType.BYTE_ARRAY or not available():
        return None
    try:
        return NativeEncoder(schema, rt)
    except UnsupportedSchemaError:
        return None


def make_decoder(
    schema: StructType,
    record_type,
    hash_buckets: Optional[Dict[str, int]] = None,
    pack: Optional[Dict[str, List[str]]] = None,
) -> Optional[NativeDecoder]:
    """NativeDecoder if the schema/record type is natively supported and the
    library loads, else None (caller uses the Python ColumnarDecoder)."""
    rt = RecordType.parse(record_type) if not isinstance(record_type, RecordType) else record_type
    if rt == RecordType.BYTE_ARRAY or not available():
        return None
    try:
        return NativeDecoder(schema, rt, hash_buckets, pack)
    except UnsupportedSchemaError:
        # schema shape the C++ decoder can't represent -> Python fallback;
        # configuration errors (bad pack/hash_buckets) propagate instead of
        # silently disabling the fast path
        return None
