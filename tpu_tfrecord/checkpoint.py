"""Checkpoint persistence for dataset iterator state.

The reference has no resumability beyond the ``_SUCCESS`` marker (SURVEY.md
§5 checkpoint/resume: ABSENT). Here the iterator's O(1) state (epoch, shard
position, record offset — io/dataset.py) persists as a small JSON file per
process, written atomically, so a training job can bundle it with its model
checkpoint (e.g. alongside an orbax step directory) and resume mid-epoch.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from tpu_tfrecord.io.dataset import CheckpointableIterator, IteratorState

_FORMAT_VERSION = 1
# Version 2: the state carries ``window_emitted`` (mid-window position of a
# row-shuffled iterator). Semantically load-bearing — an old reader that
# dropped the field would resume at the window start and replay batches —
# so such states are WRITTEN as version 2, which old readers refuse cleanly.
_FORMAT_VERSION_WINDOWED = 2
_READABLE_VERSIONS = (1, 2)


def state_path(directory: str, process_index: Optional[int] = None) -> str:
    """Per-process state file ('_input_state.<pid>.json'): every host owns
    its own position, mirroring the per-host shard assignment."""
    if process_index is None:
        try:
            import jax

            process_index = jax.process_index()
        except Exception:  # graftlint: swallow(no distributed runtime: process 0)
            process_index = 0
    # "_"-prefixed like _SUCCESS: shard discovery treats it as metadata, so a
    # state file inside a dataset directory can never be read as a shard.
    return os.path.join(directory, f"_input_state.{process_index}.json")


def _extract_state(state_or_iterator) -> IteratorState:
    return (
        state_or_iterator.state()
        if isinstance(state_or_iterator, CheckpointableIterator)
        else state_or_iterator
    )


def _make_payload(state: IteratorState, step: Optional[int] = None) -> dict:
    version = (
        _FORMAT_VERSION_WINDOWED
        if getattr(state, "window_emitted", 0)
        else _FORMAT_VERSION
    )
    payload = {"version": version, "state": state.to_json()}
    if step is not None:
        payload["step"] = step
    return payload


def _check_version(payload: dict, where: str) -> None:
    if payload.get("version") not in _READABLE_VERSIONS:
        raise ValueError(
            f"unsupported input-state version {payload.get('version')} {where}"
        )


def save_state(
    directory: str,
    state_or_iterator,
    process_index: Optional[int] = None,
    step: Optional[int] = None,
) -> str:
    """Atomically persist iterator state; returns the file path."""
    state = _extract_state(state_or_iterator)
    os.makedirs(directory, exist_ok=True)
    path = state_path(directory, process_index)
    payload = _make_payload(state, step)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)
    return path


def load_state(
    directory: str, process_index: Optional[int] = None
) -> Optional[IteratorState]:
    """Load this process's saved state; None if no checkpoint exists."""
    path = state_path(directory, process_index)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        payload = json.load(fh)
    _check_version(payload, f"at {path}")
    return IteratorState.from_json(payload["state"])


class TrainCheckpointer:
    """Model state + input position, saved ATOMICALLY together per step.

    The failure mode this removes: params restored from step N while the
    input pipeline resumes from wherever its own file last said — a
    silently skewed data order. Both items go into ONE orbax Composite
    checkpoint (``state`` pytree + ``input_state`` json), so orbax's own
    finalization makes the pairing atomic: a crash mid-save can never
    produce a restorable step with params but no matching input position.
    The iterator-state fingerprint still guards dataset identity on resume.

    Scope: single-controller jobs (the examples' shape). Multi-host
    pipelines, where every process owns a distinct input position, keep
    using per-process ``save_state``/``load_state`` alongside their model
    checkpointer.

    Usage::

        ckpt = TrainCheckpointer("/ckpts", max_to_keep=3)
        ...
        ckpt.save(step, {"params": params, "opt_state": opt_state}, it)
        ...
        step, state, resume = ckpt.restore(
            {"params": params, "opt_state": opt_state})
        with ds.batches(resume) as it: ...
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state_pytree, state_or_iterator) -> None:
        """Persist the model pytree and the input position for ``step``."""
        payload = _make_payload(_extract_state(state_or_iterator), step)
        self._mgr.save(
            step,
            args=self._ocp.args.Composite(
                state=self._ocp.args.StandardSave(state_pytree),
                input_state=self._ocp.args.JsonSave(payload),
            ),
            force=True,
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template_pytree):
        """(step, pytree, IteratorState) for the latest checkpoint, or
        (None, template, None) when none exists."""
        step = self._mgr.latest_step()
        if step is None:
            return None, template_pytree, None
        restored = self._mgr.restore(
            step,
            args=self._ocp.args.Composite(
                state=self._ocp.args.StandardRestore(template_pytree),
                input_state=self._ocp.args.JsonRestore(),
            ),
        )
        payload = restored["input_state"]
        _check_version(payload, f"in checkpoint step {step}")
        return step, restored["state"], IteratorState.from_json(payload["state"])

    def close(self) -> None:
        self._mgr.close()
