"""Checkpoint persistence for dataset iterator state and train state.

The reference has no resumability beyond the ``_SUCCESS`` marker (SURVEY.md
§5 checkpoint/resume: ABSENT). Here the iterator's O(1) state (epoch, shard
position, record offset — io/dataset.py) persists as a small JSON file per
process, written atomically, so a training job can bundle it with its model
checkpoint (e.g. alongside an orbax step directory) and resume mid-epoch.

ISSUE 16 grows the module into the ASYNC SHARDED checkpoint layer — the
lever that retires the flight recorder's ``ckpt_bound`` verdict
(telemetry.training_verdict). Every writer here splits into two phases:

- **snapshot** (caller's thread, the only part the train loop blocks on):
  one ``jax.device_get`` of the pytree leaves into reusable host buffers
  plus the O(1) input-state/packer payload — ``ckpt.snapshot``;
- **commit** (ONE background thread): stage per-process shard files into a
  generation directory, fsync each, ``os.replace`` into place, and write
  the generation MANIFEST LAST — ``ckpt.commit``. A kill -9 at ANY point
  leaves the newest *complete* generation restorable.

Backpressure is bounded and observable: at most one commit is ever in
flight; the next ``save()`` waits on the previous commit (every blocked
save lands a ``ckpt.commit_wait`` record — never silently dropped) and
``wait()``/``close()`` drain. Commit failures re-raise on the next
``save()``/``wait()`` as ``CheckpointCommitError``.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import sys
import threading
import time
import zipfile
from typing import Callable, Optional

import numpy as np

from tpu_tfrecord.io.dataset import CheckpointableIterator, IteratorState

_FORMAT_VERSION = 1
# Version 2: the state carries ``window_emitted`` (mid-window position of a
# row-shuffled iterator). Semantically load-bearing — an old reader that
# dropped the field would resume at the window start and replay batches —
# so such states are WRITTEN as version 2, which old readers refuse cleanly.
_FORMAT_VERSION_WINDOWED = 2
_READABLE_VERSIONS = (1, 2)


def state_path(directory: str, process_index: Optional[int] = None) -> str:
    """Per-process state file ('_input_state.<pid>.json'): every host owns
    its own position, mirroring the per-host shard assignment."""
    if process_index is None:
        try:
            import jax

            process_index = jax.process_index()
        except Exception:  # graftlint: swallow(no distributed runtime: process 0)
            process_index = 0
    # "_"-prefixed like _SUCCESS: shard discovery treats it as metadata, so a
    # state file inside a dataset directory can never be read as a shard.
    return os.path.join(directory, f"_input_state.{process_index}.json")


def _extract_state(state_or_iterator) -> IteratorState:
    return (
        state_or_iterator.state()
        if isinstance(state_or_iterator, CheckpointableIterator)
        else state_or_iterator
    )


def _make_payload(state: IteratorState, step: Optional[int] = None) -> dict:
    version = (
        _FORMAT_VERSION_WINDOWED
        if getattr(state, "window_emitted", 0)
        else _FORMAT_VERSION
    )
    payload = {"version": version, "state": state.to_json()}
    if step is not None:
        payload["step"] = step
    return payload


def _check_version(payload: dict, where: str) -> None:
    if payload.get("version") not in _READABLE_VERSIONS:
        raise ValueError(
            f"unsupported input-state version {payload.get('version')} {where}"
        )


# ---------------------------------------------------------------------------
# Durability primitives (shared by every checkpoint writer in the tree)
# ---------------------------------------------------------------------------


class TornStateError(ValueError):
    """A state/checkpoint artifact exists but its bytes cannot be parsed —
    the signature of a torn write (a crash that outran fsync, a power
    loss surfacing a zero-length "committed" file) or foreign bytes. The
    loud, NAMED twin of a raw ``json.JSONDecodeError``: the message says
    which file and what to do about it."""


class CheckpointCommitError(RuntimeError):
    """A background checkpoint commit failed. Raised on the NEXT
    ``save()``/``wait()``/``close()`` so an async failure is never
    silent; ``__cause__`` carries the original exception."""


#: Deterministic kill-point seam for the crash-matrix tests
#: (tests/test_ckpt_chaos.py): when TFR_CKPT_CHAOS_STAGE names a stage the
#: writer is about to enter, the writer touches TFR_CKPT_CHAOS_MARK and
#: parks forever — the parent test sees the marker and lands its SIGKILL
#: at EXACTLY that point (snapshot / shard / pre_manifest / manifest /
#: state). Inert (two env reads) outside the chaos tests.
_CHAOS_STAGE_ENV = "TFR_CKPT_CHAOS_STAGE"
_CHAOS_MARK_ENV = "TFR_CKPT_CHAOS_MARK"
#: pass through the armed stage this many times before parking, so the
#: test can land the kill on generation N with N-1 already complete
_CHAOS_SKIP_ENV = "TFR_CKPT_CHAOS_SKIP"
_chaos_hits: dict = {}


def _chaos_point(stage: str) -> None:
    if os.environ.get(_CHAOS_STAGE_ENV) != stage:
        return
    _chaos_hits[stage] = _chaos_hits.get(stage, 0) + 1
    if _chaos_hits[stage] <= int(os.environ.get(_CHAOS_SKIP_ENV, "0")):
        return
    mark = os.environ.get(_CHAOS_MARK_ENV)
    if mark:
        tmp = f"{mark}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(stage)
        os.replace(tmp, mark)
    while True:  # park here until the test's SIGKILL lands
        time.sleep(60)


def _fsync_dir(directory: str) -> None:
    """Best-effort fsync of a directory fd, making a just-landed rename
    durable against power loss (the file's bytes were fsynced before the
    rename; the directory entry needs its own flush on POSIX)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # graftlint: swallow(dirfd fsync is best-effort: some filesystems refuse O_RDONLY dir fds)
        pass


def durable_write(
    path: str,
    data: Optional[bytes] = None,
    write_fn: Optional[Callable] = None,
    chaos: Optional[str] = None,
) -> None:
    """The ONE stage-and-commit helper every checkpoint writer goes
    through: write ``data`` (or let ``write_fn(fh)`` write) to a
    pid-suffixed tmp twin, flush + fsync the FILE, ``os.replace`` into
    place, then best-effort fsync the directory — so a crash at any
    instant leaves either the old complete artifact or the new complete
    artifact, never a zero-length/torn stump. graftlint's atomic-write
    rule recognizes a call to this helper as the commit of a staged
    write (the manifest-last idiom)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            if write_fn is not None:
                write_fn(fh)
            if data is not None:
                fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        if chaos is not None:
            _chaos_point(chaos)
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


class FencedWriteError(OSError):
    """A ``durable_append`` found the file replaced underneath it (the
    inode changed): some other writer committed a NEW artifact at the
    same path — for the dispatcher journal, a promoted standby that
    bumped the generation. The append was NOT performed. OSError-shaped
    so existing journal-failure accounting treats it as a write failure,
    while callers that care (zombie-primary fencing) can tell it apart."""


def durable_append(
    path: str, data: bytes, expect_ino: Optional[int] = None
) -> int:
    """Append one record to ``path`` durably: open in append mode, write,
    flush, fsync — so a committed record survives a host crash, and a
    crash mid-append tears at most the UNCOMMITTED tail (readers of
    append-mode journals must replay to the newest consistent prefix;
    the dispatcher journal's line framing makes the torn tail
    detectable). Returns the file's inode.

    ``expect_ino`` is the fencing seam: when given and the opened file's
    inode differs, the file was atomically replaced by another writer
    (``durable_write``/``os.replace`` gives the path a fresh inode) and
    ``FencedWriteError`` is raised BEFORE any byte lands — a fenced
    writer can never interleave stale records into its successor's
    journal. graftlint's atomic-write rule recognizes this append+fsync
    shape (appends never tear previously committed bytes)."""
    with open(path, "ab") as fh:
        st = os.fstat(fh.fileno())
        if expect_ino is not None and st.st_ino != expect_ino:
            raise FencedWriteError(
                f"{path} was replaced underneath this writer "
                f"(inode {st.st_ino} != expected {expect_ino})"
            )
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
        return st.st_ino


def save_state(
    directory: str,
    state_or_iterator,
    process_index: Optional[int] = None,
    step: Optional[int] = None,
) -> str:
    """Atomically AND durably persist iterator state; returns the file
    path. The write goes through ``durable_write`` (fsync before rename),
    so a power-loss-shaped crash can never surface a zero-length
    "committed" state file."""
    state = _extract_state(state_or_iterator)
    os.makedirs(directory, exist_ok=True)
    path = state_path(directory, process_index)
    payload = _make_payload(state, step)
    durable_write(path, json.dumps(payload).encode("utf-8"), chaos="state")
    return path


def load_state(
    directory: str, process_index: Optional[int] = None
) -> Optional[IteratorState]:
    """Load this process's saved state; None if no checkpoint exists.
    An existing-but-unparseable file raises ``TornStateError`` (loud and
    named), never a raw ``json.JSONDecodeError``."""
    path = state_path(directory, process_index)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (ValueError, UnicodeDecodeError) as e:
        raise TornStateError(
            f"input-state file {path} exists but cannot be parsed "
            f"({type(e).__name__}: {e}) — a torn write from a crash that "
            "outran fsync, or foreign bytes. Delete the file to start "
            "fresh, or restore it alongside its model checkpoint."
        ) from e
    _check_version(payload, f"at {path}")
    return IteratorState.from_json(payload["state"])


# ---------------------------------------------------------------------------
# The background commit lane (shared by AsyncCheckpointer / AsyncStateSaver)
# ---------------------------------------------------------------------------


class _Commit:
    """One in-flight commit: the closure, its completion event, and the
    error slot the worker fills on failure."""

    __slots__ = ("step", "fn", "done", "error")

    def __init__(self, step: int, fn: Callable[[], None]):
        self.step = step
        self.fn = fn
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class _CommitWorker:
    """ONE daemon commit thread with at-most-one-in-flight backpressure.

    ``reserve()`` (caller's thread) waits out the previous commit — every
    blocked save lands a ``ckpt.commit_wait`` record, so backpressure is
    bounded AND observable — and re-raises any prior failure. ``submit``
    enqueues the next commit; the worker times it into the ``ckpt.commit``
    stage and counts the inflight gauge down. ``run_inline`` is the SYNC
    twin: same throttle, same metrics, caller's thread — what the bench
    A/B and ``sync=True`` checkpointers measure against.

    ``commit_delay_s`` is the seeded slow-disk seam (env
    ``TFR_CKPT_COMMIT_THROTTLE_S`` when unset): the bench/verify chaos
    legs throttle the commit path with it to force the sync twin into a
    ``ckpt_bound`` verdict while the async path stays compute_bound.
    """

    def __init__(self, metrics=None, commit_delay_s: Optional[float] = None):
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.metrics = metrics
        if commit_delay_s is None:
            env = os.environ.get("TFR_CKPT_COMMIT_THROTTLE_S")
            commit_delay_s = float(env) if env else 0.0
        self.commit_delay_s = float(commit_delay_s)
        self._queue: "queue.Queue[Optional[_Commit]]" = queue.Queue(maxsize=1)
        self._last: Optional[_Commit] = None
        self._thread: Optional[threading.Thread] = None

    # -- caller's thread -----------------------------------------------------

    def reserve(self) -> None:
        """Block until the previous commit (if any) finishes — counted as
        ``ckpt.commit_wait`` — and re-raise its failure loudly."""
        job = self._last
        if job is not None and not job.done.is_set():
            t0 = time.perf_counter()
            job.done.wait()
            waited = time.perf_counter() - t0
            self.metrics.add(
                "ckpt.commit_wait", records=1, seconds=waited, latency=waited
            )
        self.wait()

    def submit(self, step: int, fn: Callable[[], None]) -> None:
        """Hand one commit to the background thread. Callers must
        ``reserve()`` first (the snapshot buffers are reused, so the
        previous commit must have released them)."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-commit", daemon=True
            )
            self._thread.start()
        job = _Commit(step, fn)
        self._last = job
        self.metrics.gauge("ckpt.inflight", 1)
        self._queue.put(job)

    def run_inline(self, step: int, fn: Callable[[], None]) -> None:
        """The sync twin: execute the commit on the CALLER's thread under
        the same throttle and the same ``ckpt.commit`` stage."""
        self._execute(_Commit(step, fn))
        self.wait()

    def wait(self) -> None:
        """Drain the in-flight commit; re-raise its failure as
        ``CheckpointCommitError``."""
        job = self._last
        if job is None:
            return
        job.done.wait()
        self._last = None
        if job.error is not None:
            raise CheckpointCommitError(
                f"background checkpoint commit of step {job.step} failed: "
                f"{job.error!r}"
            ) from job.error

    def close(self) -> None:
        """Drain, then stop the worker thread."""
        try:
            self.wait()
        finally:
            if self._thread is not None and self._thread.is_alive():
                self._queue.put(None)
                self._thread.join(timeout=30)
            self._thread = None

    # -- worker thread -------------------------------------------------------

    def _execute(self, job: _Commit) -> None:
        t0 = time.perf_counter()
        try:
            if self.commit_delay_s:
                time.sleep(self.commit_delay_s)
            job.fn()
        except BaseException as e:  # graftlint: swallow(stored on the job; wait()/reserve() re-raise it as CheckpointCommitError)
            job.error = e
        finally:
            dt = time.perf_counter() - t0
            self.metrics.add("ckpt.commit", records=1, seconds=dt, latency=dt)
            self.metrics.gauge("ckpt.inflight", 0)
            job.done.set()

    def _run(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            self._execute(job)


# ---------------------------------------------------------------------------
# AsyncCheckpointer: sharded generations, manifest-last, background commit
# ---------------------------------------------------------------------------

_GEN_PREFIX = "gen-"
_MANIFEST_VERSION = 1


class AsyncCheckpointer:
    """Model state + O(1) payload, saved as SHARDED GENERATIONS with a
    manifest-last commit on a background thread (ISSUE 16 / ROADMAP #4).

    ``save(step, state, payload)`` splits into:

    - **snapshot** (caller's thread — all the train loop ever blocks on,
      the ``ckpt.snapshot`` stage): one ``jax.device_get`` of the pytree
      leaves copied into reusable host buffers, plus the JSON payload;
    - **commit** (the single background thread, ``ckpt.commit``): stage
      this process's shard npz into ``gen-<step>/`` (tmp + fsync +
      ``os.replace``), then — process 0, after the optional multihost
      ``barrier`` — write ``MANIFEST.json`` LAST through the same
      fsync-then-rename helper. A kill -9 at ANY point leaves the newest
      generation either fully committed (manifest present, all shards
      landed first) or invisible to ``restore``, which falls back to the
      newest COMPLETE generation.

    Layout (one shard per process, keyed like ``state_path``)::

        directory/
          gen-00000008/
            shard-00000.npz     # leaves + json meta, fsynced, renamed
            MANIFEST.json       # committed last => generation complete
          gen-00000016/ ...

    Backpressure: at most one commit in flight; the next ``save()`` waits
    on the previous commit (``ckpt.commit_wait``, never silently
    dropped); ``wait()``/``close()`` drain. Commits also sweep retired
    generations beyond ``keep`` and DEAD generations (shards without a
    manifest, older than the newest manifest — the orphans an interrupted
    commit leaves), extending the writer's ``_JOB_META``-style staging
    hygiene; each removal counts ``ckpt.generations_swept``.

    ``sync=True`` is the measurement twin: identical bytes and layout,
    commit executed inline on the caller's thread (what the bench A/B
    pins the async win against).

    Scope: single-controller and one-shard-per-process multihost jobs.
    On a multihost mesh pass ``barrier`` (e.g. a
    ``multihost_utils.sync_global_devices`` wrapper) so process 0 writes
    the manifest only after every process committed its shard.
    """

    MANIFEST = "MANIFEST.json"

    def __init__(
        self,
        directory: str,
        *,
        keep: Optional[int] = 2,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        sync: bool = False,
        commit_delay_s: Optional[float] = None,
        barrier: Optional[Callable[[], None]] = None,
        metrics=None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if process_index is None or process_count is None:
            try:
                import jax

                if process_index is None:
                    process_index = jax.process_index()
                if process_count is None:
                    process_count = jax.process_count()
            except Exception:  # graftlint: swallow(no distributed runtime: single process)
                process_index = process_index or 0
                process_count = process_count or 1
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.keep = keep
        self.sync = bool(sync)
        self._barrier = barrier
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.metrics = metrics
        self._worker = _CommitWorker(
            metrics=metrics, commit_delay_s=commit_delay_s
        )
        self._bufs: Optional[list] = None

    # -- layout --------------------------------------------------------------

    def _gen_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_GEN_PREFIX}{step:08d}")

    def _shard_name(self, process_index: int) -> str:
        return f"shard-{process_index:05d}.npz"

    # -- save ----------------------------------------------------------------

    def save(self, step: int, state_pytree, payload: Optional[dict] = None) -> None:
        """Snapshot ``state_pytree`` + ``payload`` for ``step`` and hand
        the commit to the background thread (inline when ``sync``)."""
        import jax

        self._worker.reserve()  # buffers are reused: previous commit first
        t0 = time.perf_counter()
        leaves, _ = jax.tree.flatten(state_pytree)
        host = jax.device_get(leaves)  # ONE transfer for the whole tree
        host = [np.asarray(h) for h in host]
        if self._bufs is None or len(self._bufs) != len(host) or any(
            b.shape != h.shape or b.dtype != h.dtype
            for b, h in zip(self._bufs, host)
        ):
            self._bufs = [np.array(h, copy=True) for h in host]
        else:
            for b, h in zip(self._bufs, host):
                np.copyto(b, h)
        meta = json.dumps(
            {"step": int(step), "payload": payload or {}}
        ).encode("utf-8")
        _chaos_point("snapshot")
        dt = time.perf_counter() - t0
        self.metrics.add("ckpt.snapshot", records=1, seconds=dt, latency=dt)
        bufs = self._bufs

        def commit() -> None:
            self._commit(int(step), bufs, meta)

        if self.sync:
            self._worker.run_inline(int(step), commit)
        else:
            self._worker.submit(int(step), commit)

    def _commit(self, step: int, leaves, meta: bytes) -> None:
        gen = self._gen_dir(step)
        os.makedirs(gen, exist_ok=True)
        for name in os.listdir(gen):
            # a previous life of this generation (killed mid-stage, then
            # re-reached after resume) may have left tmp orphans behind
            if ".tmp." in name:
                try:
                    os.remove(os.path.join(gen, name))
                except OSError:
                    pass
        shard = os.path.join(gen, self._shard_name(self.process_index))

        def write(fh) -> None:
            np.savez(
                fh,
                meta=np.frombuffer(meta, np.uint8),
                **{f"leaf_{i}": a for i, a in enumerate(leaves)},
            )

        durable_write(shard, write_fn=write, chaos="shard")
        self.metrics.count("ckpt.bytes_written", os.path.getsize(shard))
        _chaos_point("pre_manifest")
        if self._barrier is not None:
            self._barrier()  # every process's shard must land first
        if self.process_index == 0:
            manifest = {
                "version": _MANIFEST_VERSION,
                "step": step,
                "process_count": self.process_count,
                "shards": [
                    self._shard_name(i) for i in range(self.process_count)
                ],
            }
            durable_write(
                os.path.join(gen, self.MANIFEST),
                json.dumps(manifest).encode("utf-8"),
                chaos="manifest",
            )
            self._sweep(step)

    def _sweep(self, newest_step: int) -> None:
        """Generation hygiene, run after each manifest commit: retire
        complete generations beyond ``keep`` and remove DEAD ones —
        shards without a manifest older than the generation just
        committed, i.e. the orphans of an interrupted commit."""
        complete, dead = [], []
        for name in os.listdir(self.directory):
            if not name.startswith(_GEN_PREFIX):
                continue
            try:
                step = int(name[len(_GEN_PREFIX):])
            except ValueError:
                continue
            if os.path.exists(
                os.path.join(self.directory, name, self.MANIFEST)
            ):
                complete.append(step)
            elif step < newest_step:
                dead.append(step)
        complete.sort()
        retired = complete[: -self.keep] if self.keep else []
        for step in retired + dead:
            shutil.rmtree(self._gen_dir(step), ignore_errors=True)
            self.metrics.count("ckpt.generations_swept")

    # -- restore -------------------------------------------------------------

    def _complete_generations(self):
        """Ascending steps of every COMPLETE generation: manifest parses
        and every shard it names exists. Torn/garbage manifests read as
        incomplete — that is the recovery path, not an error."""
        out = []
        if not os.path.isdir(self.directory):
            return out
        for name in os.listdir(self.directory):
            if not name.startswith(_GEN_PREFIX):
                continue
            try:
                step = int(name[len(_GEN_PREFIX):])
            except ValueError:
                continue
            gen = os.path.join(self.directory, name)
            try:
                with open(os.path.join(gen, self.MANIFEST)) as fh:
                    manifest = json.load(fh)
            except (OSError, ValueError, UnicodeDecodeError):
                continue
            shards = manifest.get("shards") or []
            if shards and all(
                os.path.exists(os.path.join(gen, s)) for s in shards
            ):
                out.append(step)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self._complete_generations()
        return steps[-1] if steps else None

    def restore(self, template_pytree):
        """(step, pytree, payload) from the newest COMPLETE generation, or
        (None, template, None) when none exists. A generation whose shard
        bytes fail to load (impossible under the fsync-before-manifest
        contract, but disks lie) falls back one generation, loudly."""
        import jax

        for step in reversed(self._complete_generations()):
            shard = os.path.join(
                self._gen_dir(step), self._shard_name(self.process_index)
            )
            try:
                with np.load(shard) as z:
                    meta = json.loads(z["meta"].tobytes().decode("utf-8"))
                    leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
            except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
                print(
                    f"checkpoint generation {step} at {shard} unreadable "
                    f"({type(e).__name__}: {e}); falling back a generation",
                    file=sys.stderr,
                )
                continue
            _, treedef = jax.tree.flatten(template_pytree)
            state = jax.tree.unflatten(treedef, leaves)
            return meta["step"], state, meta.get("payload") or {}
        return None, template_pytree, None

    # -- lifecycle -----------------------------------------------------------

    def wait(self) -> None:
        """Drain the in-flight commit (re-raising its failure)."""
        self._worker.wait()

    def clear(self) -> None:
        """Remove every generation (the epoch-budget-exhausted path: the
        next run should start a fresh pass, not resume into an empty
        stream). Drains first so a commit can't resurrect one."""
        self._worker.wait()
        for name in os.listdir(self.directory):
            if name.startswith(_GEN_PREFIX):
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )

    def close(self) -> None:
        self._worker.close()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncStateSaver:
    """``save_state``'s async twin for the O(1) input-state JSON.

    State extraction — the only part that must observe the LIVE iterator
    at the save point — runs on the caller's thread (``ckpt.snapshot``);
    the fsync-then-rename write runs on the background commit thread
    (``ckpt.commit``), under the same at-most-one-in-flight /
    ``ckpt.commit_wait`` contract as ``AsyncCheckpointer``. Same file,
    same bytes as ``save_state`` — only the disk latency moves off the
    step path, so ``StepPhases``' ckpt phase measures microseconds."""

    def __init__(
        self,
        directory: str,
        process_index: Optional[int] = None,
        *,
        sync: bool = False,
        commit_delay_s: Optional[float] = None,
        metrics=None,
    ):
        self.directory = directory
        self.process_index = process_index
        self.sync = bool(sync)
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.metrics = metrics
        self._worker = _CommitWorker(
            metrics=metrics, commit_delay_s=commit_delay_s
        )

    def save(self, state_or_iterator, step: Optional[int] = None) -> str:
        """Snapshot the iterator position now; persist it in the
        background. Returns the (eventual) state-file path."""
        self._worker.reserve()
        t0 = time.perf_counter()
        payload = _make_payload(_extract_state(state_or_iterator), step)
        data = json.dumps(payload).encode("utf-8")
        path = state_path(self.directory, self.process_index)
        dt = time.perf_counter() - t0
        self.metrics.add("ckpt.snapshot", records=1, seconds=dt, latency=dt)

        def commit() -> None:
            os.makedirs(self.directory, exist_ok=True)
            durable_write(path, data, chaos="state")
            self.metrics.count("ckpt.bytes_written", len(data))

        if self.sync:
            self._worker.run_inline(step or 0, commit)
        else:
            self._worker.submit(step or 0, commit)
        return path

    def wait(self) -> None:
        self._worker.wait()

    def close(self) -> None:
        self._worker.close()

    def __enter__(self) -> "AsyncStateSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class TrainCheckpointer:
    """Model state + input position, saved ATOMICALLY together per step.

    The failure mode this removes: params restored from step N while the
    input pipeline resumes from wherever its own file last said — a
    silently skewed data order. Both items go into ONE orbax Composite
    checkpoint (``state`` pytree + ``input_state`` json), so orbax's own
    finalization makes the pairing atomic: a crash mid-save can never
    produce a restorable step with params but no matching input position.
    The iterator-state fingerprint still guards dataset identity on resume.

    Scope: single-controller jobs (the examples' shape). Multi-host
    pipelines, where every process owns a distinct input position, keep
    using per-process ``save_state``/``load_state`` alongside their model
    checkpointer.

    Usage::

        ckpt = TrainCheckpointer("/ckpts", max_to_keep=3)
        ...
        ckpt.save(step, {"params": params, "opt_state": opt_state}, it)
        ...
        step, state, resume = ckpt.restore(
            {"params": params, "opt_state": opt_state})
        with ds.batches(resume) as it: ...
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: Optional[int] = None,
        *,
        async_save: bool = True,
        metrics=None,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        if metrics is None:
            from tpu_tfrecord.metrics import METRICS as metrics  # noqa: N813
        self.metrics = metrics
        self.async_save = bool(async_save)
        try:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=self.async_save,
            )
        except TypeError:  # older orbax: sync-only manager
            self.async_save = False
            options = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state_pytree, state_or_iterator) -> None:
        """Persist the model pytree and the input position for ``step``.

        With ``async_save`` (the default) orbax finalizes the checkpoint
        on its own background thread under the same contract as
        ``AsyncCheckpointer``: at most one save in flight (blocking here
        counts as ``ckpt.commit_wait``), the caller only pays for the
        device snapshot (``ckpt.snapshot``), and ``close()`` drains."""
        if self.async_save and getattr(self._mgr, "is_saving_in_progress", None):
            if self._mgr.is_saving_in_progress():
                t0 = time.perf_counter()
                self._mgr.wait_until_finished()
                waited = time.perf_counter() - t0
                self.metrics.add(
                    "ckpt.commit_wait", records=1, seconds=waited, latency=waited
                )
        payload = _make_payload(_extract_state(state_or_iterator), step)
        t0 = time.perf_counter()
        self._mgr.save(
            step,
            args=self._ocp.args.Composite(
                state=self._ocp.args.StandardSave(state_pytree),
                input_state=self._ocp.args.JsonSave(payload),
            ),
            force=True,
        )
        dt = time.perf_counter() - t0
        self.metrics.add("ckpt.snapshot", records=1, seconds=dt, latency=dt)

    def wait(self) -> None:
        """Drain any in-flight background save."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, template_pytree):
        """(step, pytree, IteratorState) for the latest checkpoint, or
        (None, template, None) when none exists."""
        step = self._mgr.latest_step()
        if step is None:
            return None, template_pytree, None
        restored = self._mgr.restore(
            step,
            args=self._ocp.args.Composite(
                state=self._ocp.args.StandardRestore(template_pytree),
                input_state=self._ocp.args.JsonRestore(),
            ),
        )
        payload = restored["input_state"]
        _check_version(payload, f"in checkpoint step {step}")
        return step, restored["state"], IteratorState.from_json(payload["state"])

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
