"""Checkpoint persistence for dataset iterator state.

The reference has no resumability beyond the ``_SUCCESS`` marker (SURVEY.md
§5 checkpoint/resume: ABSENT). Here the iterator's O(1) state (epoch, shard
position, record offset — io/dataset.py) persists as a small JSON file per
process, written atomically, so a training job can bundle it with its model
checkpoint (e.g. alongside an orbax step directory) and resume mid-epoch.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from tpu_tfrecord.io.dataset import CheckpointableIterator, IteratorState

_FORMAT_VERSION = 1


def state_path(directory: str, process_index: Optional[int] = None) -> str:
    """Per-process state file ('_input_state.<pid>.json'): every host owns
    its own position, mirroring the per-host shard assignment."""
    if process_index is None:
        try:
            import jax

            process_index = jax.process_index()
        except Exception:
            process_index = 0
    # "_"-prefixed like _SUCCESS: shard discovery treats it as metadata, so a
    # state file inside a dataset directory can never be read as a shard.
    return os.path.join(directory, f"_input_state.{process_index}.json")


def save_state(
    directory: str,
    state_or_iterator,
    process_index: Optional[int] = None,
    step: Optional[int] = None,
) -> str:
    """Atomically persist iterator state; returns the file path."""
    state = (
        state_or_iterator.state()
        if isinstance(state_or_iterator, CheckpointableIterator)
        else state_or_iterator
    )
    os.makedirs(directory, exist_ok=True)
    path = state_path(directory, process_index)
    payload = {"version": _FORMAT_VERSION, "state": state.to_json()}
    if step is not None:
        payload["step"] = step
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)
    return path


def load_state(
    directory: str, process_index: Optional[int] = None
) -> Optional[IteratorState]:
    """Load this process's saved state; None if no checkpoint exists."""
    path = state_path(directory, process_index)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        payload = json.load(fh)
    if payload.get("version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported input-state version {payload.get('version')} at {path}"
        )
    return IteratorState.from_json(payload["state"])
