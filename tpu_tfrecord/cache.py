"""Columnar epoch cache: decode a shard once, mmap every epoch after.

BENCH_r05 shows warm-dataset ingest is decode-bound (cold_vs_bound 0.917 vs
cold_vs_disk_bound 0.375): the disk could feed ~2.4x more than the CPU can
protobuf-decode, and multi-epoch training re-pays the full tf.Example decode
every epoch. tf.data's snapshot/materialization work shows the canonical
fix — persist the DECODED representation once and serve later epochs from
it. Our decoded representation (`ColumnarBatch`: dense values + offsets +
blob buffers) is already an mmap-friendly flat layout, so the cache reload
is near zero-cost: numpy views straight over one mmap of the cache file, no
frame parsing, no per-record CRC, no protobuf decode.

On-disk container (one entry file per (shard, decode-fingerprint)):

    [MAGIC "TFRCACH1"][u32 container version]
    section payloads, 8-byte aligned, appended chunk by chunk
    [footer JSON][u64 footer length][u32 crc32c(footer)][TAIL "TFRCEND1"]

The footer carries the decode-options fingerprint, the source shard's
identity (path + size + mtime_ns), the data schema JSON, and a per-chunk
section table: for every chunk (start record index, num_rows) the ordered
column list, and for every column the sections it populates (values /
offsets / inner_offsets / blob / blob_offsets / mask) with dtype, shape,
byte offset, byte length, and CRC32C. The footer is written LAST and the
file renamed into place atomically, so a partially-written entry is never
visible under the final name; staging lives under ``_temporary/<job>/``
with the writer's ``_JOB_META`` liveness marker, and commits sweep orphaned
staging with the writer's own ``sweep_orphan_jobs``.

Validation model: an entry is fully verified ONCE per process at first open
(header, footer CRC, fingerprint, source identity, every section CRC — one
sequential pass, far cheaper than a decode epoch); every epoch after serves
zero-copy views with no re-verification. Any failure falls back to the
ground-truth TFRecord decode for that shard and the entry is re-written —
never a crash, never wrong rows. Concurrent writers (multi-process hosts)
race benignly: distinct staging files, last atomic rename wins, and a
reader keeps its mmap of whichever inode it opened.

``cache_max_bytes`` bounds the cache directory with an LRU sweep (entries
are atime-touched on hit — mtime is identity, see the entry registry;
oldest-atime entries evicted first, the just-committed entry protected).

Cache-file opens go through ``fs.local_open`` — the seam the deterministic
chaos injector (tpu_tfrecord.faults) patches — so fault-injection tests
reach this path like every other read mode.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import struct
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from tpu_tfrecord import fs as _fs, telemetry, wire
from tpu_tfrecord.columnar import Column, ColumnarBatch
from tpu_tfrecord.io import paths as p
from tpu_tfrecord.metrics import METRICS, logger, timed

MAGIC = b"TFRCACH1"
TAIL_MAGIC = b"TFRCEND1"
#: Container format version: part of both the header check and the decode
#: fingerprint, so a bump invalidates (misses) every existing entry.
VERSION = 1
ENTRY_SUFFIX = ".tfrc"

_HEADER = struct.Struct("<8sI")  # magic + container version
_TAIL = struct.Struct("<QI8s")  # footer length + footer crc + tail magic
_ALIGN = 8


class CacheOpenError(Exception):
    """An entry cannot be served. ``kind`` says why:

    - ``absent``: no entry file (or unreadable — treated as a plain miss)
    - ``stale``: fingerprint / container version / source shard identity
      changed — the entry describes data that no longer exists
    - ``corrupt``: bad magic, CRC mismatch, or unparseable metadata — the
      case the corrupt-cache fallback guarantee is about
    """

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


def default_cache_dir() -> str:
    """Per-host, per-USER default when ``cache="auto"`` is set without
    ``cache_dir``. The uid suffix keeps the directory private on multi-user
    hosts: a world-shared path with predictable entry names would let one
    user pre-stage crafted (self-consistently CRC'd) entries that another
    user's reads would validate and serve as training data."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"tpu_tfrecord_cache-{uid}")


def _norm_path(path: str) -> str:
    return path if _fs.has_scheme(path) else os.path.abspath(path)


def decode_fingerprint(ident: Dict[str, Any]) -> str:
    """Digest of everything that affects decoded chunk CONTENT: the data
    schema, record type, hash_buckets/pack fusion, verify_crc,
    max_record_bytes, requested partition fields — plus the container
    version. Options that only change HOW rows are produced (batch_size,
    num_workers, prefetch, readahead, mmap, retries, deadlines) are
    deliberately excluded: changing them still hits."""
    ident = dict(ident, container_version=VERSION)
    blob = json.dumps(ident, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:16]


def entry_filename(shard_path: str, fingerprint: str) -> str:
    """``<sha(source path)>-<fingerprint>.tfrc``: option changes create NEW
    entries (old ones age out via LRU) instead of overwriting, while a
    changed source shard overwrites its own entry on repopulate."""
    key = hashlib.sha256(_norm_path(shard_path).encode("utf-8")).hexdigest()[:20]
    return f"{key}-{fingerprint}{ENTRY_SUFFIX}"


def source_stat(shard_path: str, size_hint: Optional[int] = None) -> Dict[str, Any]:
    """The source shard identity an entry is keyed on. Local shards use
    (size, mtime_ns); scheme'd (remote) shards ask the backing filesystem
    for a modification stamp too (fsspec ``info``: mtime / LastModified /
    ETag where the store provides one) so a same-size remote rewrite still
    invalidates. A store that exposes none degrades to size-only
    invalidation — disclosed in the README."""
    if _fs.has_scheme(shard_path):
        size = int(size_hint) if size_hint else 0
        stamp = 0
        try:
            info = _fs.filesystem_for(shard_path).info(shard_path)
            if not size:
                size = int(info.get("size") or 0)
            raw = (
                info.get("mtime")
                or info.get("LastModified")
                or info.get("last_modified")
                or info.get("created")
                or info.get("ETag")
                or info.get("etag")
                or 0
            )
            if hasattr(raw, "timestamp"):  # datetime
                raw = raw.timestamp()
            if isinstance(raw, (int, float)):
                stamp = int(raw * 1e9) if raw else 0
            elif raw:  # opaque version tag (ETag): hash it into the slot
                stamp = int(
                    hashlib.sha256(str(raw).encode()).hexdigest()[:15], 16
                )
        except (AttributeError, OSError, KeyError, TypeError, ValueError):
            pass
        return {"path": shard_path, "size": size, "mtime_ns": stamp}
    st = os.stat(shard_path)
    return {
        "path": _norm_path(shard_path),
        "size": int(st.st_size),
        "mtime_ns": int(st.st_mtime_ns),
    }


# ---------------------------------------------------------------------------
# Container codec
# ---------------------------------------------------------------------------


def _section_crc(arr: np.ndarray) -> int:
    """CRC32C over a contiguous array's buffer WITHOUT a tobytes() copy
    when the native library is available — populate and open-time
    verification both pass multi-MB sections through here."""
    try:
        from tpu_tfrecord import _native

        if _native.available():
            import ctypes

            lib = _native.load()
            return int(
                lib.tfr_crc32c(
                    arr.ctypes.data_as(ctypes.c_char_p), arr.nbytes
                )
            )
    except Exception:  # noqa: BLE001 — fall back to the bytes path  # graftlint: swallow(native CRC unavailable: bytes-path CRC below returns the same value)
        pass
    return wire.crc32c(arr.tobytes())


def _column_buffers(col: Column) -> List[Tuple[str, np.ndarray]]:
    """The (role, contiguous array) sections a column populates, in a fixed
    role order so rebuild is deterministic."""
    out: List[Tuple[str, np.ndarray]] = []
    if col.values is not None:
        out.append(("values", np.ascontiguousarray(col.values)))
    if col.offsets is not None:
        out.append(("offsets", np.ascontiguousarray(col.offsets)))
    if col.inner_offsets is not None:
        out.append(("inner_offsets", np.ascontiguousarray(col.inner_offsets)))
    if col.blob is not None:
        out.append(("blob", np.frombuffer(col.blob, dtype=np.uint8)))
    if col.blob_offsets is not None:
        out.append(("blob_offsets", np.ascontiguousarray(col.blob_offsets)))
    if col.mask is not None:
        out.append(("mask", np.ascontiguousarray(col.mask)))
    return out


# Public names for the chunk-section serialization primitives: the data
# service's wire protocol (tpu_tfrecord.service_protocol) frames decoded
# chunks with exactly the cache container's section layout and per-section
# CRCs, so both serializers stay one implementation.
column_buffers = _column_buffers
section_crc = _section_crc


class CachedShard:
    """One validated, mmap'd cache entry: rebuilds ColumnarBatch chunks as
    zero-copy numpy views (bytes-like blobs are the one copy — downstream
    native calls require ``bytes``). The mmap stays alive as long as any
    served view does (numpy base chain); eviction/overwrite of the
    directory entry cannot invalidate it (POSIX inode semantics)."""

    def __init__(self, path: str, footer: Dict[str, Any], mm: mmap.mmap):
        self.path = path
        self.footer = footer
        self._mm = mm
        self._arr = np.frombuffer(mm, dtype=np.uint8)
        self.chunks: List[Dict[str, Any]] = footer["chunks"]
        self.rows = int(footer.get("rows", 0))

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    def chunk_span(self, i: int) -> Tuple[int, int]:
        meta = self.chunks[i]
        return int(meta["start"]), int(meta["num_rows"])

    def _section_array(self, sec: Dict[str, Any]) -> np.ndarray:
        off, nb = int(sec["off"]), int(sec["nbytes"])
        arr = self._arr[off : off + nb].view(np.dtype(sec["dtype"]))
        shape = sec.get("shape")
        if shape is not None and len(shape) != 1:
            arr = arr.reshape(shape)
        return arr

    def chunk_batch(self, i: int, dtype_of: Callable[[str], Any]) -> ColumnarBatch:
        """Materialize chunk ``i``: column buffers are views over the entry
        mmap; ``dtype_of(name)`` supplies the schema DataType (the
        fingerprint guarantees it matches what was cached)."""
        meta = self.chunks[i]
        cols: Dict[str, Column] = {}
        for cm in meta["columns"]:
            name = cm["name"]
            col = Column(name, dtype_of(name), hash_buckets=cm.get("hash_buckets"))
            for role, sec in cm["sections"]:
                if role == "blob":
                    off, nb = int(sec["off"]), int(sec["nbytes"])
                    col.blob = self._mm[off : off + nb]
                else:
                    setattr(col, role, self._section_array(sec))
            cols[name] = col
        return ColumnarBatch(cols, int(meta["num_rows"]))


def load_footer(path: str) -> Dict[str, Any]:
    """Parse (and CRC-check) an entry's footer without section verification.
    Raises CacheOpenError('corrupt'|'absent') — shared by the runtime open
    and the doctor's ``cache`` subcommand."""
    try:
        fh = _fs.local_open(path, "rb")
    except FileNotFoundError as e:
        raise CacheOpenError("absent", str(e)) from e
    except OSError as e:
        raise CacheOpenError("absent", f"unreadable cache entry {path}: {e}") from e
    with fh:
        header = wire.read_exact(fh, _HEADER.size)
        if len(header) < _HEADER.size:
            raise CacheOpenError("corrupt", f"cache entry too short: {path}")
        magic, version = _HEADER.unpack(header)
        if magic != MAGIC:
            raise CacheOpenError("corrupt", f"bad cache magic in {path}")
        if version != VERSION:
            raise CacheOpenError(
                "stale", f"cache container v{version} != v{VERSION} in {path}"
            )
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        if size < _HEADER.size + _TAIL.size:
            raise CacheOpenError("corrupt", f"cache entry truncated: {path}")
        fh.seek(size - _TAIL.size)
        tail_bytes = wire.read_exact(fh, _TAIL.size)
        if len(tail_bytes) < _TAIL.size:  # file shrank under us
            raise CacheOpenError("corrupt", f"cache entry truncated: {path}")
        flen, fcrc, tail = _TAIL.unpack(tail_bytes)
        if tail != TAIL_MAGIC or flen > size - _HEADER.size - _TAIL.size:
            raise CacheOpenError(
                "corrupt", f"bad cache tail in {path} (truncated write?)"
            )
        fh.seek(size - _TAIL.size - flen)
        blob = wire.read_exact(fh, flen)
        if len(blob) < flen or wire.crc32c(blob) != fcrc:
            raise CacheOpenError("corrupt", f"cache footer CRC mismatch in {path}")
        try:
            footer = json.loads(blob.decode("utf-8"))
        except ValueError as e:
            raise CacheOpenError(
                "corrupt", f"unparseable cache footer in {path}: {e}"
            ) from e
    if footer.get("version") != VERSION:
        raise CacheOpenError("stale", f"cache footer version mismatch in {path}")
    return footer


def open_entry_file(
    path: str,
    expect_fingerprint: Optional[str] = None,
    source: Optional[Dict[str, Any]] = None,
    verify_sections: bool = True,
    expect_columns: Optional[set] = None,
) -> CachedShard:
    """Open + validate one entry end to end: footer, fingerprint, source
    identity, (by default) every section CRC, and — when the caller knows
    its decode plan — that every chunk carries exactly ``expect_columns``.
    Raises CacheOpenError."""
    footer = load_footer(path)
    if expect_fingerprint is not None and footer.get("fingerprint") != expect_fingerprint:
        raise CacheOpenError(
            "stale",
            f"cache fingerprint {footer.get('fingerprint')} != "
            f"{expect_fingerprint} in {path}",
        )
    if source is not None and not _source_matches(footer, source):
        raise CacheOpenError(
            "stale", f"source shard changed since {path} was written"
        )
    try:
        fh = _fs.local_open(path, "rb")
    except OSError as e:
        raise CacheOpenError("absent", f"unreadable cache entry {path}: {e}") from e
    with fh:
        try:
            mm = mmap.mmap(fh.fileno(), 0, prot=mmap.PROT_READ)
        except (OSError, ValueError) as e:
            raise CacheOpenError("corrupt", f"cannot mmap {path}: {e}") from e
    try:
        entry = _verified_entry(path, footer, mm, verify_sections, expect_columns)
    except CacheOpenError:
        raise
    except Exception as e:  # noqa: BLE001
        # footer JSON that parsed and CRC-matched but has the wrong SHAPE
        # (missing keys, non-dict values — a foreign or buggy producer):
        # same contract as any corrupt entry, for the doctor and the
        # runtime alike
        raise CacheOpenError(
            "corrupt", f"malformed cache footer structure in {path}: {e}"
        ) from e
    return entry


def _verified_entry(
    path: str,
    footer: Dict[str, Any],
    mm: mmap.mmap,
    verify_sections: bool,
    expect_columns: Optional[set] = None,
) -> CachedShard:
    entry = CachedShard(path, footer, mm)
    if verify_sections:
        size = len(entry._arr)
        next_start = 0
        for meta in footer["chunks"]:
            start, num_rows = int(meta["start"]), int(meta["num_rows"])
            if start != next_start or num_rows < 0:
                # populate writes one contiguous fresh pass from record 0;
                # anything else is a malformed producer
                raise CacheOpenError(
                    "corrupt", f"non-contiguous chunk table in {path}"
                )
            next_start = start + num_rows
            if expect_columns is not None:
                names = {str(cm["name"]) for cm in meta["columns"]}
                if names != expect_columns:
                    # a fingerprint-matching entry whose columns differ from
                    # this dataset's decode plan must fall back, not KeyError
                    # in the serve path's dtype lookup
                    raise CacheOpenError(
                        "corrupt",
                        f"cached columns {sorted(names)} != expected "
                        f"{sorted(expect_columns)} in {path}",
                    )
            for cm in meta["columns"]:
                str(cm["name"])  # serve-time lookups must not KeyError
                roles = {role for role, _sec in cm["sections"]}
                for role, sec in cm["sections"]:
                    off, nb = int(sec["off"]), int(sec["nbytes"])
                    if off < 0 or nb < 0 or off + nb > size:
                        # nb < 0 would make every later check vacuous over
                        # an empty slice (crc32c(b"") == 0)
                        raise CacheOpenError(
                            "corrupt", f"section out of bounds in {path}"
                        )
                    # geometry must be self-consistent so serve-time view/
                    # reshape/row-indexing can never raise (a CRC-valid
                    # footer from a buggy producer must fall back, not
                    # crash the epoch)
                    try:
                        dt = np.dtype(sec["dtype"])
                    except TypeError as e:
                        raise CacheOpenError(
                            "corrupt", f"bad section dtype in {path}: {e}"
                        ) from e
                    shape = sec.get("shape")
                    n_items = 1
                    for dim in shape if shape is not None else ():
                        n_items *= int(dim)
                    if nb % dt.itemsize or (
                        shape is not None and n_items * dt.itemsize != nb
                    ):
                        raise CacheOpenError(
                            "corrupt",
                            f"section shape/dtype inconsistent with its "
                            f"byte length in {path}",
                        )
                    # per-row sections must cover exactly num_rows rows
                    # (offsets carry the +1 fence) — consumers index them
                    # by row without bounds checks
                    n = nb // dt.itemsize
                    first_dim = int(shape[0]) if shape else n
                    bad_rows = (
                        (role == "mask" and n != num_rows)
                        or (role == "offsets" and n != num_rows + 1)
                        or (
                            role == "values"
                            and "offsets" not in roles
                            and first_dim != num_rows
                        )
                        or (
                            role == "blob_offsets"
                            and "offsets" not in roles
                            and n != num_rows + 1
                        )
                    )
                    if bad_rows:
                        raise CacheOpenError(
                            "corrupt",
                            f"section row count inconsistent with chunk "
                            f"num_rows in {path}",
                        )
                    if _section_crc(entry._arr[off : off + nb]) != int(sec["crc"]):
                        raise CacheOpenError(
                            "corrupt",
                            f"section CRC mismatch at offset {off} in {path}",
                        )
    return entry


class CachePopulator:
    """Streams one shard's decoded chunks into a staging entry file and
    commits it atomically. IO failures KILL the populator silently (logged
    once) — cache writing must never fail an epoch."""

    def __init__(self, cache: "ShardCache", shard_path: str, source: Dict[str, Any]):
        self._cache = cache
        self._source = source
        self.source_path = shard_path
        self.final_path = os.path.join(
            cache.cache_dir, entry_filename(shard_path, cache.fingerprint)
        )
        self._job_id = uuid.uuid4().hex[:12]
        self._tmp_dir = os.path.join(cache.cache_dir, p.TEMP_PREFIX, self._job_id)
        os.makedirs(self._tmp_dir, exist_ok=True)
        try:
            self._write_marker()
            self._tmp_path = os.path.join(
                self._tmp_dir, os.path.basename(self.final_path)
            )
            self._fh = open(self._tmp_path, "wb")
            self._fh.write(_HEADER.pack(MAGIC, VERSION))
        except BaseException:
            # a failed setup must not strand the staging dir: the marker
            # names a LIVE pid, so sweep_orphan_jobs would never reclaim it
            import shutil

            fh = getattr(self, "_fh", None)
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
            shutil.rmtree(self._tmp_dir, ignore_errors=True)
            raise
        self._pos = _HEADER.size
        self._chunks: List[Dict[str, Any]] = []
        self._rows = 0
        self._dead = False

    def _write_marker(self) -> None:
        # the writer's liveness marker, so sweep_orphan_jobs can reclaim
        # staging left by a crashed populate (same dead-pid / stale-lease
        # tests as write jobs)
        from tpu_tfrecord.io.writer import _JOB_MARKER, job_marker_payload

        try:
            with open(os.path.join(self._tmp_dir, _JOB_MARKER), "wb") as fh:
                fh.write(job_marker_payload())
        except OSError:
            pass

    def _kill(self, why: str) -> None:
        self._dead = True
        # the swallowed append/commit failures land here: one counter per
        # aborted populate job, so "caching never fails an epoch" stays
        # observable on the pulse/doctor instead of silently serving cold
        METRICS.count("cache.populate_errors")
        logger.warning(
            "tfrecord.cache populate of %s disabled: %s", self.final_path, why
        )
        self.abort()

    def _put(self, arr: np.ndarray) -> Dict[str, Any]:
        pad = (-self._pos) % _ALIGN
        if pad:
            self._fh.write(b"\0" * pad)
            self._pos += pad
        # arr is contiguous (see _column_buffers): write its buffer and CRC
        # it in place — no tobytes() copy of multi-MB sections
        self._fh.write(arr.data)
        sec = {
            "off": self._pos,
            "nbytes": arr.nbytes,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "crc": _section_crc(arr),
        }
        self._pos += arr.nbytes
        return sec

    def append(self, batch: ColumnarBatch, start: int) -> None:
        """Serialize one decoded chunk (sections + table row)."""
        if self._dead:
            return
        try:
            cols_meta = []
            for name, col in batch.columns.items():
                sections = [
                    (role, self._put(arr)) for role, arr in _column_buffers(col)
                ]
                cols_meta.append(
                    {
                        "name": name,
                        "hash_buckets": col.hash_buckets,
                        "sections": sections,
                    }
                )
            self._chunks.append(
                {
                    "start": int(start),
                    "num_rows": int(batch.num_rows),
                    "columns": cols_meta,
                }
            )
            self._rows += batch.num_rows
        except Exception as e:  # noqa: BLE001 — caching never fails an epoch  # graftlint: swallow(counted in _kill (cache.populate_errors); caching never fails an epoch)
            self._kill(f"append failed: {e}")

    def commit(self) -> bool:
        """Footer + atomic rename into place; then staging hygiene and the
        LRU sweep. Returns True when the entry landed."""
        if self._dead:
            return False
        with timed("cache.commit", METRICS), \
                telemetry.span("cache.commit", shard=self.source_path):
            return self._commit_inner()

    def _commit_inner(self) -> bool:
        try:
            footer = {
                "version": VERSION,
                "fingerprint": self._cache.fingerprint,
                "source": self._source,
                "ident": self._cache.ident,
                "rows": self._rows,
                "chunks": self._chunks,
            }
            blob = json.dumps(footer, sort_keys=True, default=str).encode("utf-8")
            self._fh.write(blob)
            self._fh.write(_TAIL.pack(len(blob), wire.crc32c(blob), TAIL_MAGIC))
            self._fh.close()
            self._pos += len(blob) + _TAIL.size
            # the rename may REPLACE a previous generation (corrupt-entry
            # rewrite, changed source): the sweep's running total must see
            # the NET directory growth, not the full entry size
            try:
                replaced = os.path.getsize(self.final_path)
            except OSError:
                replaced = 0
            # resolved at call time so the chaos injector's rename faults
            # reach the cache commit like any writer commit
            _fs.filesystem_for(self._cache.cache_dir).rename(
                self._tmp_path, self.final_path
            )
        except Exception as e:  # noqa: BLE001 — caching never fails an epoch  # graftlint: swallow(counted in _kill (cache.populate_errors); caching never fails an epoch)
            self._kill(f"commit failed: {e}")
            return False
        METRICS.count("cache.bytes_written", self._pos)
        self._cleanup_staging()
        self._cache.sweep(
            protect=self.final_path, added_bytes=self._pos - replaced
        )
        return True

    def abort(self) -> None:
        try:
            if not self._fh.closed:
                self._fh.close()
        except OSError:
            pass
        self._cleanup_staging()

    def _cleanup_staging(self) -> None:
        from tpu_tfrecord.io.writer import sweep_orphan_jobs

        fs = _fs.filesystem_for(self._cache.cache_dir)
        try:
            fs.rmtree(self._tmp_dir, ignore_errors=True)
        except OSError:
            pass
        # reclaim staging orphaned by CRASHED populates (dead local pid or
        # stale cross-host lease), then drop the shared parent when empty
        sweep_orphan_jobs(fs, self._cache.cache_dir, keep=self._job_id)
        try:
            fs.rmdir(os.path.join(self._cache.cache_dir, p.TEMP_PREFIX))
        except OSError:
            pass


#: Process-wide registry of VALIDATED entries, so the common
#: dataset-per-epoch pattern (a fresh TFRecordDataset each epoch) does not
#: re-pay the full section-CRC verification pass per dataset object. Keyed
#: by (abspath, inode, size, mtime_ns): the atomic-rename commit gives a
#: rewritten entry a new inode, an in-place modification (corruption, a
#: byte-flip test) changes mtime, and the LRU hit-touch deliberately bumps
#: ONLY atime so it never invalidates the key. Inserts prune superseded
#: generations of the same path and evictions drop theirs, so the registry
#: stays bounded by the LIVE entry set (each value pins one mmap of clean,
#: evictable pages).
_REGISTRY_LOCK = threading.Lock()
_ENTRY_REGISTRY: Dict[Tuple[str, int, int, int], CachedShard] = {}


def _registry_key(path: str) -> Tuple[str, int, int, int]:
    st = os.stat(path)
    return (
        os.path.abspath(path),
        int(st.st_ino),
        int(st.st_size),
        int(st.st_mtime_ns),
    )


def _registry_put(key: Tuple[str, int, int, int], entry: CachedShard) -> None:
    """Insert, PRUNING any superseded generation of the same entry path —
    a rewritten/invalidated entry's old value must not pin its mmap (and
    the deleted inode's disk blocks) for the process lifetime."""
    with _REGISTRY_LOCK:
        for k in [k for k in _ENTRY_REGISTRY if k[0] == key[0] and k != key]:
            del _ENTRY_REGISTRY[k]
        _ENTRY_REGISTRY[key] = entry


def _registry_drop_path(path: str) -> None:
    """Forget every generation of one entry path (eviction, failed
    revalidation)."""
    apath = os.path.abspath(path)
    with _REGISTRY_LOCK:
        for k in [k for k in _ENTRY_REGISTRY if k[0] == apath]:
            del _ENTRY_REGISTRY[k]


def release_registry(cache_dir: Optional[str] = None) -> int:
    """Drop validated-entry registrations (all, or those under one cache
    dir), unpinning their mmaps — for callers that delete a cache dir
    out-of-band (the bench's throwaway probe dir, tests): rmtree alone
    frees no disk while the registry still maps the inodes. Entries also
    held by live datasets stay alive through those references. Returns the
    number released."""
    with _REGISTRY_LOCK:
        if cache_dir is None:
            n = len(_ENTRY_REGISTRY)
            _ENTRY_REGISTRY.clear()
            return n
        prefix = os.path.abspath(cache_dir) + os.sep
        victims = [k for k in _ENTRY_REGISTRY if k[0].startswith(prefix)]
        for k in victims:
            del _ENTRY_REGISTRY[k]
        return len(victims)


def _touch_atime(path: str) -> None:
    """LRU usage stamp: bump atime, PRESERVE mtime (mtime is part of the
    registry identity — a plain utime would alias a hit with a rewrite)."""
    import time as _time

    try:
        st = os.stat(path)
        os.utime(path, ns=(_time.time_ns(), st.st_mtime_ns))
    except OSError:
        pass


def _source_matches(footer: Dict[str, Any], source: Dict[str, Any]) -> bool:
    src = footer.get("source") or {}
    return int(src.get("size", -1)) == int(source["size"]) and int(
        src.get("mtime_ns", -1)
    ) == int(source["mtime_ns"])


class ShardCache:
    """Per-dataset cache manager: one validated CachedShard per source
    shard, kept for the life of the dataset (epoch 2+ serves without
    re-verifying; fresh dataset objects reuse the process-level registry),
    plus populate / eviction plumbing. Thread-safe (parallel shard workers
    hit it concurrently)."""

    def __init__(
        self,
        cache_dir: str,
        ident: Dict[str, Any],
        max_bytes: Optional[int] = None,
        expect_columns: Optional[set] = None,
    ):
        self.cache_dir = os.fspath(cache_dir)
        if _fs.has_scheme(self.cache_dir):
            # the serve path mmaps entry files; a remote cache_dir would
            # fail far from the config error that caused it
            raise ValueError(
                f"cache_dir must be a local path (the cache is mmap-served); "
                f"got {self.cache_dir!r}"
            )
        self.ident = ident
        self.fingerprint = decode_fingerprint(ident)
        self.max_bytes = max_bytes
        # the exact column set a decoded chunk carries (data columns minus
        # pack members, plus group names and partition fields): entries
        # whose chunks differ are corrupt, not servable
        self.expect_columns = set(expect_columns) if expect_columns else None
        self._lock = threading.Lock()
        self._entries: Dict[str, CachedShard] = {}
        # source identity computed by the last open_entry MISS, consumed by
        # the populator() that follows it — for remote shards source_stat
        # is a metadata round-trip, paid once per miss and NEVER on the
        # held-entry (warm epoch) path
        self._miss_source: Dict[str, Dict[str, Any]] = {}
        # running directory size (None = not yet scanned): lets each
        # populate commit answer "under budget?" without re-listing and
        # re-statting the whole cache dir — O(1) per commit instead of the
        # O(entries) that made a 10k-shard populate epoch quadratic. Other
        # processes' commits drift it; every actual sweep rescans exactly.
        self._total_bytes: Optional[int] = None
        os.makedirs(self.cache_dir, exist_ok=True)

    def entry_path(self, shard_path: str) -> str:
        return os.path.join(
            self.cache_dir, entry_filename(shard_path, self.fingerprint)
        )

    def open_entry(
        self, shard, source: Optional[Dict[str, Any]] = None
    ) -> Optional[CachedShard]:
        """Serve-side lookup: a validated entry (hit) or None (miss —
        populate and decode from the source). ``source`` is the shard's
        precomputed identity (callers that also populate pass it so remote
        shards pay ONE metadata round-trip per miss, not two). Counts
        ``cache.hits`` / ``cache.misses`` per shard-epoch and
        ``cache.corrupt_fallbacks`` when the miss was a CRC/format
        failure."""
        path = self.entry_path(shard.path)
        with self._lock:
            entry = self._entries.get(shard.path)
        if entry is not None:
            _touch_atime(path)  # a served entry must look hot to the LRU
            METRICS.count("cache.hits")
            return entry
        try:
            if source is None:
                source = source_stat(shard.path, shard.size)
            with self._lock:
                self._miss_source[shard.path] = source
            key = None
            try:
                key = _registry_key(path)
            except OSError:
                pass
            if key is not None:
                with _REGISTRY_LOCK:
                    entry = _ENTRY_REGISTRY.get(key)
                if (
                    entry is not None
                    and entry.footer.get("fingerprint") == self.fingerprint
                    and _source_matches(entry.footer, source)
                ):
                    # already section-verified by an earlier dataset in
                    # this process; same inode+size+mtime => same bytes
                    with self._lock:
                        self._entries[shard.path] = entry
                    _touch_atime(path)
                    METRICS.count("cache.hits")
                    return entry
                if entry is not None:
                    _registry_drop_path(path)  # superseded: unpin its mmap
                entry = None
            # the once-per-process full section verification: worth a
            # latency histogram of its own — a slow first epoch on a big
            # cache is usually THIS, not decode. Timed by hand, NOT via
            # ``timed``: a routine cold miss raises CacheOpenError here,
            # and the error-counting exit would report cache.open.errors
            # on every perfectly healthy first epoch (the span still
            # self-marks failed=1, which a trace reader wants to see)
            _t0 = time.perf_counter()
            try:
                with telemetry.span("cache.open", shard=shard.path):
                    entry = open_entry_file(
                        path,
                        expect_fingerprint=self.fingerprint,
                        source=source,
                        expect_columns=self.expect_columns,
                    )
            finally:
                _dt = time.perf_counter() - _t0
                METRICS.add("cache.open", seconds=_dt, latency=_dt)
            if key is not None:
                _registry_put(key, entry)
        except CacheOpenError as e:
            if e.kind == "corrupt":
                METRICS.count("cache.corrupt_fallbacks")
                logger.warning(
                    "tfrecord.cache corrupt entry for %s — falling back to "
                    "TFRecord decode and rewriting: %s", shard.path, e,
                )
            METRICS.count("cache.misses")
            return None
        except OSError as e:
            # an injected/transient open fault is a miss, never a crash
            METRICS.count("cache.misses")
            logger.warning("tfrecord.cache open failed for %s: %s", path, e)
            return None
        except Exception as e:  # noqa: BLE001
            # metadata that parsed but has the wrong shape (a corruption
            # the ~2^-32 footer CRC false-negative window lets through):
            # same contract as any corrupt entry — fall back, rewrite
            METRICS.count("cache.corrupt_fallbacks")
            METRICS.count("cache.misses")
            logger.warning(
                "tfrecord.cache malformed entry for %s — falling back to "
                "TFRecord decode and rewriting: %s", shard.path, e,
            )
            return None
        with self._lock:
            self._entries[shard.path] = entry
        _touch_atime(path)  # LRU usage stamp
        METRICS.count("cache.hits")
        return entry

    def peek_entry(self, shard) -> bool:
        """Advisory probe: will ``open_entry`` (as the serve path is
        about to call it) be a hit? Used by the data service's
        shared-cache accounting — a decode worker stamps ``cached: true``
        on its eof so the dispatcher can count fleet-wide warm-cache
        completions per tenant. Deliberately side-effect-free: no
        ``cache.hits``/``cache.misses`` counters, no registry mutation,
        no section-CRC verification pass (a held or registry-known entry
        answers from memory; otherwise only the footer metadata is
        read). A True here that open_entry then fails to serve (entry
        corrupted in the microseconds between) merely overstates one
        counter — it can never affect served rows."""
        with self._lock:
            if shard.path in self._entries:
                return True
        path = self.entry_path(shard.path)
        try:
            key = _registry_key(path)
        except OSError:
            return False  # no entry file at all
        with _REGISTRY_LOCK:
            entry = _ENTRY_REGISTRY.get(key)
        try:
            source = source_stat(shard.path, shard.size)
            if entry is not None:
                return (
                    entry.footer.get("fingerprint") == self.fingerprint
                    and _source_matches(entry.footer, source)
                )
            footer = load_footer(path)
        except Exception:  # noqa: BLE001 — unreadable/corrupt = not cached  # graftlint: swallow(side-effect-free probe: unreadable reads as not-cached)
            return False
        return (
            footer.get("fingerprint") == self.fingerprint
            and _source_matches(footer, source)
        )

    def populator(
        self, shard, source: Optional[Dict[str, Any]] = None
    ) -> Optional[CachePopulator]:
        """Start a populate for one shard; None when staging cannot be set
        up (the epoch proceeds uncached). Reuses the source identity the
        preceding open_entry miss computed, so a miss costs one metadata
        round-trip total."""
        try:
            if source is None:
                with self._lock:
                    source = self._miss_source.pop(shard.path, None)
            if source is None:
                source = source_stat(shard.path, shard.size)
            return CachePopulator(self, shard.path, source)
        except OSError as e:
            logger.warning(
                "tfrecord.cache cannot stage entry for %s: %s", shard.path, e
            )
            return None

    def forget(self, shard_path: str) -> None:
        """Drop a held entry (tests / explicit invalidation)."""
        with self._lock:
            self._entries.pop(shard_path, None)

    def sweep(
        self, protect: Optional[str] = None, added_bytes: int = 0
    ) -> List[str]:
        """LRU eviction to ``max_bytes``: oldest-atime entries go first
        (hits re-stamp atime explicitly — reliable even under relatime);
        ``protect`` (the just-committed entry) is never evicted. The
        running-total fast path skips the full directory scan while the
        budget clearly holds (``added_bytes`` = what the caller just
        committed). Never raises."""
        if not self.max_bytes:
            return []
        with self._lock:
            if self._total_bytes is not None:
                self._total_bytes += added_bytes
                if self._total_bytes <= self.max_bytes:
                    return []
        evicted: List[str] = []
        try:
            entries = []
            for name in os.listdir(self.cache_dir):
                if not name.endswith(ENTRY_SUFFIX):
                    continue
                path = os.path.join(self.cache_dir, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                entries.append((st.st_atime_ns, st.st_size, path))
            total = sum(sz for _, sz, _ in entries)
            for _mt, sz, path in sorted(entries):
                if total <= self.max_bytes:
                    break
                if protect is not None and os.path.basename(path) == os.path.basename(protect):
                    continue
                try:
                    os.remove(path)
                except OSError:
                    continue
                _registry_drop_path(path)  # unpin the evicted mmap
                total -= sz
                evicted.append(path)
                METRICS.count("cache.evictions")
            with self._lock:
                self._total_bytes = total  # exact again after the rescan
        except OSError:
            pass
        return evicted


# ---------------------------------------------------------------------------
# Offline inspection (tools/tfrecord_doctor.py `cache` subcommand)
# ---------------------------------------------------------------------------


def inspect_entry(path: str) -> Dict[str, Any]:
    """Full offline report for one entry file: footer fields, section-CRC
    verification, and source-shard freshness. ``status`` is one of
    ``ok`` | ``corrupt`` | ``stale`` | ``source_missing``."""
    report: Dict[str, Any] = {
        "entry": path,
        "size_bytes": None,
        "status": "ok",
    }
    try:
        report["size_bytes"] = os.path.getsize(path)
    except OSError:
        pass
    try:
        entry = open_entry_file(path, verify_sections=True)
    except CacheOpenError as e:
        report["status"] = "stale" if e.kind == "stale" else "corrupt"
        report["error"] = str(e)
        try:  # a stale-but-parseable footer still carries useful identity
            footer = load_footer(path)
            report["fingerprint"] = footer.get("fingerprint")
            report["source"] = footer.get("source")
        except CacheOpenError:
            pass
        return report
    footer = entry.footer
    src = footer.get("source") or {}
    report.update(
        {
            "fingerprint": footer.get("fingerprint"),
            "source": src,
            "rows": entry.rows,
            "chunks": entry.num_chunks,
            "crc_verified": True,
        }
    )
    src_path = src.get("path")
    if src_path and _fs.has_scheme(src_path):
        # remote source: same freshness probe the runtime uses (backend
        # size + mtime/ETag stamp); an unreachable store must not claim
        # the shard vanished — report unverified instead
        try:
            if not _fs.filesystem_for(src_path).exists(src_path):
                report["status"] = "source_missing"
                return report
            if not _source_matches(footer, source_stat(src_path)):
                report["status"] = "stale"
        except Exception:  # noqa: BLE001 — store unavailable, not stale  # graftlint: swallow(doctor report discloses source_check=unavailable)
            report["source_check"] = "unavailable"
        return report
    if src_path:
        try:
            current = source_stat(src_path)
        except OSError:
            report["status"] = "source_missing"
            return report
        if not _source_matches(footer, current):
            report["status"] = "stale"
    return report


def iter_entry_reports(cache_dir: str) -> Iterator[Dict[str, Any]]:
    """One inspect_entry report per ``*.tfrc`` file under ``cache_dir``.
    An unreadable directory RAISES (OSError): an audit that silently
    reports zero entries would read as a healthy empty cache."""
    for name in sorted(os.listdir(cache_dir)):
        if name.endswith(ENTRY_SUFFIX):
            yield inspect_entry(os.path.join(cache_dir, name))
