"""Disaggregated data service: dispatcher + decode workers + trainer consumers.

The single biggest scale unlock named by ROADMAP #1, straight from "tf.data
service: A Case for Disaggregating ML Input Data Processing" (PAPERS.md):
decode CPU must scale independently of accelerator count. A **dispatcher**
process owns shard->worker leasing (the same deterministic interleaved
assignment as per-host shard selection — ``io.paths.interleave``, one
owner); N **decode workers** read/decode/pack shards — serving from the
columnar epoch cache when warm, populating it on miss via
``CachePopulator``'s atomic staging — and stream length-framed,
CRC-stamped chunks (``service_protocol``) to M **trainer consumers**; the
consumer side is just an alternative chunk source for
``TFRecordDataset._chunk_stream``, so batches, checkpoints, shuffling, and
every downstream layer are byte-identical to local reads.

Robustness is the contract, not a feature:

- **Worker death**: workers heartbeat the dispatcher; a SIGKILLed worker's
  lease expires (heartbeat age > ``lease_ttl_s``, the same
  staleness-by-heartbeat model the fleet aggregator uses) and the shard is
  re-routed to a surviving worker (``service.lease_reassignments``).
  Exactly-once delivery is CONSUMER-owned: the consumer tracks its
  position (``IteratorState`` semantics — absolute record offsets within
  the shard), re-requests from its acked offset, and drops/slices any
  redelivered prefix (``service.redelivered_dropped``) — redelivery can
  never double-count, and a worker that dies mid-chunk can never leave a
  hole, because the next worker decodes the same deterministic stream.
- **Dispatcher death**: every assignment-state mutation is journaled —
  one fsynced delta line per mutation over a durable snapshot line
  (``checkpoint.durable_append`` / ``durable_write``); a restarted
  dispatcher replays the newest consistent prefix (workers, leases, done
  set, reassignment count, trace identity) and workers re-register
  through their heartbeat loops. Consumers ride ``RetryPolicy``-shaped
  backoff through the outage and resume from their acked position.
  ISSUE 17 removes the dispatcher SPOF outright: the lease space is
  **partitioned** across K dispatchers by rendezvous-hashing the tenant
  digest over a static ``PartitionMap`` (no coordination service — every
  consumer/worker/scaler parses the same spec), and each partition gets
  a **warm standby** that tails the primary's journal, detects death by
  ping loss, promotes itself with a bumped fencing generation (the
  journal compaction's ``os.replace`` gives the file a new inode, so a
  resurrected zombie's next append is rejected —
  ``service.fenced_writes`` — and the zombie demotes), and best-effort
  adopts the dead primary's advertised address. A primary whose journal
  writes keep failing demotes ITSELF (``service.demotions``) rather than
  run unjournaled under a standby that would recover stale state.
- **Service unreachable**: past ``service_fallback_ms`` without progress
  the consumer degrades to DIRECT LOCAL reads of the same shard
  (``service.fallbacks``) — byte-identical rows either way, because the
  fallback is literally ``TFRecordDataset._decode_shard``. Later shards
  probe the service with one quick attempt until it heals.

Every socket hop rides ``service_protocol`` framing (masked-CRC control
frames; chunk sections CRC-stamped with the cache container's own
primitives) and is fault-injectable through the seeded ``FaultPlan``
socket seam (``connect``/``recv`` rules), same replayable ledger as file
faults.

ISSUE 12 makes the service elastic and multi-tenant:

- **Multi-tenant leasing**: the dispatcher keys its lease table by the
  consumer's ``tenant`` — a digest of the dataset's decode fingerprint
  (``TFRecordDataset._cache_ident``, the exact identity the columnar
  epoch cache keys entries by) plus the global shard list. M consumers
  from DIFFERENT jobs (different batch sizes, prefetch depths, resume
  points) over the same dataset share ONE lease table, one done-set,
  and — because the workers' epoch cache uses the same fingerprint —
  one warm columnar cache: job 2 over an already-served dataset is
  served entirely from cache (zero ground-truth reads, pinned by the
  worker's ``cache.hits``/``cache.misses`` counters for local sources
  and the Range server's file-GET counter for remote ones). Jobs with
  different fingerprints get isolated lease tables and per-tenant
  counters. Counters: ``service.tenants`` (distinct fingerprints seen),
  ``service.shared_cache_hits`` (shard completions served from the warm
  cache, reported by workers on ``eof`` and forwarded on
  ``shard_done``).
- **Draining** (the scale-down half of tpu_tfrecord.elastic): a worker
  marked draining (``ServiceDispatcher.drain``) has its unstarted
  leases handed back for re-routing (``elastic.drained_leases`` —
  planned drift, never counted as a lease_reassignment), is excluded
  from new routes, finishes the streams it is serving, then says a
  clean ``goodbye`` (``elastic.drains``) and exits — its telemetry
  spool lands a ``final: true`` snapshot, so the fleet doctor reads a
  drained worker as finished, not dead. A victim SIGKILLed mid-drain
  degrades to the ordinary dead-worker path: heartbeat expiry +
  consumer re-route + exactly-once dedupe.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from tpu_tfrecord import service_protocol as sp
from tpu_tfrecord import telemetry, wire
from tpu_tfrecord.columnar import slice_batch
from tpu_tfrecord.io.paths import interleave_owner
from tpu_tfrecord.metrics import METRICS, log_salvage_event, logger

PROTO_VERSION = sp.PROTO_VERSION

#: worker -> dispatcher heartbeat cadence, as a fraction of the lease TTL
#: (3 beats per TTL: one lost datagram never expires a healthy lease).
HEARTBEAT_FRACTION = 3.0

DEFAULT_LEASE_TTL_S = 10.0

#: constructed-dataset cache entries a decode worker keeps (one per job
#: digest); beyond this the oldest job's dataset is evicted.
MAX_CACHED_JOBS = 4

#: journal format version written by this code. v2 is line-oriented:
#: first line a full-state ``snapshot`` record (carrying the fencing
#: ``generation``), then one delta record per mutation, each appended
#: fsync-before-return (``checkpoint.durable_append``). Replay folds the
#: newest consistent prefix — a torn tail (host crash mid-append) is
#: dropped, never fatal. v1 (a single atomically-rewritten JSON object)
#: is still replayed for backward compatibility.
JOURNAL_VERSION = 2

#: delta appends between snapshot compactions (bounds replay cost and
#: keeps the standby's tail cheap).
JOURNAL_COMPACT_EVERY = 256

#: consecutive journal write failures before a primary demotes itself
#: (stops granting leases). An unjournaled primary is worse than a dead
#: one: a standby would take over from a stale journal.
JOURNAL_DEMOTE_AFTER = 3

#: consecutive failed primary pings before a warm standby takes over.
STANDBY_TAKEOVER_MISSES = 3

#: set by faults.install_chaos: every dispatcher-journal write consults
#: this plan under op="journal" (torn_write / sigkill / errors).
_JOURNAL_CHAOS = None


class PartitionMap:
    """The static partition map: K lease-space partitions, each a primary
    dispatcher address plus optional warm standbys, with NO coordination
    service — consumers, workers, and the ``FleetScaler`` all parse the
    same spec string and agree on ownership by rendezvous-hashing the
    tenant digest.

    Spec grammar (the ``service`` option / ``--dispatcher`` flag):

    - ``"host:port"`` — one partition, no standby (the pre-HA form);
    - ``"host:port|host:port2"`` — one partition with a warm standby;
    - ``"h:p1|h:p2,h:p3|h:p4"`` — two partitions, each with a standby;
    - ``"@/path/map.json"`` — read ``{"partitions": [["h:p", ...], ...]}``
      from a file (the fleet-config deployment shape).

    Ownership is highest-random-weight (rendezvous) hashing of
    ``tenant_digest`` over partition indices: deterministic everywhere,
    no ring state, and growing K from N to N+1 remaps only ~1/(N+1) of
    tenants."""

    def __init__(self, partitions: List[List[str]]):
        if not partitions or any(not p for p in partitions):
            raise ValueError("partition map needs >= 1 address per partition")
        self.partitions = [[str(a) for a in p] for p in partitions]
        for group in self.partitions:
            for addr in group:
                sp.parse_addr(addr)  # loud on anything that isn't host:port

    @staticmethod
    def parse(spec: str) -> "PartitionMap":
        spec = str(spec).strip()
        if spec.startswith("@"):
            with open(spec[1:], "rb") as fh:
                obj = json.loads(fh.read().decode("utf-8"))
            return PartitionMap([list(p) for p in obj["partitions"]])
        return PartitionMap(
            [
                [a.strip() for a in part.split("|") if a.strip()]
                for part in spec.split(",")
                if part.strip()
            ]
        )

    @property
    def k(self) -> int:
        return len(self.partitions)

    def partition_for(self, tenant: str) -> int:
        """Rendezvous hash: the partition whose (index, tenant) score is
        highest owns the tenant's lease space. Same inputs, same owner,
        on every consumer/worker/scaler — no coordination needed."""
        return max(
            range(len(self.partitions)),
            key=lambda i: hashlib.sha256(
                f"{i}|{tenant}".encode()
            ).digest(),
        )

    def addrs(self, partition: int) -> List[str]:
        """Primary first, then standbys, for one partition."""
        return list(self.partitions[partition])

    def to_spec(self) -> str:
        return ",".join("|".join(p) for p in self.partitions)


class _ConnTracker:
    """Live accepted-connection registry for a serving loop: ``stop`` must
    close every open connection, not just the listener — a process death
    closes all fds at once, and an in-process stop() (tests, clean
    shutdown) has to look the same to peers AND release the port for an
    immediate same-port restart."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: set = set()

    def track(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)

    def untrack(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.discard(conn)

    def close_all(self) -> None:
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class ServiceUnavailable(ConnectionError):
    """The dispatcher answered but cannot serve (e.g. no alive workers) —
    transport-shaped, so consumer retry/fallback nets handle it."""


class ServiceSpecError(RuntimeError):
    """Worker and consumer disagree about the dataset (shard list digest,
    fused-decode availability). Loud by design: divergent views of the
    data must never be papered over by a fallback."""


def shards_digest(shards) -> str:
    """Identity of the GLOBAL shard list ((path, size) pairs, discovery
    order) — consumer and worker must agree before any bytes flow."""
    blob = json.dumps(
        [(sh.path, sh.size) for sh in shards], sort_keys=True
    ).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def build_job_spec(ds) -> Dict[str, Any]:
    """Everything a decode worker needs to reproduce this dataset's chunk
    stream byte-for-byte: source paths, the RESOLVED schema (no inference
    divergence), requested columns, decode fusions, corruption policy, and
    the global shard-list digest. Options that only change how chunks are
    produced locally (prefetch, workers, mmap, readahead, stall
    thresholds) are deliberately absent — they are the worker's own
    business."""
    opts = ds.options
    spec: Dict[str, Any] = {
        "proto": PROTO_VERSION,
        "paths": ds.source_paths,
        "columns": [f.name for f in ds.schema],
        "schema": ds._reader.schema().to_json(),
        "record_type": opts.record_type.value,
        "verify_crc": opts.verify_crc,
        "on_corrupt": opts.on_corrupt,
        "max_corrupt_records": opts.max_corrupt_records,
        "corrupt_fallback": opts.corrupt_fallback,
        "on_stall": opts.on_stall,
        "batch_size": ds.batch_size,
        "slab_bytes": ds.slab_bytes,
        "max_record_bytes": ds.max_record_bytes,
        "hash_buckets": ds.hash_buckets,
        "pack": ds.pack,
        "shards_digest": shards_digest(ds._reader.shards),
        "tenant": tenant_digest(ds),
    }
    if ds.hash_buckets or ds.pack:
        # fused decode changes which COLUMNS a chunk carries (members fold
        # into group matrices) — both sides must agree
        spec["fused"] = ds._native_decoder is not None
    return spec


def job_digest(spec: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()
    ).hexdigest()[:16]


def tenant_digest(ds) -> str:
    """The multi-tenant sharing key: everything that changes decoded
    chunk CONTENT (the dataset's cache fingerprint — the same identity
    the columnar epoch cache keys entries by) plus the global shard
    list. Two jobs that differ only in consumption shape (batch size,
    prefetch, workers, resume point) produce the SAME tenant and share
    one lease table and one warm cache fleet-wide; anything that changes
    the rows themselves isolates them."""
    ident = dict(ds._cache_ident())
    ident["shards"] = shards_digest(ds._reader.shards)
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()
    ).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------


class _WorkerInfo:
    __slots__ = ("worker_id", "addr", "pid", "beat")

    def __init__(self, worker_id: str, addr: str, pid: int, beat: float):
        self.worker_id = worker_id
        self.addr = addr
        self.pid = pid
        self.beat = beat


class ServiceDispatcher:
    """Owns shard->worker leasing and nothing else — no data bytes ever
    flow through it. All mutable assignment state (workers, leases, done
    set, reassignment count, trace identity) is journaled on every
    mutation — one fsynced delta line via ``checkpoint.durable_append``
    over a durable snapshot line — so a crash loses at most the
    heartbeat freshness (which workers re-supply within one TTL). The
    same instance is also the partition's warm STANDBY when built with
    ``standby_of=<primary addr>``: it tails the shared journal, rejects
    lease-path ops with ``not_primary``, and promotes itself (generation
    bump = zombie fence) when the primary stops answering pings.

    Lease model: ``route`` picks the owner among the ALIVE workers with the
    interleaved assignment (``interleave_owner`` over the sorted alive
    list — the same one owner per-host shard selection uses). A re-route
    of a leased shard counts as a reassignment only when the previous
    lessee is dead or explicitly excluded by the consumer that watched it
    die; assignment drift from fleet growth is rebalancing, not failure.
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        journal: Optional[str] = None,
        lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
        clock=time.monotonic,
        standby_of: Optional[str] = None,
        partition_index: int = 0,
        generation: int = 0,
        demote_after: int = JOURNAL_DEMOTE_AFTER,
        takeover_misses: int = STANDBY_TAKEOVER_MISSES,
        ping_interval_s: Optional[float] = None,
        takeover_addr: bool = True,
    ):
        if lease_ttl_s <= 0:
            raise ValueError("lease_ttl_s must be > 0")
        if standby_of is not None and journal is None:
            raise ValueError(
                "a standby needs the primary's journal path to tail"
            )
        self.lease_ttl_s = float(lease_ttl_s)
        self.journal = journal
        self._clock = clock
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerInfo] = {}
        self._leases: Dict[str, str] = {}  # "tenant/shard_path" -> worker_id
        self._done: Dict[str, str] = {}
        self._reassignments = 0
        # workers marked for graceful scale-down (wid -> drain-marked-at):
        # excluded from new routes, expected to goodbye once idle
        self._draining: Dict[str, float] = {}
        # tenant (decode fingerprint) -> sharing bookkeeping: which
        # consumers/jobs ride this lease table, and how many shard
        # completions the warm cache absorbed
        self._tenants: Dict[str, Dict[str, Any]] = {}
        #: written by an attached elastic.FleetScaler; surfaced in status()
        self.scaler_status: Optional[Dict[str, Any]] = None
        # -- HA state (partitioning + failover + fencing) ------------------
        self.partition_index = int(partition_index)
        self.generation = int(generation)
        #: None = acting primary; an address = warm standby tailing that
        #: primary's journal, promoting itself on heartbeat loss
        self._standby_of = str(standby_of) if standby_of is not None else None
        self._role = "standby" if standby_of is not None else "dispatcher"
        self.failed_over = False
        #: True once journal writes failed ``demote_after`` times in a row
        #: (or were fenced): a demoted primary grants NO leases — a
        #: standby promoted off the journal must never race live state
        #: that was silently running unjournaled
        self._demoted = False
        self._demote_after = max(1, int(demote_after))
        self._journal_fail_streak = 0
        #: a failed append leaves an undefined tail on disk: the next
        #: successful write must be a full snapshot compaction
        self._journal_dirty = False
        self._journal_ino: Optional[int] = None
        self._appends_since_compact = 0
        self._takeover_misses = max(1, int(takeover_misses))
        self.ping_interval_s = (
            float(ping_interval_s)
            if ping_interval_s is not None
            else min(1.0, self.lease_ttl_s / 4.0)
        )
        self._takeover_addr = bool(takeover_addr)
        self._extra_srvs: List[socket.socket] = []
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns = _ConnTracker()
        self._ctx = telemetry.current_context().with_role(self._role)
        if journal is not None and os.path.exists(journal):
            self._replay_journal(journal)
        if journal is not None and self._standby_of is None:
            # a PRIMARY compacts at birth: one fresh fsynced snapshot
            # carrying its generation, so standbys tail a well-formed v2
            # journal from the first byte (and a replayed v1 journal is
            # upgraded in place)
            with self._lock:
                self._compact_locked()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.addr = sp.format_addr(host, self._srv.getsockname()[1])

    # -- journal ------------------------------------------------------------
    #
    # v2 layout: line 1 is a full-state ``snapshot`` record (carrying the
    # fencing generation), every later line one delta record, each landed
    # with ``checkpoint.durable_append`` (fsync before return) so committed
    # records survive a host crash. Replay folds the NEWEST CONSISTENT
    # PREFIX: a torn final line — crash or injected torn_write mid-append —
    # is dropped, and anything after an unparseable record is ignored
    # (records after a tear were written by a writer that already knew its
    # append failed; the compact-on-next-write rule below repairs the file
    # before they could exist). A v1 journal (single JSON object, no
    # ``kind``) replays as a generation-0 snapshot.

    def _replay_journal(self, path: str) -> None:
        """Restore assignment state from the journal. Journaled workers
        get a fresh heartbeat grace of one TTL — they must re-heartbeat
        (their loop re-registers on ``known: false``) or they expire
        exactly like a SIGKILLed worker. The journaled trace identity is
        re-adopted so the restarted (or promoted) dispatcher stays part
        of the same logical run. Also the standby's continuous-catch-up
        path: each tail tick re-reads and re-folds (journals are snapshot
        + a bounded delta tail, so a full re-fold is cheap)."""
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as e:
            raise RuntimeError(f"unreadable dispatcher journal {path}: {e}")
        records = self._parse_journal(data)
        now = self._clock()
        trace = None
        with self._lock:
            self._reset_state_locked()
            for obj in records:
                t = self._fold_locked(obj, now)
                if t is not None:
                    trace = t
        if isinstance(trace, dict):
            self._ctx = telemetry.adopt(
                telemetry.TraceContext.from_json(trace).with_role(self._role)
            )

    @staticmethod
    def _parse_journal(data: bytes) -> List[Dict[str, Any]]:
        """Decode journal bytes to the newest consistent record prefix.
        Empty -> no records. A whole-file JSON object (v1, written without
        a trailing newline) -> one legacy snapshot. Otherwise v2 lines:
        fold complete (newline-terminated) lines in order and STOP at the
        first torn/unparseable one — replay-to-consistent-prefix, the
        contract the truncation tests pin."""
        if not data.strip():
            return []
        try:
            whole = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            whole = None
        if isinstance(whole, dict) and whole.get("kind") is None:
            return [dict(whole, kind="snapshot")]  # v1 full-state object
        records: List[Dict[str, Any]] = []
        lines = data.split(b"\n")
        complete, tail = lines[:-1], lines[-1]
        for raw in complete:
            try:
                obj = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                break  # mid-journal tear: keep the consistent prefix
            if not isinstance(obj, dict) or "kind" not in obj:
                break
            records.append(obj)
        # ``tail`` is bytes after the last newline: a torn final record
        # (the fsync'd newline never landed) — dropped by construction
        del tail
        return records

    def _reset_state_locked(self) -> None:
        self._workers = {}
        self._leases = {}
        self._done = {}
        self._reassignments = 0
        self._draining = {}
        self._tenants = {}

    def _tenant_fold_locked(self, tenant: str) -> Dict[str, Any]:
        info = self._tenants.get(tenant)
        if info is None:
            info = self._tenants[tenant] = {
                "consumers": set(), "jobs": set(),
                "shared_cache_hits": 0, "completions": 0,
            }
        return info

    def _fold_locked(self, obj: Dict[str, Any], now: float):
        """Apply one journal record to the assignment books. Returns the
        trace dict when the record carries one (snapshot), else None."""
        kind = obj.get("kind")
        if kind == "snapshot":
            self._reset_state_locked()
            self.generation = max(self.generation, int(obj.get("generation", 0)))
            for wid, info in dict(obj.get("workers", {})).items():
                self._workers[str(wid)] = _WorkerInfo(
                    str(wid), str(info["addr"]), int(info.get("pid", 0)), now
                )
            self._leases = {
                str(k): str(v) for k, v in dict(obj.get("leases", {})).items()
            }
            self._done = {
                str(k): str(v) for k, v in dict(obj.get("done", {})).items()
            }
            self._reassignments = int(obj.get("reassignments", 0))
            self._draining = {
                str(w): now
                for w in obj.get("draining", [])
                if str(w) in self._workers
            }
            for t, info in dict(obj.get("tenants", {})).items():
                self._tenants[str(t)] = {
                    "consumers": set(info.get("consumers", [])),
                    "jobs": set(info.get("jobs", [])),
                    "shared_cache_hits": int(info.get("shared_cache_hits", 0)),
                    "completions": int(info.get("completions", 0)),
                }
            return obj.get("trace")
        if kind == "register":
            wid = str(obj["worker_id"])
            self._workers[wid] = _WorkerInfo(
                wid, str(obj["addr"]), int(obj.get("pid", 0)), now
            )
            self._draining.pop(wid, None)
        elif kind == "drain":
            wid = str(obj["worker_id"])
            if wid in self._workers:
                self._draining[wid] = now
            for k in [k for k, v in self._leases.items() if v == wid]:
                del self._leases[k]
        elif kind == "goodbye":
            wid = str(obj["worker_id"])
            self._workers.pop(wid, None)
            self._draining.pop(wid, None)
            for k in [k for k, v in self._leases.items() if v == wid]:
                del self._leases[k]
        elif kind == "lease":
            key = str(obj["key"])
            self._leases[key] = str(obj["worker_id"])
            self._reassignments += int(obj.get("reassigned", 0))
            info = self._tenant_fold_locked(key.split("/", 1)[0])
            if obj.get("consumer") and len(info["consumers"]) < 1024:
                info["consumers"].add(str(obj["consumer"]))
            if obj.get("job") and len(info["jobs"]) < 1024:
                info["jobs"].add(str(obj["job"]))
        elif kind == "done":
            key = str(obj["key"])
            self._leases.pop(key, None)
            self._done.setdefault(key, str(obj.get("worker_id", "")))
            info = self._tenant_fold_locked(key.split("/", 1)[0])
            info["completions"] += 1
            if obj.get("cached"):
                info["shared_cache_hits"] += 1
        # unknown kinds fold to nothing: a NEWER writer's record types
        # must not break an older replayer's consistent prefix
        return None

    def _snapshot_payload_locked(self) -> Dict[str, Any]:
        return {
            "kind": "snapshot",
            "version": JOURNAL_VERSION,
            "generation": self.generation,
            "lease_ttl_s": self.lease_ttl_s,
            "partition": self.partition_index,
            "workers": {
                w.worker_id: {"addr": w.addr, "pid": w.pid}
                for w in self._workers.values()
            },
            "leases": self._leases,
            "done": self._done,
            "reassignments": self._reassignments,
            "draining": sorted(self._draining),
            "tenants": {
                t: {
                    "consumers": sorted(info["consumers"]),
                    "jobs": sorted(info["jobs"]),
                    "shared_cache_hits": info["shared_cache_hits"],
                    "completions": info["completions"],
                }
                for t, info in self._tenants.items()
            },
            "trace": self._ctx.to_json(),
        }

    def _compact_locked(self) -> None:
        """Rewrite the journal as one fresh snapshot line — durably
        (fsync-before-rename via ``checkpoint.durable_write``, the PR 16
        helper: standby correctness depends on journal bytes surviving a
        host crash) and atomically (``os.replace`` gives the file a NEW
        inode, which is the fence: a zombie primary's next
        ``durable_append`` sees the inode change and is rejected before
        any stale byte lands)."""
        from tpu_tfrecord import checkpoint

        if self.journal is None:
            return
        line = (
            json.dumps(self._snapshot_payload_locked(), sort_keys=True).encode()
            + b"\n"
        )
        plan = _JOURNAL_CHAOS
        if plan is not None:
            plan.apply_journal(self.journal, line)
        checkpoint.durable_write(self.journal, line)
        self._journal_ino = os.stat(self.journal).st_ino
        self._appends_since_compact = 0
        self._journal_dirty = False
        self._journal_fail_streak = 0

    def _journal_event_locked(self, event: Dict[str, Any]) -> None:
        """Land one mutation record. Primaries only — a standby reads the
        journal, never writes it. Failure policy (the satellite-2
        contract): count every failure; after ``demote_after``
        CONSECUTIVE failures, or a single fenced write (the file was
        replaced by a promoted standby), demote — stop granting leases
        rather than keep running unjournaled under a standby that would
        recover stale state."""
        from tpu_tfrecord import checkpoint

        if self.journal is None or self._standby_of is not None or self._demoted:
            return
        try:
            if self._journal_dirty:
                # the previous append failed partway: the on-disk tail is
                # undefined, so the next durable write must be a full
                # snapshot (which also covers this event's mutation)
                self._compact_locked()
                return
            line = json.dumps(event, sort_keys=True).encode() + b"\n"
            plan = _JOURNAL_CHAOS
            if plan is not None:
                plan.apply_journal(self.journal, line)
            self._journal_ino = checkpoint.durable_append(
                self.journal, line, expect_ino=self._journal_ino
            )
            self._journal_fail_streak = 0
            self._appends_since_compact += 1
            if self._appends_since_compact >= JOURNAL_COMPACT_EVERY:
                self._compact_locked()
        except checkpoint.FencedWriteError as e:
            # a promoted standby owns this journal now: one stale write
            # attempt is all a zombie gets before it stops serving
            METRICS.count("service.fenced_writes")
            self._demote_locked("fenced", e)
        except OSError as e:
            METRICS.count("service.journal_errors")
            self._journal_dirty = True
            self._journal_fail_streak += 1
            logger.warning("dispatcher journal write failed: %s", e)
            if self._journal_fail_streak >= self._demote_after:
                self._demote_locked("journal_errors", e)

    def _demote_locked(self, reason: str, err: BaseException) -> None:
        if self._demoted:
            return
        self._demoted = True
        METRICS.count("service.demotions")
        logger.warning(
            "dispatcher demoted (%s): no further leases will be granted "
            "(last error: %s)", reason, err,
        )
        telemetry.instant("service.demoted", reason=reason, error=str(err))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ServiceDispatcher":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        if self._standby_of is not None:
            s = threading.Thread(target=self._standby_loop, daemon=True)
            s.start()
            self._threads.append(s)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for srv in self._extra_srvs:
            try:
                srv.close()
            except OSError:
                pass
        self._conns.close_all()
        # Wait out the accept thread: while it is blocked in accept(2) the
        # kernel keeps the listening socket's file description — and the
        # PORT — alive past close(), and a same-port restart (the
        # dispatcher-crash story) would race EADDRINUSE against its 0.2s
        # poll. Bounded: the poll timeout guarantees exit.
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    def __enter__(self) -> "ServiceDispatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self, srv: Optional[socket.socket] = None) -> None:
        srv = srv if srv is not None else self._srv
        try:
            srv.settimeout(0.2)
        except OSError:
            return  # stop() closed the listener before we first polled
        while not self._stop.is_set():
            try:
                conn, _peer = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sp.enable_nodelay(conn)
            self._conns.track(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        peer = "client"
        try:
            conn.settimeout(max(1.0, self.lease_ttl_s * 4))
            while not self._stop.is_set():
                msg = sp.recv_msg(conn, peer, allow_eof=True)
                if msg is None:
                    return
                sp.send_msg(conn, self._handle(msg))
        except (OSError, sp.ProtocolError):
            return
        finally:
            self._conns.untrack(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- warm standby / failover --------------------------------------------

    def _standby_loop(self) -> None:
        """The warm-standby tick: tail the primary's journal (the PR 8
        replay path reused for continuous catch-up — full re-fold of
        snapshot + bounded delta tail), then ping the primary. After
        ``takeover_misses`` consecutive failed pings — or a primary that
        answers but admits it stopped accepting (demoted) — promote.
        All waits ride the stop event (the injectable-wait seam);
        cadence is ``ping_interval_s``."""
        misses = 0
        while not self._stop.wait(self.ping_interval_s):
            if self._standby_of is None:
                return  # promoted by an external call
            try:
                if os.path.exists(self.journal):
                    self._replay_journal(self.journal)
            except RuntimeError:
                pass  # transiently unreadable: keep last good fold
            if self._ping_primary():
                misses = 0
                continue
            misses += 1
            if misses >= self._takeover_misses:
                self.promote()
                return

    def _ping_primary(self) -> bool:
        addr = self._standby_of
        if addr is None:
            return True
        try:
            conn = sp.connect(addr, timeout=max(0.2, self.ping_interval_s))
            try:
                conn.settimeout(max(0.2, self.ping_interval_s))
                reply = sp.request(
                    conn, addr, {"op": "ping", "proto": PROTO_VERSION}
                )
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
        except (OSError, sp.ProtocolError):
            return False
        # a primary that answers but no longer accepts (demoted after
        # journal failures, or fenced) is DOWN for takeover purposes
        return bool(reply.get("ok")) and bool(reply.get("accepting", True))

    def promote(self) -> None:
        """Standby -> acting primary. Bumps the generation and compacts
        the journal (``durable_write`` -> new inode), which IS the fence:
        the dead primary resurrected as a zombie fails its next append on
        the inode change, counts ``service.fenced_writes``, and demotes.
        Then best-effort takes over the dead primary's advertised address
        (clients that never learned the standby's address reconnect to
        the same host:port); clients that DO know it ride their partition
        map's address rotation either way."""
        with self._lock:
            if self._standby_of is None:
                return
            primary_addr = self._standby_of
            self._standby_of = None
            self._role = "dispatcher"
            self.failed_over = True
            self._demoted = False
            self.generation += 1
            try:
                self._compact_locked()
            except OSError as e:
                # promotion must not die on a journal hiccup — the next
                # mutation retries the compaction via the dirty flag
                METRICS.count("service.journal_errors")
                self._journal_dirty = True
                logger.warning("promotion compaction failed: %s", e)
        self._ctx = telemetry.adopt(self._ctx.with_role("dispatcher"))
        METRICS.count("service.failovers")
        METRICS.gauge("service.partition", float(self.partition_index))
        telemetry.instant(
            "service.failover",
            partition=self.partition_index,
            generation=self.generation,
            old_primary=primary_addr,
            addr=self.addr,
        )
        logger.warning(
            "standby took over partition %d (generation %d, old primary %s)",
            self.partition_index, self.generation, primary_addr,
        )
        if self._takeover_addr:
            self._adopt_address(primary_addr)

    def _adopt_address(self, addr: str) -> None:
        """Best-effort bind of the dead primary's advertised host:port as
        an ADDITIONAL accept socket. On the same host this succeeds the
        moment the primary's listener dies (SO_REUSEADDR); across hosts
        (or while a zombie still holds the port) it fails quietly —
        partition-map address rotation covers those clients."""
        try:
            host, port = sp.parse_addr(addr)
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((host, port))
            srv.listen(64)
        except OSError as e:
            logger.warning(
                "could not take over advertised address %s: %s", addr, e
            )
            return
        self._extra_srvs.append(srv)
        t = threading.Thread(
            target=self._accept_loop, args=(srv,), daemon=True
        )
        t.start()
        self._threads.append(t)
        telemetry.instant("service.failover", adopted_addr=addr,
                          partition=self.partition_index,
                          generation=self.generation)

    # -- request handling ---------------------------------------------------

    def _handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        if msg.get("proto", PROTO_VERSION) != PROTO_VERSION:
            return {"error": "proto_mismatch", "proto": PROTO_VERSION}
        try:
            if op in ("route", "shard_done", "drain") and not self.accepting:
                # a standby (or a demoted zombie) grants NOTHING: route
                # and completion records belong to the acting primary's
                # journal. Workers' register/heartbeat still land (the
                # standby keeps fleet freshness warm for takeover) and
                # status/ping answer honestly.
                METRICS.count("service.not_primary_rejects")
                return {
                    "error": "not_primary",
                    "role": self._role,
                    "demoted": self._demoted,
                    "primary": self._standby_of,
                }
            if op == "register_worker":
                return self._op_register(msg)
            if op == "heartbeat":
                return self._op_heartbeat(msg)
            if op == "route":
                return self._op_route(msg)
            if op == "shard_done":
                return self._op_shard_done(msg)
            if op == "goodbye":
                return self._op_goodbye(msg)
            if op == "drain":
                return {"ok": True,
                        "drained": self.drain(str(msg["worker_id"]))}
            if op == "scaler_status":
                # a federated FleetScaler running elsewhere publishes its
                # verdict here so serve-status shows it on every partition
                st = msg.get("status")
                self.scaler_status = dict(st) if isinstance(st, dict) else None
                return {"ok": True}
            if op == "status":
                return self.status()
            if op == "ping":
                return {"ok": True, "role": self._role,
                        "accepting": self.accepting,
                        "generation": self.generation}
            return {"error": f"unknown op {op!r}"}
        except (KeyError, TypeError, ValueError) as e:
            return {"error": f"malformed {op!r} request: {e}"}

    @property
    def accepting(self) -> bool:
        """Is this process the acting, non-demoted primary for its
        partition — the only state in which leases may be granted?"""
        return self._standby_of is None and not self._demoted

    def _alive_locked(self, now: float) -> List[str]:
        return sorted(
            w.worker_id
            for w in self._workers.values()
            if now - w.beat <= self.lease_ttl_s
        )

    def _op_register(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        wid = str(msg["worker_id"])
        with self._lock:
            self._workers[wid] = _WorkerInfo(
                wid, str(msg["addr"]), int(msg.get("pid", 0)), self._clock()
            )
            # a re-registering worker is a FRESH worker (restart, or a
            # journal-replayed identity coming back): any old drain mark
            # belonged to its previous life
            self._draining.pop(wid, None)
            self._journal_event_locked(
                {"kind": "register", "worker_id": wid,
                 "addr": str(msg["addr"]), "pid": int(msg.get("pid", 0))}
            )
        return {
            "ok": True,
            "worker_id": wid,
            "lease_ttl_s": self.lease_ttl_s,
            "trace": self._ctx.to_json(),
        }

    def _op_heartbeat(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        wid = str(msg["worker_id"])
        with self._lock:
            info = self._workers.get(wid)
            if info is not None:
                info.beat = self._clock()
            drain = wid in self._draining
        # known=False sends the worker back through register (the
        # journal-less restart path); drain=True tells the worker to
        # finish its in-flight streams, say goodbye, and exit
        return {"ok": True, "known": info is not None, "drain": drain}

    def drain(self, worker_id: str) -> bool:
        """Mark one worker draining (the elastic scale-down path): its
        current leases are handed back for re-routing (planned drift —
        never counted as a lease_reassignment), new routes exclude it,
        and its heartbeat replies carry ``drain: true`` until it says
        goodbye. Returns False for an unknown or already-draining
        worker."""
        wid = str(worker_id)
        with self._lock:
            if wid not in self._workers or wid in self._draining:
                return False
            self._draining[wid] = self._clock()
            released = [k for k, v in self._leases.items() if v == wid]
            for k in released:
                del self._leases[k]
            self._journal_event_locked({"kind": "drain", "worker_id": wid})
        if released:
            METRICS.count("elastic.drained_leases", len(released))
        telemetry.instant(
            "elastic.drain", worker=wid, released_leases=len(released)
        )
        return True

    def _op_goodbye(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """A draining worker finished its streams and is exiting cleanly:
        drop it from the books entirely (it is neither alive nor dead —
        it is GONE, the same way a finished process never joins the fleet
        doctor's dead list)."""
        wid = str(msg["worker_id"])
        with self._lock:
            known = self._workers.pop(wid, None) is not None
            was_draining = self._draining.pop(wid, None) is not None
            for k in [k for k, v in self._leases.items() if v == wid]:
                del self._leases[k]
            self._journal_event_locked({"kind": "goodbye", "worker_id": wid})
        if known and was_draining:
            METRICS.count("elastic.drains")
            telemetry.instant("elastic.drain_complete", worker=wid)
        return {"ok": True, "known": known}

    def _tenant_locked(self, msg: Dict[str, Any]) -> str:
        """Resolve the lease-table key space for one request: the
        consumer's tenant (decode fingerprint — jobs that share it share
        leases and the warm cache) with the job digest as the fallback
        for tenant-less peers. Tracks which consumers/jobs ride each
        tenant for the serve-status picture."""
        tenant = str(msg.get("tenant") or msg["job"])
        info = self._tenants.get(tenant)
        if info is None:
            info = self._tenants[tenant] = {
                "consumers": set(), "jobs": set(),
                "shared_cache_hits": 0, "completions": 0,
            }
            METRICS.count("service.tenants")
        consumer = msg.get("consumer")
        if consumer and len(info["consumers"]) < 1024:
            # bounded: every short-lived iterator mints a fresh consumer
            # id, and this census set rides the journal — a long-lived
            # dispatcher must not grow it without limit (the count
            # saturates at the cap; leases/done are the real state)
            info["consumers"].add(str(consumer))
        if msg.get("job") and len(info["jobs"]) < 1024:
            info["jobs"].add(str(msg["job"]))
        return tenant

    def _op_route(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        shard_path = str(msg["path"])
        shard_index = int(msg["shard_index"])
        exclude = {str(w) for w in msg.get("exclude", [])}
        with self._lock:
            tenant = self._tenant_locked(msg)
            key = f"{tenant}/{shard_path}"
            now = self._clock()
            alive = self._alive_locked(now)
            # draining workers take no NEW shards — they are finishing
            # what they already serve; consumer-witnessed suspects next
            serving = [w for w in alive if w not in self._draining]
            candidates = [w for w in serving if w not in exclude]
            if not candidates:
                candidates = [w for w in alive if w not in exclude]
            if not candidates:
                # every alive worker is excluded: better a possibly-flaky
                # (or draining) worker than no route at all (the
                # consumer's fallback budget still bounds the pain)
                candidates = alive
            if not candidates:
                return {"error": "no_workers"}
            wid = candidates[interleave_owner(shard_index, len(candidates))]
            trace = msg.get("trace")
            if isinstance(trace, dict):
                # the dispatcher's half of the consumer's service.lease
                # span: one instant linked by the lease span's id, so the
                # merged timeline shows WHO routed this lease and when
                telemetry.instant(
                    "service.route",
                    shard=shard_path, worker=wid,
                    trace_id=trace.get("trace_id"),
                    parent_span_id=trace.get("span_id"),
                )
            prev = self._leases.get(key)
            reassigned = False
            if prev is not None and prev != wid:
                if prev not in alive or prev in exclude:
                    reassigned = True
                    self._reassignments += 1
                    METRICS.count("service.lease_reassignments")
                    telemetry.instant(
                        "service.lease_reassigned",
                        shard=shard_path, from_worker=prev, to_worker=wid,
                    )
            if prev != wid:
                self._leases[key] = wid
                self._journal_event_locked(
                    {"kind": "lease", "key": key, "worker_id": wid,
                     "reassigned": int(reassigned),
                     "consumer": msg.get("consumer"), "job": msg.get("job")}
                )
            return {
                "ok": True,
                "worker": self._workers[wid].addr,
                "worker_id": wid,
                # the dispatcher's REAL ttl, so consumers age their
                # suspect lists on the fleet's actual reassignment clock
                # rather than trusting a local option to match it
                "lease_ttl_s": self.lease_ttl_s,
            }

    def _op_shard_done(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            tenant = self._tenant_locked(msg)
            key = f"{tenant}/{msg['path']}"
            wid = self._leases.pop(key, None) or str(msg.get("worker_id", ""))
            if key not in self._done:
                self._done[key] = wid
                METRICS.count("service.shards_done")
            info = self._tenants[tenant]
            info["completions"] += 1
            if msg.get("cached"):
                # the worker served this shard entirely from the warm
                # columnar cache (reported on its eof): the fleet-wide
                # pay-decode-once payoff, made countable
                info["shared_cache_hits"] += 1
                METRICS.count("service.shared_cache_hits")
            self._journal_event_locked(
                {"kind": "done", "key": key, "worker_id": wid,
                 "cached": bool(msg.get("cached")),
                 "consumer": msg.get("consumer"), "job": msg.get("job")}
            )
        return {"ok": True}

    def status(self) -> Dict[str, Any]:
        """The serve-status picture: one entry per worker (lease count,
        shards done, heartbeat age) + service totals."""
        with self._lock:
            now = self._clock()
            alive = set(self._alive_locked(now))
            done_by: Dict[str, int] = {}
            for wid in self._done.values():
                done_by[wid] = done_by.get(wid, 0) + 1
            leases_by: Dict[str, List[str]] = {}
            for key, wid in self._leases.items():
                leases_by.setdefault(wid, []).append(key.split("/", 1)[1])
            workers = [
                {
                    "worker_id": w.worker_id,
                    "addr": w.addr,
                    "pid": w.pid,
                    "alive": w.worker_id in alive,
                    "draining": w.worker_id in self._draining,
                    "heartbeat_age_s": round(now - w.beat, 3),
                    "leases": sorted(leases_by.get(w.worker_id, [])),
                    "shards_done": done_by.get(w.worker_id, 0),
                }
                for w in sorted(self._workers.values(), key=lambda w: w.worker_id)
            ]
            tenants = {
                t: {
                    "consumers": len(info["consumers"]),
                    "jobs": len(info["jobs"]),
                    "leases": sum(
                        1 for k in self._leases if k.startswith(t + "/")
                    ),
                    "shards_done": sum(
                        1 for k in self._done if k.startswith(t + "/")
                    ),
                    "completions": info["completions"],
                    "shared_cache_hits": info["shared_cache_hits"],
                }
                for t, info in sorted(self._tenants.items())
            }
            out = {
                "ok": True,
                "role": self._role,
                "addr": self.addr,
                "partition": self.partition_index,
                "generation": self.generation,
                "accepting": self._standby_of is None and not self._demoted,
                "demoted": self._demoted,
                "failed_over": self.failed_over,
                "standby_of": self._standby_of,
                "lease_ttl_s": self.lease_ttl_s,
                "workers": workers,
                "alive": len(alive),
                "draining": sorted(self._draining),
                "tenants": tenants,
                "shards_done": len(self._done),
                "active_leases": len(self._leases),
                "lease_reassignments": self._reassignments,
                "trace_id": self._ctx.trace_id,
            }
            if self.scaler_status is not None:
                out["scaler"] = self.scaler_status
            return out


# ---------------------------------------------------------------------------
# Decode worker
# ---------------------------------------------------------------------------


class DecodeWorker:
    """One decode process: registers with the dispatcher (adopting the
    dispatcher's trace as its parent, so spool snapshots and merged
    timelines correlate), heartbeats at TTL/3, and serves ``fetch``
    requests by streaming a shard's decoded chunks — through the columnar
    epoch cache when the worker has one configured (serve on hit,
    ``CachePopulator`` atomic staging on miss), exactly like a local read.

    ``options`` carries the WORKER-LOCAL knobs (cache mode/dir/budget,
    stall-guard thresholds, trace) — everything that changes decoded ROWS
    comes from the consumer's job spec instead, so a worker can serve any
    compatible job."""

    def __init__(
        self,
        dispatcher_addr: str,
        options=None,
        port: int = 0,
        host: str = "127.0.0.1",
        worker_id: Optional[str] = None,
        role: str = "decode_worker",
        drain_grace_s: float = 1.0,
        clock=time.monotonic,
        sleep=None,
    ):
        # ``dispatcher_addr`` accepts the full PartitionMap spec: a worker
        # registers with (and heartbeats) EVERY partition, one beat loop
        # per partition, rotating primary -> standby on transport failure
        # — so any partition can route work here, and a promoted standby
        # hears from the fleet within one beat
        self._partition_map = PartitionMap.parse(dispatcher_addr)
        self.dispatcher_addr = self._partition_map.addrs(0)[0]
        self._options = options
        self._role = role
        # drain completes only after the worker has been idle (no fetch
        # stream in flight) for this long continuously — a consumer that
        # just routed here must get its stream before the goodbye
        self.drain_grace_s = float(drain_grace_s)
        self._clock = clock
        self._inflight = 0
        self._idle_since = clock()
        self._inflight_lock = threading.Lock()
        self._draining = threading.Event()
        #: set once the goodbye has been sent and the worker stopped
        self.drained = threading.Event()
        self._beat_lock = threading.Lock()
        self._beat_loops_left = 0
        self._stop = threading.Event()
        self._sleep = sleep if sleep is not None else self._stop.wait
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.addr = sp.format_addr(host, self._srv.getsockname()[1])
        self.worker_id = worker_id or f"{host}:{self._srv.getsockname()[1]}"
        self.lease_ttl_s = DEFAULT_LEASE_TTL_S
        self._registered = threading.Event()
        self._datasets: Dict[str, Tuple[Any, Dict[str, int]]] = {}
        self._ds_lock = threading.Lock()
        self._build_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._conns = _ConnTracker()

    def start(self) -> "DecodeWorker":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        self._threads.append(self._accept_thread)
        self._beat_loops_left = self._partition_map.k
        for part in range(self._partition_map.k):
            beat = threading.Thread(
                target=self._beat_loop,
                args=(self._partition_map.addrs(part),),
                daemon=True,
            )
            beat.start()
            self._threads.append(beat)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._conns.close_all()
        # free the data port deterministically (see ServiceDispatcher.stop);
        # the beat thread is NOT joined — it may be mid-RPC to a dead
        # dispatcher with a seconds-scale timeout, and it holds no port
        t = getattr(self, "_accept_thread", None)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def __enter__(self) -> "DecodeWorker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def wait_registered(self, timeout: Optional[float] = None) -> bool:
        return self._registered.wait(timeout)

    # -- dispatcher side ----------------------------------------------------

    def _beat_loop(self, addrs: List[str]) -> None:
        """Register, then heartbeat at TTL/3 forever — one loop per
        PARTITION, against whichever of the partition's addresses
        (primary first, then standbys) currently answers. Any transport
        error (dispatcher crashed/restarting, primary dead awaiting
        takeover) rotates to the partition's next address, backs off, and
        retries — a restarted dispatcher answers ``known: false`` until
        we re-register, which this loop does on the next beat; a standby
        accepts register/heartbeat too, keeping fleet freshness warm for
        its takeover."""
        conn: Optional[socket.socket] = None
        registered = False
        backoff = 0.05
        addr_idx = 0
        addr = addrs[0]
        while not self._stop.is_set():
            try:
                if conn is None:
                    addr = addrs[addr_idx % len(addrs)]
                    conn = sp.connect(addr, timeout=5.0)
                if not registered:
                    reply = sp.request(
                        conn,
                        addr,
                        {
                            "op": "register_worker",
                            "proto": PROTO_VERSION,
                            "worker_id": self.worker_id,
                            "addr": self.addr,
                            "pid": os.getpid(),
                        },
                    )
                    if reply.get("error"):
                        raise ServiceUnavailable(str(reply["error"]))
                    self.lease_ttl_s = float(
                        reply.get("lease_ttl_s", DEFAULT_LEASE_TTL_S)
                    )
                    trace = reply.get("trace")
                    if isinstance(trace, dict):
                        telemetry.adopt_child_from_json(trace, role=self._role)
                    registered = True
                    self._registered.set()
                    METRICS.count("service.registrations")
                else:
                    reply = sp.request(
                        conn,
                        addr,
                        {
                            "op": "heartbeat",
                            "proto": PROTO_VERSION,
                            "worker_id": self.worker_id,
                        },
                    )
                    if not reply.get("known", False):
                        registered = False
                        continue
                    if reply.get("drain"):
                        self._draining.set()
                backoff = 0.05
                if self._draining.is_set():
                    # draining: finish in-flight streams, then goodbye.
                    # Poll fast — the beat cadence (TTL/3) would add
                    # seconds of dead air to every scale-down.
                    if self._drain_ready():
                        try:
                            sp.request(
                                conn, addr,
                                {"op": "goodbye", "proto": PROTO_VERSION,
                                 "worker_id": self.worker_id},
                            )
                        finally:
                            self._beat_loop_finished()
                        return
                    self._sleep(min(0.1, self.drain_grace_s / 2 or 0.1))
                    continue
                self._sleep(max(0.05, self.lease_ttl_s / HEARTBEAT_FRACTION))
            except (OSError, sp.ProtocolError, ServiceUnavailable):
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    conn = None
                registered = False
                # next attempt tries the partition's next address (the
                # warm standby when the primary is dead); wraps around so
                # a recovered/readopted primary address is retried too
                addr_idx += 1
                self._sleep(backoff)
                backoff = min(backoff * 2, 2.0)

    def _beat_loop_finished(self) -> None:
        """One partition's drain goodbye is done; the LAST loop to finish
        marks the whole worker drained and stops it (the single-partition
        behavior, generalized)."""
        with self._beat_lock:
            self._beat_loops_left -= 1
            last = self._beat_loops_left <= 0
        if last:
            METRICS.count("service.worker_drained")
            self.drained.set()
            self.stop()

    # -- drain bookkeeping ---------------------------------------------------

    def _fetch_begin(self) -> None:
        with self._inflight_lock:
            self._inflight += 1

    def _fetch_end(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle_since = self._clock()

    def _drain_ready(self) -> bool:
        """Drain completes once no fetch stream has been in flight for
        ``drain_grace_s`` continuously: in-flight consumers finish their
        shard, and a consumer holding a just-issued (stale) route gets
        its stream rather than a closed port. A new fetch during the
        grace resets it."""
        with self._inflight_lock:
            if self._inflight > 0:
                return False
            return self._clock() - self._idle_since >= self.drain_grace_s

    # -- data side ----------------------------------------------------------

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _peer = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sp.enable_nodelay(conn)
            self._conns.track(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        peer = "consumer"
        try:
            # sends block under normal consumer backpressure; the timeout
            # only reaps connections whose peer is wedged outright
            conn.settimeout(300.0)
            while not self._stop.is_set():
                msg = sp.recv_msg(conn, peer, allow_eof=True)
                if msg is None:
                    return
                if msg.get("proto", PROTO_VERSION) != PROTO_VERSION:
                    # same loud rejection as the dispatcher's _handle: a
                    # version-skewed peer must never receive chunks whose
                    # section layout it would mis-parse
                    sp.send_msg(conn, {"op": "error", "kind": "proto_mismatch",
                                       "error": f"worker speaks proto "
                                       f"{PROTO_VERSION}, peer sent "
                                       f"{msg.get('proto')!r}"})
                elif msg.get("op") == "fetch":
                    # draining workers still serve: routes already steer
                    # new shards away, and rejecting a raced route would
                    # only force a retry loop — "finish the current
                    # lease" means every stream that reaches us completes
                    self._fetch_begin()
                    try:
                        if not self._handle_fetch(conn, msg, peer):
                            return
                    finally:
                        self._fetch_end()
                elif msg.get("op") == "ping":
                    sp.send_msg(conn, {"ok": True, "worker_id": self.worker_id})
                else:
                    sp.send_msg(conn, {"op": "error", "kind": "bad_request",
                                       "error": f"unknown op {msg.get('op')!r}"})
        except (OSError, sp.ProtocolError):
            return  # consumer went away — its dedupe makes this safe
        finally:
            self._conns.untrack(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dataset_for(self, spec: Dict[str, Any]):
        """Build (and cache by job digest) the dataset that reproduces the
        consumer's chunk stream, merged with this worker's own local knobs
        (epoch cache, stall thresholds)."""
        digest = job_digest(spec)
        with self._ds_lock:
            hit = self._datasets.get(digest)
            if hit is not None:
                return hit
        # One build at a time: the acceptance topology (2 consumers, same
        # job) guarantees near-simultaneous cold fetches, which must not
        # each pay the seconds-long construction; the second fetch waits
        # here and takes the cache hit (the keepalive in _handle_fetch
        # covers the wait on the consumer's deadline).
        with self._build_lock:
            with self._ds_lock:
                hit = self._datasets.get(digest)
                if hit is not None:
                    return hit
            return self._build_dataset(spec, digest)

    def _build_dataset(self, spec: Dict[str, Any], digest: str):
        from tpu_tfrecord.io.dataset import TFRecordDataset
        from tpu_tfrecord.options import TFRecordOptions

        base: Dict[str, Any] = {
            "record_type": spec["record_type"],
            "verify_crc": spec["verify_crc"],
            "schema": spec["schema"],
            "on_corrupt": spec["on_corrupt"],
            "max_corrupt_records": spec["max_corrupt_records"],
            "corrupt_fallback": spec["corrupt_fallback"],
            "on_stall": spec["on_stall"],
        }
        wo = self._options
        if wo is not None:
            base.update(
                cache=wo.cache,
                cache_dir=wo.cache_dir,
                cache_max_bytes=wo.cache_max_bytes,
                trace=wo.trace,
                read_deadline_ms=wo.read_deadline_ms,
                open_deadline_ms=wo.open_deadline_ms,
                hedge_after_ms=wo.hedge_after_ms,
                watchdog_timeout_ms=wo.watchdog_timeout_ms,
            )
        ds = TFRecordDataset(
            spec["paths"],
            batch_size=int(spec["batch_size"]),
            options=TFRecordOptions.from_map(base),
            columns=list(spec["columns"]),
            num_epochs=1,
            process_index=0,
            process_count=1,
            num_workers=1,
            hash_buckets=spec.get("hash_buckets") or None,
            pack=spec.get("pack") or None,
            slab_bytes=int(spec["slab_bytes"]),
            max_record_bytes=int(spec["max_record_bytes"]),
        )
        mine = shards_digest(ds._reader.shards)
        if mine != spec["shards_digest"]:
            raise ServiceSpecError(
                f"shard list diverged: worker sees digest {mine}, consumer "
                f"sent {spec['shards_digest']} — the dataset changed under "
                "the service"
            )
        want_fused = spec.get("fused")
        have_fused = ds._native_decoder is not None
        if want_fused is not None and bool(want_fused) != have_fused:
            raise ServiceSpecError(
                f"fused-decode availability diverged (consumer "
                f"fused={want_fused}, worker fused={have_fused}): chunks "
                "would carry different columns"
            )
        idx_of = {sh.path: i for i, sh in enumerate(ds.shards)}
        with self._ds_lock:
            self._datasets[digest] = (ds, idx_of)
            # LRU-ish cap: a long-lived worker serving a succession of
            # distinct jobs must not grow without bound (each entry holds
            # decoder state, shard lists, and IO scratch); insertion order
            # approximates recency well enough here because a job's
            # fetches arrive in bursts.
            while len(self._datasets) > MAX_CACHED_JOBS:
                evicted = next(iter(self._datasets))
                if evicted == digest:
                    break
                del self._datasets[evicted]
        return ds, idx_of

    def _handle_fetch(
        self, conn: socket.socket, msg: Dict[str, Any], peer: str
    ) -> bool:
        """Stream one shard from ``skip``; returns False when the
        connection is no longer usable for further requests."""
        try:
            spec = msg["spec"]
            shard_path = str(msg["shard"])
            skip = int(msg.get("skip", 0))
        except (KeyError, TypeError, ValueError) as e:
            sp.send_msg(conn, {"op": "error", "kind": "bad_request",
                               "error": f"malformed fetch: {e!r}"})
            return True
        # Liveness vs construction: the first fetch of a job pays seconds
        # of dataset construction on a loaded box, so build on the side
        # and stream `building` keepalives — the consumer's per-op recv
        # deadline then measures LIVENESS, and a cold healthy worker is
        # never mistaken for a dead one (a deadline miss here used to add
        # a spurious lease reassignment per cold worker).
        built: Dict[str, Any] = {}
        done = threading.Event()

        def _build() -> None:
            try:
                built["ds"] = self._dataset_for(spec)
            except BaseException as e:  # graftlint: swallow(error shipped to the consumer as a protocol error op)
                built["err"] = e
            finally:
                done.set()

        threading.Thread(target=_build, daemon=True).start()
        try:
            while not done.wait(0.25):
                sp.send_msg(conn, {"op": "building"})
        except OSError:
            return False  # consumer went away mid-construction
        err = built.get("err")
        if err is not None:
            if isinstance(err, ServiceSpecError):
                kind = "spec_mismatch"
            elif isinstance(err, (KeyError, TypeError, ValueError)):
                kind = "bad_request"
            else:  # dataset construction (bad paths, IO)
                kind = "io"
            sp.send_msg(conn, {"op": "error", "kind": kind, "error": str(err)})
            return True
        ds, idx_of = built["ds"]
        try:
            idx = idx_of[shard_path]
        except KeyError:
            sp.send_msg(conn, {"op": "error", "kind": "bad_request",
                               "error": f"unknown shard {shard_path!r}"})
            return True
        METRICS.count("service.fetches")
        # the worker's half of the consumer's service.lease span: the
        # consumer ships its lease span id in the fetch message, and the
        # service.serve span links back to it by parent_span_id — merged
        # traces render route -> lease -> serve -> eof as one causal chain
        trace = msg.get("trace")
        trace = trace if isinstance(trace, dict) else None
        # Will this shard be served from the warm columnar cache (zero
        # ground-truth reads)? Peeked BEFORE the stream so the eof can
        # carry it to the consumer, which forwards it on shard_done —
        # the dispatcher's per-tenant shared_cache_hits accounting.
        cached = False
        if getattr(ds, "_cache", None) is not None:
            cached = ds._cache.peek_entry(ds.shards[idx])
            if cached:
                METRICS.count("service.cache_served")
        k = 0
        try:
            with telemetry.span("service.serve", shard=shard_path) as span:
                if trace is not None:
                    span.set(
                        trace_id=trace.get("trace_id"),
                        parent_span_id=trace.get("span_id"),
                    )
                for chunk, _e, _p, start in ds._decode_shard(0, 0, idx, skip):
                    nbytes = sp.send_chunk(conn, chunk, start, k)
                    k += 1
                    METRICS.count("service.chunks_sent")
                    METRICS.count("service.bytes_sent", nbytes)
                span.set(chunks=k)
            sp.send_msg(conn, {"op": "eof", "chunks": k, "cached": cached})
            METRICS.count("service.shards_served")
            return True
        except wire.TFRecordCorruptionError as e:
            try:
                sp.send_msg(conn, {"op": "error", "kind": "corruption",
                                   "error": str(e)})
            except OSError:
                pass
            return False
        except (OSError, sp.ProtocolError) as e:
            # consumer vanished mid-stream, or the worker's own read
            # failed: if the pipe still works, tell the consumer so it can
            # try another worker rather than waiting out its deadline
            try:
                sp.send_msg(conn, {"op": "error", "kind": "io",
                                   "error": str(e)})
            except OSError:
                pass
            return False


# ---------------------------------------------------------------------------
# Consumer client
# ---------------------------------------------------------------------------


class ServiceClient:
    """The consumer side: an alternative chunk source for one iterator.
    ``shard_chunks`` yields the exact ``(chunk, epoch, pos, start)`` tuples
    ``TFRecordDataset._chunk_stream`` would have decoded locally, fetched
    from leased workers instead — with reconnect-and-dedupe on worker
    death, dispatcher-outage backoff, and the local-read fallback."""

    def __init__(self, ds):
        opts = ds.options
        self._ds = ds
        self.deadline_s = (opts.service_deadline_ms or 5000.0) / 1000.0
        fb = opts.service_fallback_ms
        self.fallback_s = fb / 1000.0 if fb is not None else None
        self._clock = ds.retry_policy.clock
        self._sleep = ds.retry_policy.sleep
        self._spec = build_job_spec(ds)
        self._job = job_digest(self._spec)
        # the multi-tenant sharing key (decode fingerprint + shard list):
        # jobs that share it share one lease table and one warm cache
        self._tenant = self._spec["tenant"]
        # the static partition map: this dataset's tenant hashes to ONE
        # owning partition; the client speaks only to that partition's
        # addresses (primary first), rotating to the standby on transport
        # failure or a not_primary reply — failover is just the existing
        # RetryPolicy backoff landing on the next address
        pm = PartitionMap.parse(opts.service)
        self.partition = pm.partition_for(self._tenant)
        self._addrs = pm.addrs(self.partition)
        self._addr_idx = 0
        self.addr = self._addrs[0]
        METRICS.gauge("service.partition", float(self.partition))
        # consumer identity for the dispatcher's per-tenant census only —
        # never part of any lease key
        self._consumer_id = (
            f"{socket.gethostname()}-{os.getpid()}-{os.urandom(3).hex()}"
        )
        self._fetch_cached = False
        self._dtype_of = ds.chunk_dtypes().__getitem__
        self._verify = opts.verify_crc
        self._global_index = {
            sh.path: i for i, sh in enumerate(ds._reader.shards)
        }
        self._disp: Optional[socket.socket] = None
        self._degraded = False
        # Worker ids this client WATCHED fail (wid -> suspected-at time),
        # remembered across shards: until the dispatcher expires the dead
        # worker's heartbeat (one lease TTL), routing would otherwise hand
        # every subsequent shard to the corpse first — one connect-fail
        # and one spurious lease_reassignment per shard. Suspicion is
        # client-scoped and self-healing three ways: the dispatcher
        # ignores exclusions that would leave no candidates, a suspect
        # that completes a shard for us is cleared, and suspicion ages out
        # after one lease TTL (by then the dispatcher's own heartbeat
        # accounting has caught a genuinely dead worker — one transient
        # hiccup must not exile a healthy worker for the client's life).
        self._suspects: Dict[str, float] = {}
        self._suspect_ttl_s = opts.service_lease_ttl_s

    def close(self) -> None:
        if self._disp is not None:
            try:
                self._disp.close()
            except OSError:
                pass
            self._disp = None

    def _rotate_addr(self) -> None:
        """Advance to the owning partition's next address (primary ->
        standby -> primary ...): the client-side half of failover. The
        wrap-around matters — a promoted standby may have adopted the
        dead primary's advertised address, so the old address is retried
        too."""
        if len(self._addrs) > 1:
            self._addr_idx = (self._addr_idx + 1) % len(self._addrs)
            self.addr = self._addrs[self._addr_idx]

    def _dispatcher_rpc(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        if self._disp is None:
            try:
                s = sp.connect(self.addr, timeout=self.deadline_s)
            except OSError:
                # a refused/timed-out CONNECT must rotate too — otherwise
                # a client whose current address is the dead primary
                # retries that same corpse until its fallback budget
                # dies, never reaching the promoted standby
                self._rotate_addr()
                raise
            s.settimeout(self.deadline_s)
            self._disp = s
        try:
            reply = sp.request(self._disp, self.addr, obj)
        except (OSError, sp.ProtocolError):
            self.close()
            self._rotate_addr()
            raise
        if reply.get("error") == "not_primary":
            # an honest standby (or demoted zombie): same retry shape as
            # a dead dispatcher, but the next attempt must try the
            # partition's other address
            self.close()
            self._rotate_addr()
        return reply

    def _live_suspects(self) -> List[str]:
        now = self._clock()
        for wid in [w for w, t in self._suspects.items()
                    if now - t >= self._suspect_ttl_s]:
            del self._suspects[wid]
        return list(self._suspects)

    def _shard_done(
        self, worker_id: str, shard_path: str, cached: bool = False
    ) -> None:
        try:
            self._dispatcher_rpc(
                {"op": "shard_done", "proto": PROTO_VERSION, "job": self._job,
                 "tenant": self._tenant, "consumer": self._consumer_id,
                 "path": shard_path, "worker_id": worker_id,
                 "cached": cached}
            )
        except (OSError, sp.ProtocolError):
            pass  # accounting only — the consumer's own position is truth

    def shard_chunks(self, epoch: int, pos: int, shard_idx: int, skip: int, stop):
        """Yield one shard's chunk tuples from the resume point, exactly
        once: ``consumed`` tracks the absolute record offset acked into
        the pipeline; every retry re-requests FROM that offset and any
        redelivered prefix is dropped/sliced, so a worker death, a
        dispatcher restart, or a reconnect can duplicate nothing and skip
        nothing."""
        ds = self._ds
        shard = ds.shards[shard_idx]
        consumed = skip
        exclude: List[str] = self._live_suspects()
        budget_start = self._clock()
        attempt = 0
        while not stop.is_set():
            wid = None
            # one lease = one span: route -> lease -> serve -> eof, each
            # attempt its own child of this process's context. The span id
            # rides the route and fetch messages, so the dispatcher's
            # service.route instant and the worker's service.serve span
            # link back by parent_span_id in the merged timeline.
            ctx = telemetry.current_context().child("service.lease")
            try:
                with telemetry.span(
                    "service.lease", shard=shard.path,
                    trace_id=ctx.trace_id, span_id=ctx.span_id,
                    parent_span_id=ctx.parent_span_id,
                ) as lease:
                    reply = self._dispatcher_rpc(
                        {
                            "op": "route",
                            "proto": PROTO_VERSION,
                            "job": self._job,
                            "tenant": self._tenant,
                            "consumer": self._consumer_id,
                            "path": shard.path,
                            "shard_index": self._global_index[shard.path],
                            "exclude": exclude,
                            "trace": ctx.to_json(),
                        }
                    )
                    if reply.get("error"):
                        raise ServiceUnavailable(str(reply["error"]))
                    worker_addr, wid = (
                        str(reply["worker"]), str(reply["worker_id"])
                    )
                    lease.set(worker=wid)
                    ttl = reply.get("lease_ttl_s")
                    if ttl is not None:
                        self._suspect_ttl_s = float(ttl)
                    for item in self._fetch_shard(
                        worker_addr, shard.path, consumed, epoch, pos, stop,
                        trace=ctx,
                    ):
                        yield item
                        consumed = item[3] + item[0].num_rows
                        budget_start = self._clock()  # progress resets the budget
                        exclude = self._live_suspects()
                        attempt = 0
                # a suspect that just completed a shard for us is healthy
                self._suspects.pop(wid, None)
                self._shard_done(wid, shard.path, cached=self._fetch_cached)
                self._degraded = False
                return
            except ServiceSpecError:
                raise
            except wire.TFRecordCorruptionError:
                raise  # same outcome a local strict read would have had
            except (OSError, sp.ProtocolError, ServiceUnavailable) as e:
                METRICS.count("service.reconnects")
                if wid is not None and wid not in exclude:
                    exclude.append(wid)
                if wid is not None:
                    self._suspects[wid] = self._clock()
                attempt += 1
                now = self._clock()
                exhausted = (
                    self.fallback_s is not None
                    and now - budget_start >= self.fallback_s
                )
                if exhausted or self._degraded:
                    self._fallback(shard.path, e)
                    yield from ds._decode_shard(epoch, pos, shard_idx, consumed)
                    return
                # the policy owns backoff shape (capped exponential, full
                # jitter — M consumers losing the same worker must not
                # retry the dispatcher in lockstep), and the sleep never
                # overruns the remaining fallback budget
                delay = ds.retry_policy.backoff(min(attempt, 16))
                if self.fallback_s is not None:
                    delay = min(
                        delay, max(0.0, self.fallback_s - (now - budget_start))
                    )
                self._sleep(delay)

    def _fetch_shard(self, worker_addr, shard_path, skip, epoch, pos, stop,
                     trace=None):
        self._fetch_cached = False
        sock = sp.connect(worker_addr, timeout=self.deadline_s)
        try:
            sock.settimeout(self.deadline_s)
            msg = {"op": "fetch", "proto": PROTO_VERSION, "spec": self._spec,
                   "shard": shard_path, "skip": skip}
            if trace is not None:
                msg["trace"] = trace.to_json()
            sp.send_msg(sock, msg)
            consumed = skip
            while not stop.is_set():
                # EOF here (allow_eof=False) raises ProtocolError: a worker
                # that closes mid-shard without an `eof` message is a death
                msg = sp.recv_msg(sock, worker_addr)
                op = msg.get("op")
                if op == "chunk":
                    chunk = sp.recv_chunk_body(
                        sock, msg, worker_addr, self._dtype_of, self._verify
                    )
                    start = int(msg["start"])
                    rows = chunk.num_rows
                    METRICS.count("service.chunks_recv")
                    if rows == 0 or start + rows <= consumed:
                        METRICS.count("service.redelivered_dropped")
                        continue
                    if start < consumed:
                        # partial overlap with already-acked rows: keep
                        # only the unseen suffix
                        METRICS.count("service.redelivered_dropped")
                        chunk = slice_batch(chunk, consumed - start, rows)
                        start = consumed
                    yield chunk, epoch, pos, start
                    consumed = start + chunk.num_rows
                elif op == "building":
                    continue  # keepalive: the worker is constructing its
                    # dataset — alive, just not streaming yet
                elif op == "eof":
                    # the worker's warm-cache disclosure rides the eof;
                    # shard_chunks forwards it on shard_done
                    self._fetch_cached = bool(msg.get("cached", False))
                    return
                elif op == "error":
                    kind = msg.get("kind")
                    err = str(msg.get("error", "worker error"))
                    if kind == "corruption":
                        raise wire.TFRecordCorruptionError(err)
                    if kind == "spec_mismatch":
                        raise ServiceSpecError(err)
                    raise ServiceUnavailable(f"{worker_addr}: {err}")
                else:
                    raise sp.ProtocolError(
                        f"unexpected message {op!r} from {worker_addr}"
                    )
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _fallback(self, shard_path: str, err: BaseException) -> None:
        self._degraded = True
        METRICS.count("service.fallbacks")
        telemetry.instant("service.fallback", shard=shard_path, error=str(err))
        log_salvage_event(
            path=shard_path, kind="service_fallback", error=str(err)
        )


def fetch_status(addr: str, timeout: float = 5.0) -> Dict[str, Any]:
    """One status round trip to a dispatcher (the ``serve-status`` doctor
    subcommand's transport)."""
    sock = sp.connect(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        return sp.request(
            sock, addr, {"op": "status", "proto": PROTO_VERSION}
        )
    finally:
        try:
            sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# CLI — `python -m tpu_tfrecord.service dispatcher|worker`
# ---------------------------------------------------------------------------


def _run_forever(stop_event: threading.Event) -> None:
    import signal

    def _term(_sig, _frm):
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:
        pass  # not the main thread (tests drive main() directly)
    try:
        # the event IS the wait seam: no bare time.sleep in a policy
        # module, and SIGTERM/stop wakes the loop immediately
        while not stop_event.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass


def _spool_args(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--spool-dir", default=None,
                    help="telemetry spool directory (tfrecord_doctor fleet)")
    ap.add_argument("--spool-interval", type=float, default=1.0)


def _maybe_spool(args, role: str):
    if args.spool_dir is None:
        return None
    from tpu_tfrecord import fleet

    fleet.acquire_spool(args.spool_dir, role=role, interval_s=args.spool_interval)
    return args.spool_dir


def dispatcher_main(argv: List[str]) -> int:
    from tpu_tfrecord.options import TFRecordOptions

    defaults = TFRecordOptions()
    ap = argparse.ArgumentParser(prog="tpu_tfrecord.service dispatcher")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--journal", default=None,
                    help="assignment journal path (fsynced snapshot+delta "
                    "lines; a restarted dispatcher replays it, a warm "
                    "standby tails it)")
    ap.add_argument("--lease-ttl-s", type=float,
                    default=defaults.service_lease_ttl_s)
    ap.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                    help="run as the warm standby of the primary at this "
                    "address: tail its journal (--journal must point at "
                    "the SAME file), detect death by ping loss, promote "
                    "with a bumped generation (fencing the zombie) and "
                    "take over the advertised address")
    ap.add_argument("--partition", type=int, default=0,
                    help="this dispatcher's index in the static partition "
                    "map (consumers hash tenants over it)")
    ap.add_argument("--generation", type=int, default=0,
                    help="starting fencing generation (normally 0; the "
                    "journal's replayed generation wins if higher)")
    ap.add_argument("--takeover-misses", type=int,
                    default=STANDBY_TAKEOVER_MISSES,
                    help="consecutive failed primary pings before a "
                    "standby promotes itself")
    ap.add_argument("--ping-interval", type=float, default=None,
                    help="standby ping/tail cadence in seconds (default "
                    "min(1, lease_ttl/4))")
    ap.add_argument("--no-addr-takeover", action="store_true",
                    help="do not try to bind the dead primary's advertised "
                    "address on promotion (clients rotate to the standby "
                    "address via the partition map instead)")
    ap.add_argument("--elastic", action="store_true",
                    help="run a FleetScaler (tpu_tfrecord.elastic): spawn "
                    "decode-worker subprocesses on producer_bound, drain "
                    "them on consumer_bound/idle")
    ap.add_argument("--scaler-spool", default=None, metavar="DIR",
                    help="telemetry spool dir the scaler reads the cluster "
                    "verdict from (default: --spool-dir)")
    ap.add_argument("--min-workers", type=int,
                    default=defaults.elastic_min_workers)
    ap.add_argument("--max-workers", type=int, default=None,
                    help="fleet ceiling (default: the options-vocabulary "
                    "default, currently 8)")
    ap.add_argument("--scale-interval", type=float,
                    default=defaults.elastic_interval_s or 1.0)
    ap.add_argument("--hysteresis", type=int, default=2)
    ap.add_argument("--cooldown", type=float, default=5.0)
    ap.add_argument("--scaler-roles", default=None, metavar="ROLE[,ROLE]",
                    help="scope the scaler's cluster verdict to spools "
                    "stamped with these telemetry roles (e.g. 'trainer'); "
                    "default: every spooling process with an occupancy "
                    "gauge votes")
    ap.add_argument("--worker-arg", action="append", default=[],
                    metavar="ARG", help="extra CLI arg for every spawned "
                    "worker (repeatable; e.g. --worker-arg=--cache "
                    "--worker-arg=auto)")
    ap.add_argument("--partition-map", default=None, metavar="SPEC",
                    help="full PartitionMap spec spawned workers register "
                    "with (so every partition can route to them); default: "
                    "just this dispatcher's address")
    _spool_args(ap)
    args = ap.parse_args(argv)
    role = "standby" if args.standby_of else "dispatcher"
    telemetry.adopt_from_env(role=role)
    d = ServiceDispatcher(
        port=args.port, host=args.host, journal=args.journal,
        lease_ttl_s=args.lease_ttl_s,
        standby_of=args.standby_of,
        partition_index=args.partition,
        generation=args.generation,
        takeover_misses=args.takeover_misses,
        ping_interval_s=args.ping_interval,
        takeover_addr=not args.no_addr_takeover,
    ).start()
    spool = _maybe_spool(args, role)
    scaler = None
    spawner = None
    if args.elastic:
        from tpu_tfrecord import elastic

        scaler_spool = args.scaler_spool or args.spool_dir
        if scaler_spool is None:
            ap.error("--elastic needs --scaler-spool (or --spool-dir): the "
                     "scaler reads the cluster verdict from a spool dir")
        if args.standby_of:
            ap.error("--elastic belongs on a PRIMARY: a standby must not "
                     "run a second scaler over the same fleet")
        spawner = elastic.subprocess_spawner(
            args.partition_map or d.addr, tuple(args.worker_arg)
        )
        max_workers = (
            args.max_workers
            if args.max_workers is not None
            else (defaults.elastic_max_workers or 8)
        )
        # under a partition map the one scaler federates: this partition
        # in-process, every other partition through a remote handle
        targets: Any = d
        if args.partition_map:
            pmap = PartitionMap.parse(args.partition_map)
            targets = [
                d if i == args.partition
                else elastic.DispatcherHandle(pmap.addrs(i))
                for i in range(pmap.k)
            ]
        scaler = elastic.FleetScaler(
            targets, spawner, spool_dir=scaler_spool,
            policy=elastic.ScalerPolicy(
                hysteresis=args.hysteresis, cooldown_s=args.cooldown,
                min_workers=args.min_workers, max_workers=max_workers,
            ),
            interval_s=args.scale_interval,
            roles=(
                [r.strip() for r in args.scaler_roles.split(",") if r.strip()]
                if args.scaler_roles else None
            ),
        ).start()
    print(json.dumps({"event": "ready", "role": role,
                      "addr": d.addr, "pid": os.getpid(),
                      "partition": d.partition_index,
                      "generation": d.generation,
                      "standby_of": args.standby_of,
                      "elastic": bool(scaler)}), flush=True)
    try:
        _run_forever(d._stop)
    finally:
        if scaler is not None:
            scaler.stop()
        d.stop()
        if spawner is not None:
            spawner.reap()
        if spool is not None:
            from tpu_tfrecord import fleet

            fleet.release_spool(spool)
    return 0


def worker_main(argv: List[str]) -> int:
    from tpu_tfrecord.options import TFRecordOptions

    ap = argparse.ArgumentParser(prog="tpu_tfrecord.service worker")
    ap.add_argument("--dispatcher", required=True,
                    help="dispatcher address, or a full PartitionMap spec "
                    "('h:p1|h:p2,h:p3' / '@map.json'): the worker "
                    "registers with and heartbeats EVERY partition")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--role", default="decode_worker")
    ap.add_argument("--cache", default="off", choices=("off", "auto"),
                    help="columnar epoch cache mode for this worker")
    ap.add_argument("--cache-dir", default=None)
    ap.add_argument("--cache-max-bytes", type=int, default=None)
    ap.add_argument("--drain-grace", type=float, default=1.0,
                    help="idle seconds before a draining worker says "
                    "goodbye and exits (default 1.0)")
    ap.add_argument("--fault-plan", default=None, metavar="PLAN_JSON",
                    help="install a seeded FaultPlan (tpu_tfrecord.faults) "
                    "for the life of this worker — deterministic chaos on "
                    "a real fleet")
    _spool_args(ap)
    args = ap.parse_args(argv)
    telemetry.adopt_from_env(role=args.role)
    if args.fault_plan is not None:
        from tpu_tfrecord.faults import FaultPlan, install_chaos

        with open(args.fault_plan) as fh:
            plan = FaultPlan.from_json(json.load(fh))
        # held for the process's whole life; process exit is the release
        install_chaos(plan).__enter__()
    opts = TFRecordOptions.from_map(
        cache=args.cache, cache_dir=args.cache_dir,
        cache_max_bytes=args.cache_max_bytes,
    )
    w = DecodeWorker(
        args.dispatcher, options=opts, port=args.port, host=args.host,
        worker_id=args.worker_id, role=args.role,
        drain_grace_s=args.drain_grace,
    ).start()
    spool = _maybe_spool(args, args.role)
    print(json.dumps({"event": "ready", "role": args.role, "addr": w.addr,
                      "worker_id": w.worker_id, "pid": os.getpid()}),
          flush=True)
    try:
        _run_forever(w._stop)
    finally:
        w.stop()
        if spool is not None:
            from tpu_tfrecord import fleet

            fleet.release_spool(spool)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "dispatcher":
        return dispatcher_main(argv[1:])
    if argv and argv[0] == "worker":
        return worker_main(argv[1:])
    sys.stderr.write(
        "usage: python -m tpu_tfrecord.service {dispatcher|worker} [options]\n"
    )
    return 2


if __name__ == "__main__":
    sys.exit(main())
