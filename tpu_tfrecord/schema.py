"""Schema model: the TPU-native equivalent of Spark's StructType.

Mirrors the data-type vocabulary the reference supports (README.md "Supported
data types" table; TFRecordSerializer.scala:68-152): scalar Integer/Long/
Float/Double/Decimal/String/Binary, Array of those, and Array-of-Array (which
maps to SequenceExample FeatureLists). NullType arises only from schema
inference over empty feature lists (TensorFlowInferSchema.scala:147-188).

Unlike the reference's stringly-typed three-site option parsing, the schema is
a small immutable object graph with JSON round-trip (for shipping across
processes — the analog of reference SerializableConfiguration,
DefaultSource.scala:145-182) and a numpy/JAX dtype mapping for the columnar
TPU ingest path.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np


class DataType:
    """Base class for all schema data types. Instances are immutable."""

    _name: str = "datatype"

    def simple_string(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return self.simple_string()

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    def to_json(self) -> Any:
        return self._name


class NullType(DataType):
    _name = "null"


class IntegerType(DataType):
    _name = "integer"


class LongType(DataType):
    _name = "long"


class FloatType(DataType):
    _name = "float"


class DoubleType(DataType):
    _name = "double"


class DecimalType(DataType):
    """Decimal(10, 0) — the reference always reads decimals at Spark's
    USER_DEFAULT precision/scale and downcasts to float32 on the wire
    (TFRecordSerializer.scala:88-90)."""

    _name = "decimal(10,0)"

    def __init__(self, precision: int = 10, scale: int = 0):
        self.precision = precision
        self.scale = scale

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, DecimalType)
            and self.precision == other.precision
            and self.scale == other.scale
        )

    def __hash__(self) -> int:
        return hash((DecimalType, self.precision, self.scale))

    def to_json(self) -> Any:
        return self.simple_string()


class StringType(DataType):
    _name = "string"


class BinaryType(DataType):
    _name = "binary"


class ArrayType(DataType):
    """Array of a single element type; ``contains_null`` as in Spark."""

    def __init__(self, element_type: DataType, contains_null: bool = True):
        self.element_type = element_type
        self.contains_null = contains_null

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"

    def __eq__(self, other: Any) -> bool:
        # Note: like the reference's type lattice, equality ignores
        # contains_null (ArrayType(LongType, _) patterns in
        # TensorFlowInferSchema.scala:194-207).
        return isinstance(other, ArrayType) and self.element_type == other.element_type

    def __hash__(self) -> int:
        return hash((ArrayType, self.element_type))

    def to_json(self) -> Any:
        return {
            "type": "array",
            "elementType": self.element_type.to_json(),
            "containsNull": self.contains_null,
        }


_ATOMIC_TYPES: Dict[str, DataType] = {
    "null": NullType(),
    "integer": IntegerType(),
    "long": LongType(),
    "float": FloatType(),
    "double": DoubleType(),
    "string": StringType(),
    "binary": BinaryType(),
}


def data_type_from_json(obj: Any) -> DataType:
    if isinstance(obj, str):
        if obj in _ATOMIC_TYPES:
            return _ATOMIC_TYPES[obj]
        if obj.startswith("decimal("):
            inner = obj[len("decimal(") : -1]
            precision, scale = (int(x) for x in inner.split(","))
            return DecimalType(precision, scale)
        raise ValueError(f"unknown data type {obj!r}")
    if isinstance(obj, dict) and obj.get("type") == "array":
        return ArrayType(
            data_type_from_json(obj["elementType"]), bool(obj.get("containsNull", True))
        )
    raise ValueError(f"unknown data type {obj!r}")


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "type": self.data_type.to_json(),
            "nullable": self.nullable,
        }

    @staticmethod
    def from_json(obj: Dict[str, Any]) -> "StructField":
        return StructField(
            obj["name"], data_type_from_json(obj["type"]), bool(obj.get("nullable", True))
        )


class StructType:
    """An ordered collection of StructFields — the row schema."""

    def __init__(self, fields: List[StructField]):
        self.fields: Tuple[StructField, ...] = tuple(fields)
        self._index = {f.name: i for i, f in enumerate(self.fields)}
        if len(self._index) != len(self.fields):
            raise ValueError("duplicate field names in schema")

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field_index(self, name: str) -> int:
        return self._index[name]

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self) -> Iterator[StructField]:
        return iter(self.fields)

    def __getitem__(self, key) -> StructField:
        if isinstance(key, str):
            return self.fields[self._index[key]]
        return self.fields[key]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, StructType) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.fields)

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{f.name}:{f.data_type.simple_string()}{'' if f.nullable else ' not null'}"
            for f in self.fields
        )
        return f"StructType({inner})"

    def add(self, name: str, data_type: DataType, nullable: bool = True) -> "StructType":
        return StructType(list(self.fields) + [StructField(name, data_type, nullable)])

    def select(self, names: List[str]) -> "StructType":
        missing = [n for n in names if n not in self._index]
        if missing:
            raise ValueError(
                f"unknown column(s) {missing}; available: {self.names}"
            )
        return StructType([self[n] for n in names])

    def drop(self, names) -> "StructType":
        drop_set = set(names)
        return StructType([f for f in self.fields if f.name not in drop_set])

    def to_json(self) -> Dict[str, Any]:
        return {"type": "struct", "fields": [f.to_json() for f in self.fields]}

    def json(self) -> str:
        return json.dumps(self.to_json())

    @staticmethod
    def from_json(obj) -> "StructType":
        if isinstance(obj, str):
            obj = json.loads(obj)
        return StructType([StructField.from_json(f) for f in obj["fields"]])


# ---------------------------------------------------------------------------
# numpy / JAX dtype mapping (the columnar & device view of the schema)
# ---------------------------------------------------------------------------

_NUMPY_DTYPES: Dict[type, np.dtype] = {
    IntegerType: np.dtype(np.int32),
    LongType: np.dtype(np.int64),
    FloatType: np.dtype(np.float32),
    DoubleType: np.dtype(np.float64),
    DecimalType: np.dtype(np.float64),
}


def numpy_dtype(data_type: DataType) -> Optional[np.dtype]:
    """The numpy dtype used for columnar buffers; None for bytes-like types."""
    if isinstance(data_type, (StringType, BinaryType, NullType)):
        return None
    if isinstance(data_type, ArrayType):
        return numpy_dtype(data_type.element_type)
    dt = _NUMPY_DTYPES.get(type(data_type))
    if dt is None:
        raise ValueError(f"no numpy dtype for {data_type}")
    return dt


def is_numeric(data_type: DataType) -> bool:
    return type(data_type) in _NUMPY_DTYPES


# Singletons for ergonomic schema literals (mirroring Spark's object types).
NULL = NullType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
