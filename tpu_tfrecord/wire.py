"""TFRecord wire format: record framing with masked CRC32C checksums.

Re-implements natively what the reference delegates to the shaded JVM library
``org.tensorflow:tensorflow-hadoop`` (``TFRecordFileInputFormat`` /
``TFRecordWriter``; see reference pom.xml:372-376 and SURVEY.md §2.8).

Frame layout per record::

    uint64  length        (little-endian)
    uint32  masked_crc32c(length bytes)
    bytes   data[length]
    uint32  masked_crc32c(data)

This module is the pure-Python reference implementation; `tpu_tfrecord._native`
provides a C++ fast path (SSE4.2 / slicing-by-8 CRC32C, zero-copy frame scan)
that this module transparently uses when the extension is built.
"""

from __future__ import annotations

import gzip
import io
import os
import struct
import zlib
from typing import BinaryIO, Iterator, List, Optional, Tuple

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli, polynomial 0x1EDC6F41, reflected 0x82F63B78)
# ---------------------------------------------------------------------------

_POLY = 0x82F63B78


def _make_tables(n: int = 8) -> List[List[int]]:
    """Slicing-by-N tables: table[0] is the plain byte-at-a-time table."""
    t0 = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (_POLY if crc & 1 else 0)
        t0.append(crc)
    tables = [t0]
    for k in range(1, n):
        prev = tables[k - 1]
        tk = []
        for i in range(256):
            c = prev[i]
            tk.append((c >> 8) ^ t0[c & 0xFF])
        tables.append(tk)
    return tables


_TABLES = _make_tables(8)
_T0, _T1, _T2, _T3, _T4, _T5, _T6, _T7 = _TABLES


def crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-Python CRC32C (slicing-by-8). Correct but slow; C++ is the fast path."""
    crc = crc ^ 0xFFFFFFFF
    n = len(data)
    i = 0
    # Process 8 bytes at a time via slicing-by-8.
    end8 = n - (n % 8)
    while i < end8:
        b0 = data[i] ^ (crc & 0xFF)
        b1 = data[i + 1] ^ ((crc >> 8) & 0xFF)
        b2 = data[i + 2] ^ ((crc >> 16) & 0xFF)
        b3 = data[i + 3] ^ ((crc >> 24) & 0xFF)
        crc = (
            _T7[b0]
            ^ _T6[b1]
            ^ _T5[b2]
            ^ _T4[b3]
            ^ _T3[data[i + 4]]
            ^ _T2[data[i + 5]]
            ^ _T1[data[i + 6]]
            ^ _T0[data[i + 7]]
        )
        i += 8
    while i < n:
        crc = (crc >> 8) ^ _T0[(crc ^ data[i]) & 0xFF]
        i += 1
    return crc ^ 0xFFFFFFFF


def _crc32c_bootstrap(data: bytes) -> int:
    """First call probes for the C++ library and rebinds ``crc32c`` to the
    fastest available implementation (hardware CRC32 via SSE4.2)."""
    global crc32c
    impl = crc32c_py
    try:
        from tpu_tfrecord import _native

        if _native.available():
            impl = _native.crc32c
    except Exception:  # graftlint: swallow(crc32c bootstrap: fall through to the next implementation)
        pass
    crc32c = impl
    return impl(data)


crc32c = _crc32c_bootstrap

_MASK_DELTA = 0xA282EAD8


def masked_crc32c(data: bytes) -> int:
    """The TFRecord 'masked' CRC: rotate right by 15 and add a constant."""
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) & 0xFFFFFFFF) + _MASK_DELTA) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Compression codecs
# ---------------------------------------------------------------------------
#
# The reference maps a `codec` option onto Hadoop compression-codec class names
# (DefaultSource.scala:95-102) and infers the read codec from the file
# extension (Hadoop behavior). We support the same codecs Hadoop's
# GzipCodec/DefaultCodec provide, keyed by short name, Hadoop class name, or
# file extension.

_CODEC_ALIASES = {
    "": None,
    "none": None,
    "uncompressed": None,
    "gzip": "gzip",
    "gz": "gzip",
    "org.apache.hadoop.io.compress.gzipcodec": "gzip",
    "deflate": "deflate",
    "zlib": "deflate",
    "org.apache.hadoop.io.compress.defaultcodec": "deflate",
    "org.apache.hadoop.io.compress.deflatecodec": "deflate",
    "zstd": "zstd",
    "zstandard": "zstd",
    "org.apache.hadoop.io.compress.zstandardcodec": "zstd",
    # Full Hadoop passthrough breadth (ref DefaultSource.scala:95-102
    # forwards ANY codec class name into the Hadoop conf): snappy and lz4
    # via the dependency-free implementations in hadoop_codecs.py, bzip2
    # via stdlib bz2.
    "snappy": "snappy",
    "org.apache.hadoop.io.compress.snappycodec": "snappy",
    "lz4": "lz4",
    "org.apache.hadoop.io.compress.lz4codec": "lz4",
    "bzip2": "bzip2",
    "bz2": "bzip2",
    "org.apache.hadoop.io.compress.bzip2codec": "bzip2",
}

_CODEC_EXTENSIONS = {
    "gzip": ".gz",
    "deflate": ".deflate",
    "zstd": ".zst",
    "snappy": ".snappy",
    "lz4": ".lz4",
    "bzip2": ".bz2",
}


def _zstandard():
    """The optional zstandard module, or None (zstd support is gated)."""
    try:
        import zstandard

        return zstandard
    except ImportError:
        return None


def normalize_codec(codec: Optional[str]) -> Optional[str]:
    """Resolve a user-supplied codec name to a canonical codec or raise."""
    if codec is None:
        return None
    key = codec.strip().lower()
    if key in _CODEC_ALIASES:
        resolved = _CODEC_ALIASES[key]
        if resolved == "zstd" and _zstandard() is None:
            raise ValueError(
                f"codec {codec!r} requires the optional 'zstandard' package"
            )
        return resolved
    raise ValueError(
        f"Unsupported codec {codec!r}: supported codecs are 'gzip', "
        "'deflate', 'zstd', 'snappy', 'lz4', and 'bzip2' (or their Hadoop "
        "class names)"
    )


def codec_extension(codec: Optional[str]) -> str:
    """File-name suffix appended after '.tfrecord' (ref DefaultSource.scala:112-114)."""
    codec = normalize_codec(codec)
    return _CODEC_EXTENSIONS.get(codec, "") if codec else ""


def codec_supports_chunks(codec: Optional[str]) -> bool:
    """True if ``compress_chunk`` can emit independently-decodable pieces
    for this codec (concatenating chunks yields a valid stream). True for
    every supported codec today; the probe exists so a future stream-only
    codec degrades the parallel writer to committer-side compression instead
    of producing corrupt files."""
    return normalize_codec(codec) in (
        None, "gzip", "deflate", "zstd", "snappy", "lz4", "bzip2"
    )


def compress_chunk(codec: Optional[str], data) -> bytes:
    """Compress one slab into a self-contained piece of ``codec``'s stream
    format, such that the byte-concatenation of chunks is a valid file of
    that codec. This is what lets the parallel writer compress slabs on
    worker threads instead of serializing behind one stream object:

    - gzip: one gzip member (multi-member files are standard; GzipFile
      reads them). ``mtime=0`` keeps output a pure function of the input.
    - deflate: one zlib stream (the read side handles concatenated
      streams, mirroring how it already handles concatenated zstd frames).
    - zstd: one zstd frame.
    - bzip2: one bz2 stream (stdlib reads multi-stream files).
    - snappy/lz4: a whole number of Hadoop BlockCompressorStream blocks
      (blocks are independent by construction).

    Deterministic: equal input bytes yield equal output bytes, so shard
    content is a function of the data and options, never of worker timing.
    """
    codec = normalize_codec(codec)
    # zlib/gzip/bz2/zstd accept any buffer (numpy arrays included) — no
    # bytes() copy of a multi-MB slab on the worker's hot path
    if codec is None:
        return bytes(data)
    if codec == "gzip":
        return gzip.compress(data, compresslevel=9, mtime=0)
    if codec == "deflate":
        return zlib.compress(data)
    if codec == "zstd":
        zstd = _zstandard()
        if zstd is None:  # normalize_codec guards; defensive
            raise ValueError("zstd codec requires the optional 'zstandard' package")
        return zstd.ZstdCompressor().compress(data)
    if codec == "bzip2":
        import bz2

        return bz2.compress(data)
    if codec in ("snappy", "lz4"):
        from tpu_tfrecord.hadoop_codecs import compress_hadoop_blocks

        return compress_hadoop_blocks(codec, data)
    raise ValueError(f"codec {codec!r} has no chunked compressor")


def codec_from_path(path: str) -> Optional[str]:
    """Infer the codec from a file extension, like Hadoop's codec factory."""
    lower = path.lower()
    if lower.endswith(".gz") or lower.endswith(".gzip"):
        return "gzip"
    if lower.endswith(".deflate") or lower.endswith(".zlib"):
        return "deflate"
    if lower.endswith(".zst") or lower.endswith(".zstd"):
        return "zstd"
    if lower.endswith(".snappy"):
        return "snappy"
    if lower.endswith(".lz4"):
        return "lz4"
    if lower.endswith(".bz2") or lower.endswith(".bzip2"):
        return "bzip2"
    return None


def open_compressed(
    path: str, mode: str, codec: Optional[str], retry_policy=None
) -> BinaryIO:
    """Open a (possibly compressed) record stream. Paths with a URL scheme
    route through the pluggable filesystem layer (tpu_tfrecord.fs — the
    reference's Hadoop FileSystem + CodecStreams equivalent,
    TFRecordOutputWriter.scala:19); the codec wraps the raw stream either
    way. Plain paths open through ``fs.local_open`` — the raw-open seam
    the chaos injector (tpu_tfrecord.faults) patches. ``retry_policy``
    reaches the remote block prefetcher: transient fetch faults self-heal
    from the exact byte offset instead of failing the whole stream."""
    codec = normalize_codec(codec)
    from tpu_tfrecord import fs as _fs

    if _fs.has_scheme(path):
        fsys = _fs.filesystem_for(path)
        if mode in ("rb", "r"):
            # block-pipelined readahead for big remote objects (the Hadoop
            # FS connector streaming the reference gets for free — L6)
            raw = _fs.open_for_read(fsys, path, retry_policy=retry_policy)
        else:
            raw = fsys.open(path, mode)
    else:
        raw = _fs.local_open(path, mode)
    return wrap_codec(path, mode, codec, raw)


def wrap_codec(
    path: str, mode: str, codec: Optional[str], raw: BinaryIO
) -> BinaryIO:
    """Wrap an already-open raw byte stream in the codec for ``path`` —
    the codec half of ``open_compressed``, shared with the stall guard
    (which inserts its deadline/hedge stream UNDER the codec)."""
    if codec == "gzip":
        return _ClosingGzip(raw, mode)  # type: ignore[return-value]
    if codec == "deflate":
        return _DeflateFile(path, mode, fileobj=raw)
    if codec == "zstd":
        return _ZstdFile(path, mode, fileobj=raw)
    if codec in ("snappy", "lz4"):
        from tpu_tfrecord.hadoop_codecs import HadoopBlockFile

        return HadoopBlockFile(path, mode, codec, fileobj=raw)
    if codec == "bzip2":
        from tpu_tfrecord.hadoop_codecs import Bz2File

        return Bz2File(path, mode, fileobj=raw)
    return raw


class _ZstdFile(io.RawIOBase):
    """zstd-framed stream (Hadoop ZStandardCodec / .zst files), backed by
    the optional ``zstandard`` package. Reads stream incrementally through
    ``decompressobj`` and CHECK frame completion at EOF via its ``eof``
    flag — ``stream_reader`` returns a clean short read on a truncated
    frame, which would silently drop trailing records (the same trap
    _DeflateFile guards with zlib's eof). Concatenated frames are handled.
    Writes flush the frame on close and close the underlying stream
    (remote writers upload on close)."""

    _READ_CHUNK = 1 << 20  # compressed bytes per underlying read

    def __init__(self, path: str, mode: str, fileobj: Optional[BinaryIO] = None):
        super().__init__()
        zstd = _zstandard()
        if zstd is None:  # normalize_codec guards, but be safe
            raise ValueError("zstd codec requires the optional 'zstandard' package")
        self._zstd = zstd
        self._path = path
        if "w" in mode:
            self._raw = fileobj if fileobj is not None else open(path, "wb")
            self._writer = zstd.ZstdCompressor().stream_writer(
                self._raw, closefd=False
            )
            self._dobj = None
        else:
            self._raw = fileobj if fileobj is not None else open(path, "rb")
            self._writer = None
            self._dobj = zstd.ZstdDecompressor().decompressobj()
            self._pending = bytearray()
            self._eof = False

    def readable(self) -> bool:
        return self._dobj is not None

    def writable(self) -> bool:
        return self._writer is not None

    def _fill(self) -> None:
        raw = self._raw.read(self._READ_CHUNK)
        if not raw:
            if not self._dobj.eof:
                raise TFRecordCorruptionError(
                    f"truncated zstd stream in {self._path}"
                )
            self._eof = True
            return
        try:
            while raw:
                if self._dobj.eof:
                    # The previous frame ended exactly at a read-chunk
                    # boundary (eof=True, empty unused_data): a finished
                    # decompressobj cannot be fed again, so start a fresh
                    # one for the next concatenated frame.
                    self._dobj = self._zstd.ZstdDecompressor().decompressobj()
                self._pending += self._dobj.decompress(raw)
                if self._dobj.eof:
                    # concatenated frames: restart on the leftover input
                    raw = self._dobj.unused_data
                    if raw:
                        self._dobj = self._zstd.ZstdDecompressor().decompressobj()
                        continue
                break
        except self._zstd.ZstdError as e:
            raise TFRecordCorruptionError(
                f"corrupt zstd stream in {self._path}: {e}"
            ) from e

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            while not self._eof:
                self._fill()
            out = bytes(self._pending)
            self._pending = bytearray()
            return out
        while len(self._pending) < size and not self._eof:
            self._fill()
        out = bytes(self._pending[:size])
        del self._pending[:size]
        return out

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def write(self, data) -> int:
        return self._writer.write(data)

    def close(self) -> None:
        if not self.closed:
            try:
                if self._writer is not None:
                    self._writer.close()  # flushes the frame
            finally:
                if not self._raw.closed:
                    self._raw.close()
                super().close()


class _ClosingGzip(gzip.GzipFile):
    """GzipFile that also closes the underlying stream — GzipFile(fileobj=)
    deliberately leaves it open, but remote-FS writers only upload on
    close."""

    def __init__(self, raw: BinaryIO, mode: str):
        super().__init__(fileobj=raw, mode=mode)
        self._raw = raw

    def close(self) -> None:
        try:
            super().close()
        finally:
            if not self._raw.closed:
                self._raw.close()


class _DeflateFile(io.RawIOBase):
    """zlib-wrapped file (Hadoop DefaultCodec writes raw zlib streams).

    Reads stream through ``zlib.decompressobj`` with bounded output per
    step (mirroring how gzip.open streams), so a large ``.deflate`` shard
    honors the slab-streaming bounded-memory contract (io/dataset.py
    ``_shard_slabs``) instead of materializing whole on open.

    CONCATENATED zlib streams are decoded back to back (the same contract
    _ZstdFile provides for concatenated frames): the parallel writer's
    chunked compressor emits one independent stream per slab, and a reader
    that stopped at the first stream end would silently drop every record
    after slab 0.
    """

    _READ_CHUNK = 1 << 20  # compressed bytes per underlying read

    def __init__(self, path: str, mode: str, fileobj: Optional[BinaryIO] = None):
        super().__init__()
        self._mode = mode
        self._path = path
        if "w" in mode:
            self._fh = fileobj if fileobj is not None else open(path, "wb")
            self._compress = zlib.compressobj()
            self._decompress = None
        else:
            self._fh = fileobj if fileobj is not None else open(path, "rb")
            self._compress = None
            self._decompress = zlib.decompressobj()
            self._pending = bytearray()
            self._eof = False

    def readable(self) -> bool:
        return self._decompress is not None

    def writable(self) -> bool:
        return self._compress is not None

    def _fill(self, want: int) -> None:
        """Decompress until ``want`` more bytes are pending or EOF; output
        per step is capped at ``want`` so memory stays ~pending+want. All
        zlib decode errors surface as TFRecordCorruptionError — the module's
        corruption contract — including bad bytes where a concatenated
        stream's header was expected."""
        try:
            self._fill_inner(want)
        except zlib.error as e:
            raise TFRecordCorruptionError(
                f"corrupt deflate stream in {self._path}: {e}"
            ) from e

    def _fill_inner(self, want: int) -> None:
        d = self._decompress
        if d.eof:
            # Stream finished: concatenated streams (chunked writer output)
            # restart a fresh decompressobj on the leftover input, or on the
            # next read when the stream ended exactly at a chunk boundary.
            raw = d.unused_data
            if not raw:
                raw = self._fh.read(self._READ_CHUNK)
                if not raw:
                    self._eof = True
                    return
            self._decompress = d = zlib.decompressobj()
            self._pending += d.decompress(raw, want)
            return
        if d.unconsumed_tail:
            self._pending += d.decompress(d.unconsumed_tail, want)
            return
        raw = self._fh.read(self._READ_CHUNK)
        if not raw:
            tail = d.flush()
            if not d.eof:
                # file ended mid-stream (partial copy/upload): whole-file
                # zlib.decompress raised here; streaming must too, or
                # trailing rows vanish silently
                raise TFRecordCorruptionError(
                    f"truncated deflate stream in {self._path}"
                )
            self._pending += tail
            self._eof = True
            return
        self._pending += d.decompress(raw, want)

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            while not self._eof:
                self._fill(self._READ_CHUNK)
            out = bytes(self._pending)
            self._pending = bytearray()
            return out
        while len(self._pending) < size and not self._eof:
            self._fill(size - len(self._pending))
        out = bytes(self._pending[:size])
        del self._pending[:size]
        return out

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)

    def write(self, data) -> int:
        self._fh.write(self._compress.compress(bytes(data)))
        return len(data)

    def close(self) -> None:
        if not self.closed:
            if self._compress is not None:
                self._fh.write(self._compress.flush())
            if self._fh is not None:
                self._fh.close()
            super().close()


# ---------------------------------------------------------------------------
# Record-level framing
# ---------------------------------------------------------------------------

_LEN_STRUCT = struct.Struct("<Q")
_CRC_STRUCT = struct.Struct("<I")
HEADER_BYTES = 12  # 8-byte length + 4-byte length crc
FOOTER_BYTES = 4  # 4-byte data crc


class TFRecordCorruptionError(IOError):
    """Raised when framing or CRC validation fails."""


def encode_record(data: bytes) -> bytes:
    """Frame one record (length + masked length CRC + data + masked data CRC)."""
    header = _LEN_STRUCT.pack(len(data))
    return b"".join(
        (
            header,
            _CRC_STRUCT.pack(masked_crc32c(header)),
            data,
            _CRC_STRUCT.pack(masked_crc32c(data)),
        )
    )


class RecordWriter:
    """Streaming TFRecord writer over a binary file object.

    TPU-native counterpart of the shaded ``TFRecordWriter`` used at reference
    TFRecordOutputWriter.scala:21,37.
    """

    def __init__(self, fh: BinaryIO):
        self._fh = fh
        self.records_written = 0
        self.bytes_written = 0

    def write(self, data: bytes) -> None:
        framed = encode_record(data)
        self._fh.write(framed)
        self.records_written += 1
        self.bytes_written += len(framed)

    def flush(self) -> None:
        self._fh.flush()


def read_exact(fh, n: int) -> bytes:
    """Read exactly n bytes, looping over short reads: remote/object-store
    streams may legally return fewer bytes per call than asked — only a
    0-byte read is EOF, and only EOF mid-record is truncation. Shared by
    every framing reader (RecordReader here, HadoopBlockFile)."""
    data = fh.read(n)
    if len(data) in (0, n):
        return data
    parts = [data]
    got = len(data)
    while got < n:
        more = fh.read(n - got)
        if not more:
            break
        parts.append(more)
        got += len(more)
    return b"".join(parts)


class RecordReader:
    """Streaming TFRecord reader over a binary file object.

    TPU-native counterpart of the shaded ``TFRecordFileInputFormat`` record
    reader used at reference TFRecordFileReader.scala:32-51.
    """

    def __init__(self, fh: BinaryIO, verify_crc: bool = True):
        self._fh = fh
        self._verify = verify_crc
        self.records_read = 0
        self.bytes_read = 0

    def read(self) -> Optional[bytes]:
        """Read one record; returns None at a clean EOF."""
        header = read_exact(self._fh, HEADER_BYTES)
        if len(header) == 0:
            return None
        if len(header) < HEADER_BYTES:
            raise TFRecordCorruptionError("truncated TFRecord header")
        (length,) = _LEN_STRUCT.unpack_from(header, 0)
        (length_crc,) = _CRC_STRUCT.unpack_from(header, 8)
        if self._verify and masked_crc32c(header[:8]) != length_crc:
            raise TFRecordCorruptionError("corrupt TFRecord: bad length CRC")
        body = read_exact(self._fh, length + FOOTER_BYTES)
        if len(body) < length + FOOTER_BYTES:
            raise TFRecordCorruptionError("truncated TFRecord body")
        data = body[:length]
        if self._verify:
            (data_crc,) = _CRC_STRUCT.unpack_from(body, length)
            if masked_crc32c(data) != data_crc:
                raise TFRecordCorruptionError("corrupt TFRecord: bad data CRC")
        self.records_read += 1
        self.bytes_read += HEADER_BYTES + length + FOOTER_BYTES
        return data

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec


def scan_buffer_partial(
    buf: bytes, verify_crc: bool = True, max_records: Optional[int] = None
) -> Tuple[List[Tuple[int, int]], int]:
    """Scan complete frames in a buffer; a record extending past the end is
    a TAIL (to carry into the next slab), not corruption. Returns
    ([(offset, length), ...], consumed_bytes). ``max_records`` stops the
    scan cleanly after that many records — bytes past them are neither
    framed nor CRC-checked (same contract as the native scan_partial)."""
    spans: List[Tuple[int, int]] = []
    pos = 0
    n = len(buf)
    consumed = 0
    while pos < n:
        if max_records is not None and len(spans) >= max_records:
            break
        if pos + HEADER_BYTES > n:
            break
        (length,) = _LEN_STRUCT.unpack_from(buf, pos)
        if verify_crc:
            (length_crc,) = _CRC_STRUCT.unpack_from(buf, pos + 8)
            if masked_crc32c(buf[pos : pos + 8]) != length_crc:
                raise TFRecordCorruptionError("corrupt TFRecord: bad length CRC")
        start = pos + HEADER_BYTES
        if n - start < FOOTER_BYTES or length > n - start - FOOTER_BYTES:
            break
        if verify_crc:
            (data_crc,) = _CRC_STRUCT.unpack_from(buf, start + length)
            if masked_crc32c(buf[start : start + length]) != data_crc:
                raise TFRecordCorruptionError("corrupt TFRecord: bad data CRC")
        spans.append((start, length))
        pos = start + length + FOOTER_BYTES
        consumed = pos
    return spans, consumed


def resync(
    buf,
    pos: int,
    max_record_bytes: Optional[int] = None,
    end: Optional[int] = None,
) -> int:
    """Scan forward from ``pos`` for the next plausible record header, so a
    shard with one bad frame loses one record instead of everything after
    it. A candidate offset qualifies when its 8-byte little-endian length is
    sane (<= ``max_record_bytes`` when given) AND the 4-byte masked
    length-CRC that follows matches — a ~2^-32 false-positive filter. When
    the whole candidate frame lies inside ``buf[:end]`` the data CRC must
    confirm too (~2^-64 combined); a candidate whose frame extends past the
    buffer is accepted on the header alone and carried by the caller as a
    tail. Returns the candidate offset, or -1 if none exists — the last
    HEADER_BYTES-1 bytes can never qualify and should be re-scanned with
    more data appended.
    """
    n = len(buf) if end is None else end
    i = max(0, pos)
    while i + HEADER_BYTES <= n:
        (length,) = _LEN_STRUCT.unpack_from(buf, i)
        if max_record_bytes is None or length <= max_record_bytes:
            (length_crc,) = _CRC_STRUCT.unpack_from(buf, i + 8)
            if masked_crc32c(bytes(buf[i : i + 8])) == length_crc:
                start = i + HEADER_BYTES
                if start + length + FOOTER_BYTES <= n:
                    (data_crc,) = _CRC_STRUCT.unpack_from(buf, start + length)
                    if masked_crc32c(bytes(buf[start : start + length])) == data_crc:
                        return i
                else:
                    return i
        i += 1
    return -1


def scan_buffer(
    buf: bytes, verify_crc: bool = True
) -> Iterator[Tuple[int, int]]:
    """Yield (offset, length) of each record payload in an in-memory buffer;
    a buffer that does not end on a frame boundary is corrupt.

    Strict scan = partial scan + completeness check, so the framing/CRC
    contract lives in exactly one place (same structure in the C++ twin).
    """
    spans, consumed = scan_buffer_partial(buf, verify_crc)
    if consumed != len(buf):
        raise TFRecordCorruptionError("truncated TFRecord")
    yield from spans


# ---------------------------------------------------------------------------
# File-level helpers
# ---------------------------------------------------------------------------


def write_records(
    path: str, records, codec: Optional[str] = None
) -> int:
    """Write an iterable of serialized records to one TFRecord file."""
    count = 0
    with open_compressed(path, "wb", codec) as fh:
        writer = RecordWriter(fh)
        for rec in records:
            writer.write(rec)
            count += 1
    return count


def read_records(
    path: str, codec: Optional[str] = "auto", verify_crc: bool = True
) -> Iterator[bytes]:
    """Iterate serialized records from one TFRecord file.

    ``codec='auto'`` infers compression from the extension the way the
    reference's read path relies on Hadoop to (README.md: codec "can be
    inferred automatically" on read).
    """
    if codec == "auto":
        codec = codec_from_path(path)
    with open_compressed(path, "rb", codec) as fh:
        yield from RecordReader(fh, verify_crc=verify_crc)


def file_is_empty(path: str) -> bool:
    """True if the file has zero length (ref DefaultSource.scala:82-87)."""
    return os.path.getsize(path) == 0
